"""Host-side object plane.

TPU-native replacement for the reference's pickled-MPI object transport
(reference: chainermn/communicators/mpi_communicator_base.py — object ops
``bcast_obj``/``gather_obj``/``send_obj``/``recv_obj`` built on mpi4py's
pickle-based messaging; module path per SURVEY.md §2.1, reference mount empty).

Here the object world is the set of JAX *processes* (hosts), matching the
reference's node-level object plane. Transport:

* single process — trivial identity paths (the common single-controller case);
* multi-process — pickled payloads ride ``jax.experimental.multihost_utils``
  (uint8 tensors over the DCN collective fabric) for collectives, and the
  ``jax.distributed`` coordinator's KV store for point-to-point, chunked to
  bound coordinator message sizes (the analog of the reference's 256 MB
  ``max_buf_len`` chunking in scatter_dataset).
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, List, Optional

import numpy as np

import jax

from chainermn_tpu.resilience import chaos as _chaos
from chainermn_tpu.resilience.policy import policy as _rpc_policy

# KV-store chunk bound: coordinator values are strings; keep chunks modest.
_KV_CHUNK = 4 * 1024 * 1024

# Every deadline below derives from ONE policy (resilience/policy.py):
# the total per-operation budget (CHAINERMN_TPU_RPC_TIMEOUT_MS, default
# 600 s — the historical scattered constant), the fail-fast probe slice
# (CHAINERMN_TPU_RPC_PROBE_MS, default 10 s) that bounds how long a dead
# coordinator goes unnoticed, and the jittered-exponential retry ladder.

# seeded by every ObjectPlane at construction; read by the liveness probes
_ALIVE_KEY = "og/liveness/seed"

# set by post_abort (the global except hook's MPI_Abort analog); checked by
# every liveness probe so peers of a crashed rank raise within one probe
# interval instead of waiting out their collective budgets. The flag is a
# CHILD key under the directory on purpose: key_value_dir_get (present on
# every jaxlib generation) only lists children, so probes on clients
# without key_value_try_get can still read it without blocking.
_ABORT_KEY = "og/abort"
_ABORT_FLAG = _ABORT_KEY + "/flag"


class JobAbortedError(RuntimeError):
    """Another process declared the job dead (global except hook)."""


def post_abort(reason: str) -> None:
    """Mark the job aborted for every peer (best-effort, bounded).

    The crashing process may be the coordinator host, where a graceful
    ``jax.distributed.shutdown()`` can block forever waiting for peers that
    are themselves stuck in collectives — so this posts a poison key with a
    short thread-guarded budget and swallows every failure (if the
    coordinator is already gone, peers fail fast via the liveness probe
    instead)."""
    client = _client()
    if client is None:
        return
    try:
        _guard_rpc(lambda: client.key_value_set(
            _ABORT_FLAG, reason[:512]), budget_ms=5_000)
    except Exception:
        pass


def _read_abort(client) -> Optional[str]:
    """The posted abort reason, or None — without ever blocking.

    Newer clients expose ``key_value_try_get``; older ones only have
    ``key_value_dir_get``, which returns instantly and lists the abort
    flag because it is a child of the abort directory. A blocking get is
    NOT an option here: this runs on every probe slice of every guarded
    wait, and a missing key would stall it for the full get deadline."""
    if hasattr(client, "key_value_try_get"):
        try:
            return client.key_value_try_get(_ABORT_FLAG)
        except Exception:  # NotFound: nobody aborted
            return None
    try:
        for _key, reason in client.key_value_dir_get(_ABORT_KEY):
            return reason
    except Exception:
        pass
    return None


def _client():
    """The jax.distributed coordinator client, or None."""
    try:
        from jax._src import distributed  # noqa: internal, only path to KV store

        return distributed.global_state.client
    except Exception:
        return None


class ObjectPlane:
    """Process-plane object collectives.

    Sequence counters are CLASS-level: every instance in a process shares
    them, because all instances share the coordinator's one key namespace —
    per-instance counters would collide (e.g. a user-made plane and the
    communicator's internal one both starting at seq 0). SPMD discipline
    (every process runs the same program, hence the same call order) keeps
    the counters aligned across processes, exactly like MPI collectives.
    The counters are scoped to the coordinator client: re-initializing
    jax.distributed gives a fresh KV namespace, so planes created after that
    must restart at seq 0 or they desync from peers that start fresh.
    """

    _seq: dict = {}
    # strong ref to the coordinator client the counters belong to; `is`
    # comparison is unambiguous (an id() would be reusable after free)
    _seq_client: Any = None

    def __init__(self) -> None:
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        client = _client()
        if client is not ObjectPlane._seq_client:
            ObjectPlane._seq_client = client
            ObjectPlane._seq.clear()
        self._p2p_seq = ObjectPlane._seq
        if client is not None and self.process_count > 1:
            # seed the liveness key the fail-fast probes read: a get on it
            # returns instantly while the coordinator lives, so any error
            # (incl. client-side deadline) means the coordinator is gone
            try:
                client.key_value_set(_ALIVE_KEY, "1", allow_overwrite=True)
            except TypeError:  # older client without allow_overwrite
                try:
                    client.key_value_set(_ALIVE_KEY, "1")
                except Exception:
                    pass
            except Exception:
                pass

    # -- collectives ----------------------------------------------------

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        if self.process_count == 1:
            return obj
        from jax.experimental import multihost_utils

        payload = pickle.dumps(obj) if self.process_index == root else b""
        # Ship (length, data) as uint8; broadcast_one_to_all roots at process 0,
        # so first hop payloads to process 0 over the KV store if root differs.
        # The relay key carries a sequence number like every other KV channel:
        # the coordinator rejects duplicate keys, and a reused key would hand
        # process 0 the previous bcast's stale payload.
        if root != 0:
            seq = self._next_seq(f"bcast_root/{root}")
            if self.process_index == root:
                self._kv_put(f"bcast_root/{root}/{seq}", payload)
            if self.process_index == 0:
                payload = self._kv_get(f"bcast_root/{root}/{seq}")
        n = np.array([len(payload)], dtype=np.int64)
        n = multihost_utils.broadcast_one_to_all(n)
        buf = np.zeros(int(n[0]), dtype=np.uint8)
        if self.process_index == 0 and payload:
            buf = np.frombuffer(payload, dtype=np.uint8).copy()
        buf = multihost_utils.broadcast_one_to_all(buf)
        return pickle.loads(buf.tobytes())

    def allgather_obj(self, obj: Any) -> List[Any]:
        if self.process_count == 1:
            return [obj]
        # KV-store allgather: every process publishes, barriers, reads all.
        seq = self._next_seq("allgather")
        key = f"og/ag/{seq}"
        self._kv_put(f"{key}/{self.process_index}", pickle.dumps(obj))
        self._barrier(f"{key}/barrier", _rpc_policy().barrier_ms())
        return [
            pickle.loads(self._kv_get(f"{key}/{i}"))
            for i in range(self.process_count)
        ]

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        if self.process_count == 1:
            return [obj]
        # like allgather, but only root pays the N reads
        seq = self._next_seq("gather")
        key = f"og/g/{seq}"
        self._kv_put(f"{key}/{self.process_index}", pickle.dumps(obj))
        self._barrier(f"{key}/barrier", _rpc_policy().timeout_ms)
        if self.process_index != root:
            return None
        return [
            pickle.loads(self._kv_get(f"{key}/{i}"))
            for i in range(self.process_count)
        ]

    def scatter_obj(self, objs: Optional[List[Any]], root: int = 0) -> Any:
        if self.process_count == 1:
            assert objs is not None
            return objs[0]
        seq = self._next_seq("scatter")
        key = f"og/sc/{seq}"
        if self.process_index == root:
            assert objs is not None and len(objs) == self.process_count
            for i, o in enumerate(objs):
                if i != root:
                    self._kv_put(f"{key}/{i}", pickle.dumps(o))
        self._barrier(f"{key}/barrier", _rpc_policy().timeout_ms)
        if self.process_index == root:
            return objs[self.process_index]
        return pickle.loads(self._kv_get(f"{key}/{self.process_index}"))

    # -- point-to-point -------------------------------------------------

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        if self.process_count == 1:
            raise RuntimeError("send_obj with a single process has no peer")
        seq = self._next_seq(f"p2p/{self.process_index}/{dest}/{tag}")
        self._kv_put(
            f"og/p2p/{self.process_index}/{dest}/{tag}/{seq}", pickle.dumps(obj)
        )

    def recv_obj(self, src: int, tag: int = 0) -> Any:
        if self.process_count == 1:
            raise RuntimeError("recv_obj with a single process has no peer")
        seq = self._next_seq(f"p2p/{src}/{self.process_index}/{tag}")
        data = self._kv_get(
            f"og/p2p/{src}/{self.process_index}/{tag}/{seq}"
        )
        return pickle.loads(data)

    def try_recv_obj(self, src: int, tag: int = 0,
                     timeout_ms: Optional[int] = None) -> Any:
        """Bounded receive that leaves the channel position intact on
        timeout. ``recv_obj`` increments the channel sequence *before*
        the blocking get, so a timed-out wait would permanently desync
        the channel (the next recv skips the object that eventually
        lands). Pollers — ``fleet/transport.py`` ack/data loops — need
        to come back later, so here the sequence is committed only when
        the get succeeds; a miss raises ``TimeoutError`` and the next
        call retries the SAME slot."""
        if self.process_count == 1:
            raise RuntimeError(
                "try_recv_obj with a single process has no peer")
        channel = f"p2p/{src}/{self.process_index}/{tag}"
        seq = self._p2p_seq.get(channel, 0)
        data = self._kv_get(
            f"og/p2p/{src}/{self.process_index}/{tag}/{seq}",
            timeout_ms=timeout_ms)
        self._p2p_seq[channel] = seq + 1
        return pickle.loads(data)

    # -- host barrier ----------------------------------------------------

    def barrier(self, timeout_ms: Optional[int] = None) -> None:
        """Coordinator-backed host barrier across processes.

        Unlike a device-collective barrier (``sync_global_devices``) this
        rides the KV store: it needs no cross-process device computation
        support and every wait is guarded — a dead peer or coordinator
        turns into a bounded ``JobAbortedError``/``TimeoutError`` instead
        of an infinite rendezvous (the watchdog contract)."""
        if self.process_count == 1:
            return
        seq = self._next_seq("host_barrier")
        self._barrier(f"og/hb_barrier/{seq}",
                      timeout_ms if timeout_ms is not None
                      else _rpc_policy().barrier_ms())

    # -- kv helpers (chunked; coordinator values are bounded strings) ----

    def _next_seq(self, channel: str) -> int:
        n = self._p2p_seq.get(channel, 0)
        self._p2p_seq[channel] = n + 1
        return n

    def _kv_put(self, key: str, data: bytes) -> None:
        _chaos.on_rpc("kv_put")
        client = _client()
        nchunks = max(1, (len(data) + _KV_CHUNK - 1) // _KV_CHUNK)

        def put_all():
            # ONE guard thread for the whole put (not one per chunk RPC):
            # large scatters would otherwise spawn hundreds of short-lived
            # threads; the liveness probe still fires every probe slice
            client.key_value_set(f"{key}/n", str(nchunks))
            for c in range(nchunks):
                client.key_value_set_bytes(
                    f"{key}/{c}", data[c * _KV_CHUNK:(c + 1) * _KV_CHUNK])

        # budget scales with payload so multi-GB scatters aren't cut off
        _guard_rpc(put_all, budget_ms=_rpc_policy().put_budget_ms(nchunks))

    def _kv_get(self, key: str, timeout_ms: Optional[int] = None) -> bytes:
        if timeout_ms is None:
            timeout_ms = _rpc_policy().timeout_ms
        nchunks = int(_sliced_get(f"{key}/n", timeout_ms))
        parts = []
        for c in range(nchunks):
            parts.append(_sliced_get(f"{key}/{c}", timeout_ms, raw=True))
        return b"".join(parts)

    def _barrier(self, name: str, timeout_ms: int) -> None:
        _chaos.on_rpc("barrier")
        client = _client()
        # barriers cannot be sliced (a timed-out barrier id is poisoned for
        # every participant), so guard the single long wait with probes
        _guard_rpc(lambda: client.wait_at_barrier(name, timeout_ms),
                   budget_ms=timeout_ms + _rpc_policy().probe_ms)


def _coordinator_alive() -> None:
    """Raise if the job is aborted or the coordinator is unreachable.

    Two checks: (1) the poison key posted by a crashing rank's except hook
    or the watchdog (non-blocking read; missing key = healthy); (2) a
    short get on the
    liveness key every ObjectPlane seeds at construction — it returns
    instantly while the coordinator lives, so ANY error (including a
    client-side deadline against a dead endpoint) means the coordinator is
    gone."""
    client = _client()
    reason = _read_abort(client)
    if reason is not None:
        raise JobAbortedError(
            f"job aborted by a crashed peer: {reason}")
    last = None
    pol = _rpc_policy()
    ladder = pol.liveness_ladder_ms()
    for attempt, attempt_ms in enumerate(ladder):
        # retry ladder: a loaded coordinator may miss one short deadline;
        # back off (jittered) between attempts so N stuck ranks don't
        # hammer a struggling coordinator in lockstep
        try:
            client.blocking_key_value_get(_ALIVE_KEY, attempt_ms)
            return
        except Exception as e:  # noqa: BLE001
            last = e
            if attempt + 1 < len(ladder):
                time.sleep(pol.backoff_ms(attempt) / 1000.0)
    raise RuntimeError(
        f"jax.distributed coordinator unreachable — aborting instead "
        f"of waiting out the full collective timeout: {last}") from last


def _guard_rpc(fn, budget_ms: Optional[int] = None):
    """Run a coordinator RPC that has no deadline of its own on a worker
    thread; while it blocks, probe coordinator liveness every policy probe
    slice and raise promptly if the coordinator is gone (the abandoned
    daemon thread is moot — the caller is about to tear the process
    down)."""
    pol = _rpc_policy()
    if budget_ms is None:
        budget_ms = pol.timeout_ms
    result: dict = {}

    def run():
        try:
            result["v"] = fn()
        except BaseException as e:  # noqa: BLE001 — surfaced to caller
            result["e"] = e

    th = threading.Thread(target=run, daemon=True)
    th.start()
    waited = 0
    while True:
        slice_ms = min(pol.probe_ms, budget_ms - waited)
        th.join(max(slice_ms, 1) / 1000)
        waited += slice_ms
        if not th.is_alive():
            break
        if waited >= budget_ms:
            raise TimeoutError(
                f"coordinator RPC exceeded its {budget_ms} ms budget")
        _coordinator_alive()
    if "e" in result:
        raise result["e"]
    return result.get("v")


def _is_deadline_error(e: Exception) -> bool:
    """Timed-out-waiting-for-key vs transport failure.

    Prefer a structured gRPC status when the client exposes one (``code()``
    on grpc-style errors); fall back to the canonical status NAME in the
    message (jaxlib's XlaRuntimeError stringifies as
    'DEADLINE_EXCEEDED: ...'), and only then to loose wording — gRPC/jaxlib
    phrasing varies across versions and a misclassified transport error
    would be retried while a misclassified deadline aborts the collective.
    """
    code = getattr(e, "code", None)
    if callable(code):
        try:
            name = getattr(code(), "name", "")
            if name:
                return name.upper() == "DEADLINE_EXCEEDED"
        except Exception:
            pass
    # No structured status: accept only the canonical status token and
    # jaxlib's exact key-wait phrasing. Looser matching ("timeout",
    # "timed out" anywhere) classified CONNECTION-timeout transport
    # failures as key-wait deadlines, retrying against a dead coordinator
    # instead of failing fast (bounded by _coordinator_alive, but it
    # delayed abort by whole probe windows).
    msg = str(e).lower()
    return ("deadline_exceeded" in msg
            or "timed out waiting for key" in msg)


def _sliced_get(key: str, timeout_ms: int, raw: bool = False):
    """blocking_key_value_get with the budget sliced into short attempts,
    probing coordinator liveness between slices (fail-fast)."""
    _chaos.on_rpc("kv_get")
    client = _client()
    get = (client.blocking_key_value_get_bytes if raw
           else client.blocking_key_value_get)
    waited = 0
    while True:
        slice_ms = min(_rpc_policy().probe_ms, timeout_ms - waited)
        if slice_ms <= 0:
            raise TimeoutError(
                f"key {key!r} not published within {timeout_ms} ms")
        try:
            return get(key, slice_ms)
        except Exception as e:  # noqa: BLE001
            if not _is_deadline_error(e):
                raise  # transport error: coordinator gone — fail fast
            waited += slice_ms
            _coordinator_alive()


class FsObjectPlane:
    """File-backed point-to-point object plane for supervised fleets.

    The jax.distributed coordinator cannot re-admit a rank after SIGKILL
    (the service pins membership at init), which rules the KV store out
    as the wire for the supervised-restart drill: the whole point is
    that a killed prefill host comes back under
    :class:`~chainermn_tpu.resilience.supervisor.Supervisor` and keeps
    shipping handoffs. This plane keeps the exact ``send_obj`` /
    ``recv_obj`` / ``try_recv_obj`` surface but rides a shared
    directory instead:

    * one subdirectory per directed channel ``(src, dst, tag)``, one
      file per message, named by sequence number;
    * writes are atomic (tmp + ``os.replace``) so a reader can never
      observe a torn message — a SIGKILL mid-write leaves only a tmp
      file the reader ignores;
    * the sender derives its next sequence from the files already on
      disk, so a restarted incarnation continues the channel instead of
      overwriting it; when :meth:`gc` has pruned every consumed file,
      the per-channel ``HWM`` high-water mark supplies the floor, so a
      reborn sender still never reuses a sequence number;
    * the receiver may :meth:`gc` a channel after resolving frames:
      the high-water mark is committed atomically BEFORE any file is
      unlinked, and a reborn receiver seeds its position from it — a
      crash between the two steps at worst re-deletes, never re-reads;
    * every receive is deadline-sliced exactly like the KV-store path
      (``TimeoutError`` on a miss; ``try_recv_obj`` commits the reader
      position only on success).

    Single-host scope: this is the test/drill wire for processes
    sharing a filesystem, not a datacenter transport — the production
    path is :class:`ObjectPlane` over the coordinator.
    """

    def __init__(self, root: str, index: int, count: int) -> None:
        import os as _os

        self.root = root
        self.process_index = int(index)
        self.process_count = int(count)
        self._recv_pos: dict = {}
        _os.makedirs(root, exist_ok=True)

    def _chan_dir(self, src: int, dst: int, tag: int) -> str:
        import os as _os

        return _os.path.join(self.root, f"p2p_{src}_{dst}_{tag}")

    @staticmethod
    def _read_hwm(chan_dir: str) -> int:
        """The channel's GC high-water mark: every seq below it has
        been consumed and pruned (0 when the channel was never GCed)."""
        import os as _os

        try:
            with open(_os.path.join(chan_dir, "HWM")) as f:
                return int(f.read().strip() or 0)
        except (FileNotFoundError, ValueError):
            return 0

    @classmethod
    def _next_seq(cls, chan_dir: str) -> int:
        """Next unused sequence on a channel (restart-safe): one past
        the highest frame still on disk, falling back to the GC
        high-water mark when every consumed frame has been pruned —
        counting files would re-issue seqs after a :meth:`gc`."""
        import os as _os

        try:
            names = _os.listdir(chan_dir)
        except FileNotFoundError:
            return 0
        seqs = [int(n[:-4]) for n in names if n.endswith(".obj")]
        if seqs:
            return max(seqs) + 1
        return cls._read_hwm(chan_dir)

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        import os as _os
        import tempfile

        chan = self._chan_dir(self.process_index, dest, tag)
        _os.makedirs(chan, exist_ok=True)
        seq = self._next_seq(chan)
        fd, tmp = tempfile.mkstemp(dir=chan, suffix=".tmp")
        try:
            with _os.fdopen(fd, "wb") as f:
                f.write(pickle.dumps(obj))
                f.flush()
                _os.fsync(f.fileno())
            _os.replace(tmp, _os.path.join(chan, f"{seq:08d}.obj"))
        except BaseException:
            try:
                _os.unlink(tmp)
            except OSError:
                pass
            raise

    def _read_at(self, src: int, tag: int, seq: int,
                 timeout_ms: Optional[int]) -> bytes:
        import os as _os

        pol = _rpc_policy()
        if timeout_ms is None:
            timeout_ms = pol.timeout_ms
        path = _os.path.join(self._chan_dir(src, self.process_index, tag),
                             f"{seq:08d}.obj")
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            try:
                with open(path, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                pass
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    f"object {path!r} not published within {timeout_ms} ms")
            # poll fast: the drill ships small frames on localhost, and a
            # probe-sliced sleep would add whole probe windows of latency
            time.sleep(min(left, 0.005))

    def _pos(self, src: int, tag: int) -> int:
        """Current reader position, seeded from the channel's GC
        high-water mark on first access — a reborn receiver must not
        wait on frames :meth:`gc` already unlinked."""
        chan = (src, tag)
        if chan not in self._recv_pos:
            self._recv_pos[chan] = self._read_hwm(
                self._chan_dir(src, self.process_index, tag))
        return self._recv_pos[chan]

    def recv_obj(self, src: int, tag: int = 0) -> Any:
        seq = self._pos(src, tag)
        self._recv_pos[(src, tag)] = seq + 1
        return pickle.loads(self._read_at(src, tag, seq, None))

    def try_recv_obj(self, src: int, tag: int = 0,
                     timeout_ms: Optional[int] = None) -> Any:
        """Bounded receive; the reader position advances only on
        success, so a timed-out poll retries the same slot later."""
        seq = self._pos(src, tag)
        data = self._read_at(src, tag, seq, timeout_ms)
        self._recv_pos[(src, tag)] = seq + 1
        return pickle.loads(data)

    def gc(self, src: int, tag: int = 0) -> int:
        """Prune this receiver's consumed frames on channel
        ``src → self``. Commits ``HWM = position`` atomically FIRST,
        then unlinks every ``.obj`` below it; returns the number
        pruned. Crash-safe in both orders: a crash before the mark
        leaves extra files (re-GCed later), a crash after it leaves a
        mark that only covers already-consumed frames. Unconsumed
        frames (seq >= position) are never touched, so a sender
        mid-flight loses nothing."""
        import os as _os
        import tempfile

        chan_dir = self._chan_dir(src, self.process_index, tag)
        pos = self._pos(src, tag)
        _os.makedirs(chan_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=chan_dir, suffix=".tmp")
        try:
            with _os.fdopen(fd, "w") as f:
                f.write(str(pos))
                f.flush()
                _os.fsync(f.fileno())
            _os.replace(tmp, _os.path.join(chan_dir, "HWM"))
        except BaseException:
            try:
                _os.unlink(tmp)
            except OSError:
                pass
            raise
        pruned = 0
        for name in _os.listdir(chan_dir):
            if name.endswith(".obj") and int(name[:-4]) < pos:
                try:
                    _os.unlink(_os.path.join(chan_dir, name))
                    pruned += 1
                except OSError:
                    pass                # concurrent GC: already gone
        return pruned

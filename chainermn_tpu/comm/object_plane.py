"""Host-side object plane.

TPU-native replacement for the reference's pickled-MPI object transport
(reference: chainermn/communicators/mpi_communicator_base.py — object ops
``bcast_obj``/``gather_obj``/``send_obj``/``recv_obj`` built on mpi4py's
pickle-based messaging; module path per SURVEY.md §2.1, reference mount empty).

Here the object world is the set of JAX *processes* (hosts), matching the
reference's node-level object plane. Transport:

* single process — trivial identity paths (the common single-controller case);
* multi-process — pickled payloads ride ``jax.experimental.multihost_utils``
  (uint8 tensors over the DCN collective fabric) for collectives, and the
  ``jax.distributed`` coordinator's KV store for point-to-point, chunked to
  bound coordinator message sizes (the analog of the reference's 256 MB
  ``max_buf_len`` chunking in scatter_dataset).
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional

import numpy as np

import jax

# KV-store chunk bound: coordinator values are strings; keep chunks modest.
_KV_CHUNK = 4 * 1024 * 1024


def _client():
    """The jax.distributed coordinator client, or None."""
    try:
        from jax._src import distributed  # noqa: internal, only path to KV store

        return distributed.global_state.client
    except Exception:
        return None


class ObjectPlane:
    """Process-plane object collectives.

    Sequence counters are CLASS-level: every instance in a process shares
    them, because all instances share the coordinator's one key namespace —
    per-instance counters would collide (e.g. a user-made plane and the
    communicator's internal one both starting at seq 0). SPMD discipline
    (every process runs the same program, hence the same call order) keeps
    the counters aligned across processes, exactly like MPI collectives.
    The counters are scoped to the coordinator client: re-initializing
    jax.distributed gives a fresh KV namespace, so planes created after that
    must restart at seq 0 or they desync from peers that start fresh.
    """

    _seq: dict = {}
    # strong ref to the coordinator client the counters belong to; `is`
    # comparison is unambiguous (an id() would be reusable after free)
    _seq_client: Any = None

    def __init__(self) -> None:
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        client = _client()
        if client is not ObjectPlane._seq_client:
            ObjectPlane._seq_client = client
            ObjectPlane._seq.clear()
        self._p2p_seq = ObjectPlane._seq

    # -- collectives ----------------------------------------------------

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        if self.process_count == 1:
            return obj
        from jax.experimental import multihost_utils

        payload = pickle.dumps(obj) if self.process_index == root else b""
        # Ship (length, data) as uint8; broadcast_one_to_all roots at process 0,
        # so first hop payloads to process 0 over the KV store if root differs.
        # The relay key carries a sequence number like every other KV channel:
        # the coordinator rejects duplicate keys, and a reused key would hand
        # process 0 the previous bcast's stale payload.
        if root != 0:
            seq = self._next_seq(f"bcast_root/{root}")
            if self.process_index == root:
                self._kv_put(f"bcast_root/{root}/{seq}", payload)
            if self.process_index == 0:
                payload = self._kv_get(f"bcast_root/{root}/{seq}")
        n = np.array([len(payload)], dtype=np.int64)
        n = multihost_utils.broadcast_one_to_all(n)
        buf = np.zeros(int(n[0]), dtype=np.uint8)
        if self.process_index == 0 and payload:
            buf = np.frombuffer(payload, dtype=np.uint8).copy()
        buf = multihost_utils.broadcast_one_to_all(buf)
        return pickle.loads(buf.tobytes())

    def allgather_obj(self, obj: Any) -> List[Any]:
        if self.process_count == 1:
            return [obj]
        # KV-store allgather: every process publishes, barriers, reads all.
        client = _client()
        seq = self._next_seq("allgather")
        key = f"og/ag/{seq}"
        self._kv_put(f"{key}/{self.process_index}", pickle.dumps(obj))
        client.wait_at_barrier(f"{key}/barrier", 60_000)
        return [
            pickle.loads(self._kv_get(f"{key}/{i}"))
            for i in range(self.process_count)
        ]

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        if self.process_count == 1:
            return [obj]
        # like allgather, but only root pays the N reads
        client = _client()
        seq = self._next_seq("gather")
        key = f"og/g/{seq}"
        self._kv_put(f"{key}/{self.process_index}", pickle.dumps(obj))
        client.wait_at_barrier(f"{key}/barrier", 600_000)
        if self.process_index != root:
            return None
        return [
            pickle.loads(self._kv_get(f"{key}/{i}"))
            for i in range(self.process_count)
        ]

    def scatter_obj(self, objs: Optional[List[Any]], root: int = 0) -> Any:
        if self.process_count == 1:
            assert objs is not None
            return objs[0]
        client = _client()
        seq = self._next_seq("scatter")
        key = f"og/sc/{seq}"
        if self.process_index == root:
            assert objs is not None and len(objs) == self.process_count
            for i, o in enumerate(objs):
                if i != root:
                    self._kv_put(f"{key}/{i}", pickle.dumps(o))
        client.wait_at_barrier(f"{key}/barrier", 600_000)
        if self.process_index == root:
            return objs[self.process_index]
        return pickle.loads(self._kv_get(f"{key}/{self.process_index}"))

    # -- point-to-point -------------------------------------------------

    def send_obj(self, obj: Any, dest: int, tag: int = 0) -> None:
        if self.process_count == 1:
            raise RuntimeError("send_obj with a single process has no peer")
        seq = self._next_seq(f"p2p/{self.process_index}/{dest}/{tag}")
        self._kv_put(
            f"og/p2p/{self.process_index}/{dest}/{tag}/{seq}", pickle.dumps(obj)
        )

    def recv_obj(self, src: int, tag: int = 0) -> Any:
        if self.process_count == 1:
            raise RuntimeError("recv_obj with a single process has no peer")
        seq = self._next_seq(f"p2p/{src}/{self.process_index}/{tag}")
        data = self._kv_get(
            f"og/p2p/{src}/{self.process_index}/{tag}/{seq}", timeout_ms=600_000
        )
        return pickle.loads(data)

    # -- kv helpers (chunked; coordinator values are bounded strings) ----

    def _next_seq(self, channel: str) -> int:
        n = self._p2p_seq.get(channel, 0)
        self._p2p_seq[channel] = n + 1
        return n

    def _kv_put(self, key: str, data: bytes) -> None:
        client = _client()
        nchunks = max(1, (len(data) + _KV_CHUNK - 1) // _KV_CHUNK)
        client.key_value_set(f"{key}/n", str(nchunks))
        for c in range(nchunks):
            chunk = data[c * _KV_CHUNK : (c + 1) * _KV_CHUNK]
            client.key_value_set_bytes(f"{key}/{c}", chunk)

    def _kv_get(self, key: str, timeout_ms: int = 600_000) -> bytes:
        client = _client()
        nchunks = int(client.blocking_key_value_get(f"{key}/n", timeout_ms))
        parts = []
        for c in range(nchunks):
            parts.append(
                client.blocking_key_value_get_bytes(f"{key}/{c}", timeout_ms)
            )
        return b"".join(parts)

"""chainermn_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of ChainerMN (reference:
codealphago/chainermn, a mirror of pfnet/chainermn) on the JAX/XLA stack:
device meshes + compiled collectives over ICI/DCN instead of MPI + NCCL,
functional transforms instead of define-by-run hooks, and `pjit`/`shard_map`
SPMD instead of an mpiexec process-per-GPU model.

Public surface mirrors the reference's top level
(chainermn/__init__.py per SURVEY.md §2.5; reference mount was empty):
``create_communicator``, ``create_multi_node_optimizer``, ``scatter_dataset``,
``functions``, ``links``, the multi-node iterator/evaluator/checkpointer
factories, and the global exception hook.
"""

from chainermn_tpu import _compat  # noqa: F401  (jax version shims; keep first)
from chainermn_tpu.comm import (
    CommunicatorBase,
    XlaCommunicator,
    create_communicator,
)
from chainermn_tpu import collectives, functions, links
from chainermn_tpu.collectives import make_grad_reducer
from chainermn_tpu.datasets import (
    create_empty_dataset,
    scatter_dataset,
)
from chainermn_tpu.extensions import (
    create_multi_node_checkpointer,
    create_multi_node_evaluator,
    install_global_except_hook,
)
from chainermn_tpu.iterators import (
    create_multi_node_iterator,
    create_synchronized_iterator,
)
from chainermn_tpu.links import MultiNodeBatchNormalization, MultiNodeChainList
from chainermn_tpu.optimizers import create_multi_node_optimizer
from chainermn_tpu import checkpointing
from chainermn_tpu import fleet
from chainermn_tpu import resilience
from chainermn_tpu import serving

__version__ = "0.1.0"

__all__ = [
    "CommunicatorBase",
    "XlaCommunicator",
    "create_communicator",
    "create_multi_node_optimizer",
    "collectives",
    "make_grad_reducer",
    "scatter_dataset",
    "create_empty_dataset",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
    "create_multi_node_evaluator",
    "create_multi_node_checkpointer",
    "install_global_except_hook",
    "functions",
    "links",
    "MultiNodeBatchNormalization",
    "MultiNodeChainList",
    "checkpointing",
    "fleet",
    "resilience",
    "serving",
    "__version__",
]

"""chainermn_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of ChainerMN (reference:
codealphago/chainermn, a mirror of pfnet/chainermn) on the JAX/XLA stack:
device meshes + compiled collectives over ICI/DCN instead of MPI + NCCL,
functional transforms instead of define-by-run hooks, and `pjit`/`shard_map`
SPMD instead of an mpiexec process-per-GPU model.

Public surface mirrors the reference's top level
(chainermn/__init__.py per SURVEY.md §2.5; reference mount was empty):
``create_communicator``, ``create_multi_node_optimizer``, ``scatter_dataset``,
``functions``, ``links``, the multi-node iterator/evaluator/checkpointer
factories, and the global exception hook.
"""

from chainermn_tpu.comm import (
    CommunicatorBase,
    XlaCommunicator,
    create_communicator,
)

__version__ = "0.1.0"

__all__ = [
    "CommunicatorBase",
    "XlaCommunicator",
    "create_communicator",
    "__version__",
]

from .batch_normalization import MultiNodeBatchNormalization
from .chain_list import MultiNodeChainList

__all__ = ["MultiNodeBatchNormalization", "MultiNodeChainList"]

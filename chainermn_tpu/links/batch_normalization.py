"""Cross-replica batch normalization.

Reference: chainermn/links/multi_node_batch_normalization.py (SURVEY.md §2.4;
mount empty — module path citation). The reference packs local ``mean`` and
``sq-mean`` into one buffer and all-reduces it so BN statistics span every
replica's batch; backward all-reduces the γ/β gradient terms; running
averages are kept for inference.

TPU-native form: a flax module whose statistics are ``pmean``-ed over the
communicator's mesh axes *inside the compiled forward* — the backward sync
falls out of ``psum``'s transpose, and XLA fuses the two stat reductions into
one fused collective (the reference's manual packing). Built on
``flax.linen.BatchNorm(axis_name=...)``, which implements exactly this
cross-device moment reduction.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class MultiNodeBatchNormalization(nn.Module):
    """BatchNorm whose batch statistics span all replicas.

    Reference signature: ``MultiNodeBatchNormalization(size, comm, decay,
    eps, dtype)``. ``size`` (the feature count) is inferred from the input in
    flax and accepted only for API parity; ``comm`` supplies the mesh axes to
    reduce over. Use inside a ``shard_map``/``pjit`` program whose mesh binds
    those axes; ``use_running_average=True`` for inference.
    """

    comm: Any = None
    size: Optional[int] = None           # parity only; flax infers features
    decay: float = 0.9
    eps: float = 2e-5
    dtype: Optional[Any] = None
    use_running_average: Optional[bool] = None
    communication_backend: str = "auto"  # parity only; XLA is the backend
    scale_init: Any = nn.initializers.ones_init()
    bias_init: Any = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = (
            use_running_average
            if use_running_average is not None
            else self.use_running_average
        )
        axis_name = None
        if self.comm is not None:
            names = self.comm.axis_names
            axis_name = names if len(names) > 1 else names[0]
        return nn.BatchNorm(
            use_running_average=bool(use_ra),
            momentum=self.decay,
            epsilon=self.eps,
            dtype=self.dtype,
            axis_name=axis_name,
            scale_init=self.scale_init,
            bias_init=self.bias_init,
        )(x)

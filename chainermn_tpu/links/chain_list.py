"""MultiNodeChainList — declarative graph-partition model parallelism.

Reference: chainermn/links/multi_node_chain_list.py (SURVEY.md §2.4, §3.3;
mount empty — module path citation). There, every rank registers sub-chains
with ``add_link(chain, rank_in, rank_out)``; ``__call__`` walks the registry
calling local chains and inserting blocking MPI ``send``/``recv`` (plus
``pseudo_connect`` glue) between ranks — correct only if every rank issues
communication in a globally consistent order.

TPU-native redesign: the single controller declares the **whole** stage graph
(each stage names its owner rank explicitly — the one deviation from the
reference, whose per-process scripts implied the owner). ``__call__`` builds
one uniform SPMD program: every shard traces every stage in order, inter-rank
edges lower to ``lax.ppermute`` (XLA collective-permute over ICI), and
non-owner shards compute on the zeros the permute leaves behind — harmless,
since the reference schedule is sequential anyway (idle ranks wait on recv;
here they duplicate compute in the same wall-clock slot). The runtime
deadlock class is gone: the schedule is fixed at trace time. Gradients flow
backward through the reversed permutes automatically.

Memory note: stage parameters are replicated in this executor (every shard
traces every stage) — parity-true, since the reference schedule is
sequential anyway. For LINEAR chains, :meth:`MultiNodeChainList.
to_hetero_pipeline` lowers the same registry onto the micro-batched 1F1B
pipeline (parallel/hetero_pipeline.py): per-stage parameters sharded over
the mesh axis (each device holds only its stage) and the fill/drain bubble
amortized over micro-batches — true memory AND compute scaling, beyond the
reference. BRANCHING graphs lower with :meth:`MultiNodeChainList.
to_branching_pipeline` onto the DAG schedule (parallel/branching.py):
same per-device stage params, same-depth branches computing in the same
tick. The replicated executor keeps an EXPLICIT parameter budget: past
it, ``apply`` refuses and points at the matching lowering instead of
becoming the silent OOM (VERDICT r2 #7).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu import functions as F


def _as_tuple(x) -> Tuple[int, ...]:
    if x is None:
        return ()
    if isinstance(x, (list, tuple)):
        return tuple(int(v) for v in x)
    return (int(x),)


@dataclass
class _Stage:
    module: Any                      # flax module or callable(params, *xs)
    rank: int                        # owner shard
    rank_in: Tuple[int, ...]         # () → consumes the global input
    rank_out: Tuple[int, ...]        # () → produces a model output


class MultiNodeChainList:
    """Compose sub-modules placed on ranks into one compiled program.

    Usage::

        mlp = MultiNodeChainList(comm)
        mlp.add_link(Part0(), rank=0, rank_in=None, rank_out=1)
        mlp.add_link(Part1(), rank=1, rank_in=0, rank_out=None)
        params = mlp.init(rng, x_sample)          # host-side, per stage
        y = mlp.apply(params, x)                  # inside shard_map/jit

    Stage modules are flax modules (``init``/``apply``) or plain callables
    ``f(params, *inputs)`` (then ``init`` entries may be None).
    """

    #: default replicated-parameter budget for ``apply`` (bytes). The
    #: SPMD executor replicates EVERY stage's params on every device
    #: (module docstring "Memory note"); past a few GiB of a v5e's 16 GiB
    #: HBM that silently becomes the thing that OOMs a training step, so
    #: the executor refuses with guidance instead (VERDICT r2 #7).
    DEFAULT_PARAM_BUDGET = 4 * 2 ** 30

    def __init__(self, comm, replicated_param_budget_bytes: int = None):
        self.comm = comm
        self._stages: List[_Stage] = []
        self._budget = (replicated_param_budget_bytes
                        if replicated_param_budget_bytes is not None
                        else self.DEFAULT_PARAM_BUDGET)

    def add_link(self, module, rank: Optional[int] = None,
                 rank_in=None, rank_out=None):
        if rank is None:
            raise ValueError(
                "the single-controller chain list declares the whole graph: "
                "name the owning rank explicitly, add_link(m, rank=..., ...)"
            )
        self._stages.append(
            _Stage(module, int(rank), _as_tuple(rank_in), _as_tuple(rank_out))
        )

    # ------------------------------------------------------------------

    def init(self, rng, x):
        """Initialize every stage's params by abstractly walking the graph
        on the host (stage s's sample input = its producers' outputs)."""
        params: List[Any] = []
        messages = {}
        outputs = []
        h = None
        for i, st in enumerate(self._stages):
            inputs = self._stage_inputs(st, x, messages, consume=True)
            rng, sub = jax.random.split(rng)
            if hasattr(st.module, "init"):
                p = st.module.init(sub, *inputs)
                y = st.module.apply(p, *inputs)
            else:
                p = None
                y = st.module(p, *inputs)
            params.append(p)
            for dst in st.rank_out:
                messages[(st.rank, dst)] = y
            if not st.rank_out:
                outputs.append(y)
        return params

    def _stage_inputs(self, st: _Stage, x, messages, consume: bool):
        if not st.rank_in:
            return (x,)
        inputs = []
        for src in st.rank_in:
            key = (src, st.rank)
            if key not in messages:
                raise ValueError(
                    f"stage on rank {st.rank} expects input from rank {src}, "
                    f"but no earlier stage sent to it — check rank_in/rank_out "
                    "wiring and declaration order"
                )
            inputs.append(messages.pop(key) if consume else messages[key])
        return tuple(inputs)

    def apply(self, params: Sequence[Any], x):
        """The compiled SPMD forward. Call inside shard_map over the
        communicator's axis (or under jit with the mesh bound)."""
        self._check_param_budget(params)
        messages = {}
        outputs = []
        for st, p in zip(self._stages, params):
            inputs = self._stage_inputs(st, x, messages, consume=True)
            if hasattr(st.module, "apply"):
                y = st.module.apply(p, *inputs)
            else:
                y = st.module(p, *inputs)
            for dst in st.rank_out:
                # the compiled edge: one collective-permute per (src, dst)
                phi = F.send(y, self.comm, dst, self_rank=st.rank)
                messages[(st.rank, dst)] = F.recv(self.comm, st.rank,
                                                  delegate_variable=phi)
            if not st.rank_out:
                # model output: make the owner's value visible everywhere
                outputs.append(self.comm.bcast(y, root=st.rank))
        if not outputs:
            raise ValueError("no output stage (every stage has rank_out)")
        return outputs[0] if len(outputs) == 1 else tuple(outputs)

    __call__ = apply

    def _check_param_budget(self, params):
        """Refuse to trace a replicated-params program that cannot fit.

        Every device materializes EVERY stage's parameters under this
        executor. That is parity-true for the reference's sequential
        schedule but becomes an OOM long before a real branching model
        runs out of stages — the memory-scaling boundary is explicit
        (VERDICT r2 #7): linear chains lower to the 1F1B pipeline
        (each device holds ONE stage); branching graphs must shrink,
        raise the budget consciously, or TP-shard their big stages.
        """
        # plain-callable stages may carry Python scalar leaves (no
        # .size/.dtype); tracers have both
        def _nbytes(l):
            dt = getattr(l, "dtype", None) or np.result_type(l)
            return int(np.size(l)) * np.dtype(dt).itemsize

        total = sum(
            _nbytes(l)
            for p in params
            for l in jax.tree_util.tree_leaves(p))
        if total <= self._budget:
            return
        linear = True
        try:
            self._check_linear()
        except ValueError:
            linear = False
        hint = (
            "this chain is LINEAR: lower it with to_hetero_pipeline() "
            "— each device then holds only its own stage's parameters "
            "under the 1F1B schedule"
            if linear else
            "this graph is not in canonical linear 0→1→…→S-1 form: if "
            "it is actually a reordered linear chain, relabel the ranks "
            "and lower with to_hetero_pipeline(); if it genuinely "
            "branches, lower it with to_branching_pipeline() — the DAG "
            "schedule gives each device only its own stage's params "
            "(parallel/branching.py); alternatively TP-shard the large "
            "stages over a second mesh axis "
            "(parallel/tensor_parallel.py) or raise the budget "
            "explicitly via MultiNodeChainList(comm, "
            "replicated_param_budget_bytes=...) if replication is "
            "genuinely intended")
        raise ValueError(
            f"MultiNodeChainList.apply replicates all stage parameters "
            f"on every device: {total / 2**20:.0f} MiB total exceeds "
            f"the {self._budget / 2**20:.0f} MiB budget; " + hint)

    # ------------------------------------------------------------------

    def _check_linear(self):
        """The chain must be rank 0 → 1 → … → S-1 with no branching."""
        S = len(self._stages)
        for i, st in enumerate(self._stages):
            ok = (st.rank == i
                  and st.rank_in == (() if i == 0 else (i - 1,))
                  and st.rank_out == (() if i == S - 1 else (i + 1,)))
            if not ok:
                raise ValueError(
                    f"stage {i} (rank={st.rank}, rank_in={st.rank_in}, "
                    f"rank_out={st.rank_out}) breaks the linear chain "
                    "0→1→…→S-1; branching/reordered graphs run on the "
                    "SPMD apply() executor instead"
                )

    def to_hetero_pipeline(self, params: Sequence[Any], sample_mb,
                           **pipe_kwargs):
        """Lower a LINEAR chain onto the 1F1B pipeline (memory scaling).

        Args:
          params: the per-stage params from :meth:`init`.
          sample_mb: one micro-batch example (array or ShapeDtypeStruct)
            of the chain's input — note this is a MICRO-batch: the 1F1B
            caller splits its global batch into ``[M, mb, ...]``.
          pipe_kwargs: forwarded to :class:`HeteroPipeline`
            (``wire_dtype``, ``int_bound``, ``head_in_loss``). By
            default (``head_in_loss=True``) the final stage and the
            caller's ``loss_fn`` run cond-guarded on the last device —
            so ``loss_fn`` must not contain STAGE-axis collectives; pass
            ``head_in_loss=False`` (the full-width wire format) if it
            does.

        Returns the :class:`~chainermn_tpu.parallel.HeteroPipeline`:
        ``pack_params()`` gives the ``[S, P]`` stack to shard over the
        communicator's axis, and
        :func:`~chainermn_tpu.parallel.hetero_pipeline_1f1b_value_and_grad`
        runs the training step inside shard_map. Each device then holds
        ONLY its own stage's parameters — the scaling the replicated
        ``apply()`` executor forgoes.
        """
        from chainermn_tpu.parallel import HeteroPipeline

        self._check_linear()

        def stage_fn(module):
            if hasattr(module, "apply"):
                return lambda p, h: module.apply(p, h)
            # map the {} no-params sentinel back to the None the callable
            # was built with (leaf-count check: truthiness of an array /
            # tracer params pytree would raise)
            return lambda p, h: module(
                p if jax.tree_util.tree_leaves(p) else None, h)

        stage_defs = [
            (stage_fn(st.module), p if p is not None else {})
            for st, p in zip(self._stages, params)
        ]
        return HeteroPipeline(stage_defs, sample_mb,
                              axis_name=self.comm.axis_names[0],
                              **pipe_kwargs)

    def to_branching_pipeline(self, params: Sequence[Any], sample_mb,
                              **pipe_kwargs):
        """Lower a BRANCHING (DAG) chain graph onto the scheduled
        pipeline executor — per-device stage parameters for the graphs
        ``to_hetero_pipeline`` rejects.

        Requirements (checked): stage ``i`` declared with ``rank=i``
        (device ``i`` runs stage ``i``; relabel if needed — the
        declaration order is already topological because
        ``_stage_inputs`` demands producers come first); exactly one
        output stage (``rank_out=()``) — its output feeds the caller's
        ``loss_fn``; every stage is multi-input-capable via its module's
        ``apply(p, *xs)``.

        Returns a :class:`~chainermn_tpu.parallel.BranchingPipeline`:
        shard ``pack_params()`` over the communicator's axis and run
        :func:`~chainermn_tpu.parallel.branching_pipeline_value_and_grad`
        inside shard_map. Each device materializes ONLY its own stage —
        the memory scaling the replicated ``apply()`` budget-refuses
        (reference: branching MultiNodeChainList graphs,
        chainermn/links/multi_node_chain_list.py).
        """
        from chainermn_tpu.parallel import BranchingPipeline

        for i, st in enumerate(self._stages):
            if st.rank != i:
                raise ValueError(
                    f"stage {i} declared rank {st.rank}: the pipeline "
                    "lowering places stage i on device i — relabel ranks "
                    "to the declaration order")
        rank_to_idx = {st.rank: i for i, st in enumerate(self._stages)}
        preds = []
        for st in self._stages:
            preds.append(tuple(rank_to_idx[r] for r in st.rank_in))

        def stage_fn(module):
            if hasattr(module, "apply"):
                return lambda p, *xs: module.apply(p, *xs)
            return lambda p, *xs: module(
                p if jax.tree_util.tree_leaves(p) else None, *xs)

        stage_defs = [
            (stage_fn(st.module), p if p is not None else {}, pr)
            for st, p, pr in zip(self._stages, params, preds)
        ]
        return BranchingPipeline(stage_defs, sample_mb,
                                 axis_name=self.comm.axis_names[0],
                                 **pipe_kwargs)

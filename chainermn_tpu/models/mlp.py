"""MLP — the bring-up model (reference: examples/mnist/train_mnist.py's
three-layer MLP; SURVEY.md §2.6 config #1)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Reference example topology: 784 → n_units → n_units → n_out."""

    n_units: int = 1000
    n_out: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.n_units)(x))
        x = nn.relu(nn.Dense(self.n_units)(x))
        return nn.Dense(self.n_out)(x)

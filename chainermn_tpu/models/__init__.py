from .mlp import MLP
from .transformer import TransformerLM
from .vit import ViT

__all__ = ["MLP", "TransformerLM", "ViT"]

from .mlp import MLP
from .transformer import TransformerLM

__all__ = ["MLP", "TransformerLM"]

"""Seq2seq encoder-decoder (BASELINE config #4's model family).

Reference: examples/seq2seq/seq2seq.py — an LSTM encoder-decoder for WMT
En-De with variable-length batches (SURVEY.md §2.6). TPU-first rebuild:

* recurrence via ``flax.linen.RNN`` (``lax.scan`` under the hood — static
  shapes, compiler-friendly);
* variable-length sequences become padded + masked batches, with lengths
  bucketed to multiples (``pad_batch``) so XLA compiles a handful of shapes
  instead of one per batch — the TPU answer to the reference's per-batch
  dynamic shapes;
* bfloat16 compute optional, fp32 softmax.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

PAD, BOS, EOS = 0, 1, 2


from chainermn_tpu.utils import match_vma as _match_vma


class LstmStack(nn.Module):
    n_layers: int
    n_units: int

    @nn.compact
    def __call__(self, x, seq_lengths=None, initial_carries=None):
        """Returns (final_carries, outputs)."""
        carries = []
        h = x
        for i in range(self.n_layers):
            cell = nn.OptimizedLSTMCell(features=self.n_units)
            if initial_carries is not None:
                init = initial_carries[i]
            else:
                init = cell.initialize_carry(
                    jax.random.PRNGKey(0), h.shape[:1] + h.shape[2:]
                )
            init = _match_vma(init, h)
            carry, h = nn.RNN(cell, return_carry=True)(
                h, seq_lengths=seq_lengths, initial_carry=init
            )
            carries.append(carry)
        return carries, h


class Seq2Seq(nn.Module):
    """LSTM encoder-decoder with teacher forcing.

    ``__call__(src, src_len, tgt_in)`` → logits [B, T_tgt, tgt_vocab].
    """

    n_layers: int = 2
    n_units: int = 256
    src_vocab: int = 40000
    tgt_vocab: int = 40000
    dtype: Any = jnp.float32

    def setup(self):
        self.src_embed = nn.Embed(self.src_vocab, self.n_units,
                                  dtype=self.dtype)
        self.tgt_embed = nn.Embed(self.tgt_vocab, self.n_units,
                                  dtype=self.dtype)
        self.encoder = LstmStack(self.n_layers, self.n_units)
        self.decoder = LstmStack(self.n_layers, self.n_units)
        self.proj = nn.Dense(self.tgt_vocab, dtype=jnp.float32)

    def __call__(self, src, src_len, tgt_in):
        carries, _ = self.encoder(self.src_embed(src), seq_lengths=src_len)
        _, h = self.decoder(self.tgt_embed(tgt_in),
                            initial_carries=carries)
        return self.proj(h)

    def encode(self, src, src_len):
        return self.encoder(self.src_embed(src), seq_lengths=src_len)[0]

    def decode_step(self, carries, token):
        """One greedy decode step: token [B] → (carries, logits [B, V])."""
        x = self.tgt_embed(token[:, None])
        carries, h = self.decoder(x, initial_carries=carries)
        return carries, self.proj(h[:, 0])


def greedy_translate(model, variables, src, src_len, max_len: int = 64):
    """Greedy decode: argmax tokens until ``max_len`` (reference:
    the seq2seq example's translate loop; here a ``lax.scan`` with static
    length — positions after EOS are PAD-masked).

    Returns [B, max_len] int32 token ids.
    """
    import jax

    carries = model.apply(variables, src, src_len, method=Seq2Seq.encode)
    b = src.shape[0]

    def step(carry, _):
        carries, token, done = carry
        carries, logits = model.apply(
            variables, carries, token, method=Seq2Seq.decode_step)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, PAD, nxt)
        done = jnp.logical_or(done, nxt == EOS)
        return (carries, nxt, done), nxt

    init = (carries, jnp.full((b,), BOS, jnp.int32),
            jnp.zeros((b,), bool))
    _, toks = jax.lax.scan(step, init, None, length=max_len)
    return jnp.transpose(toks)  # [B, max_len]


def beam_translate(model, variables, src, src_len, beam: int = 4,
                   max_len: int = 64, length_penalty: float = 0.6):
    """Beam-search decode (beyond the reference's greedy translate loop).

    K beams ride a folded [B*K] batch through ``decode_step``; each step
    expands to [B, K, V] continuations, keeps the global top-K by
    accumulated log-prob, and gathers LSTM carries by source beam.
    Finished beams (emitted EOS) may only extend with PAD at zero cost, so
    their scores freeze. Returns the best beam per batch row, [B, max_len]
    int32, chosen by GNMT length-normalized score
    ``score / ((5 + len) / 6) ** length_penalty``.
    """
    b = src.shape[0]
    k = beam
    carries = model.apply(variables, src, src_len, method=Seq2Seq.encode)
    carries = jax.tree_util.tree_map(
        lambda a: jnp.repeat(a, k, axis=0), carries)        # [B*K, ...]

    neg = -1e9
    # only beam 0 live at t=0, else the K beams duplicate
    scores0 = jnp.tile(jnp.array([0.0] + [neg] * (k - 1), jnp.float32),
                       (b, 1))
    out0 = jnp.full((b * k, max_len), PAD, jnp.int32)

    def step(carry, t):
        carries, tokens, scores, done, out = carry
        carries, logits = model.apply(
            variables, carries, tokens, method=Seq2Seq.decode_step)
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        logp = logp.reshape(b, k, v)
        done_bk = done.reshape(b, k)
        # finished beams: every continuation except free PAD is -inf
        pad_only = jnp.full((v,), neg, jnp.float32).at[PAD].set(0.0)
        logp = jnp.where(done_bk[..., None], pad_only[None, None], logp)
        new_scores, idx = jax.lax.top_k(
            (scores[..., None] + logp).reshape(b, k * v), k)  # [B, K]
        src_beam = idx // v
        token = (idx % v).astype(jnp.int32).reshape(-1)       # [B*K]
        gidx = (jnp.arange(b)[:, None] * k + src_beam).reshape(-1)
        carries = jax.tree_util.tree_map(lambda a: a[gidx], carries)
        out = out[gidx].at[:, t].set(token)
        done = done.reshape(-1)[gidx] | (token == EOS)
        return (carries, token, new_scores, done, out), None

    init = (carries, jnp.full((b * k,), BOS, jnp.int32), scores0,
            jnp.zeros((b * k,), bool), out0)
    (_, _, scores, _, out), _ = jax.lax.scan(
        step, init, jnp.arange(max_len))

    out = out.reshape(b, k, max_len)
    lengths = jnp.sum(out != PAD, axis=-1).astype(jnp.float32)  # [B, K]
    norm = ((5.0 + lengths) / 6.0) ** length_penalty
    best = jnp.argmax(scores / norm, axis=-1)                   # [B]
    return jnp.take_along_axis(
        out, best[:, None, None], axis=1)[:, 0]


def seq2seq_loss(logits, tgt_out, pad=PAD):
    """Token-level masked cross entropy (mean over non-pad tokens)."""
    import optax

    mask = (tgt_out != pad).astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt_out)
    total = jnp.sum(ce * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count, mask


def pad_batch(pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
              length_multiple: int = 16,
              max_len: int = 512):
    """Variable-length (src, tgt) pairs → fixed-bucket padded arrays.

    Returns (src [B,Ts], src_len [B], tgt_in [B,Tt], tgt_out [B,Tt]).
    tgt_in is BOS-shifted, tgt_out EOS-terminated; both PAD-filled. Lengths
    round up to ``length_multiple`` so XLA sees a small set of shapes.
    """
    def bucket(n):
        return min(max_len, -(-n // length_multiple) * length_multiple)

    srcs = [np.asarray(s) for s, _ in pairs]
    tgts = [np.asarray(t) for _, t in pairs]
    ts = bucket(max(len(s) for s in srcs))
    tt = bucket(max(len(t) for t in tgts) + 1)  # +1 for BOS/EOS shift
    b = len(pairs)
    src = np.full((b, ts), PAD, np.int32)
    src_len = np.zeros((b,), np.int32)
    tgt_in = np.full((b, tt), PAD, np.int32)
    tgt_out = np.full((b, tt), PAD, np.int32)
    for i, (s, t) in enumerate(zip(srcs, tgts)):
        s = s[:ts]
        t = t[:tt - 1]
        src[i, :len(s)] = s
        src_len[i] = len(s)
        tgt_in[i, 0] = BOS
        tgt_in[i, 1:len(t) + 1] = t
        tgt_out[i, :len(t)] = t
        tgt_out[i, len(t)] = EOS
    return src, src_len, tgt_in, tgt_out


def corpus_bleu(references, hypotheses, max_n: int = 4):
    """Corpus-level BLEU-4 with brevity penalty (the reference seq2seq
    example's reported metric; self-contained reimplementation of the
    standard formula — no nltk dependency).

    references/hypotheses: sequences of int token lists/arrays. PAD/BOS/EOS
    should already be stripped (``strip_special``). Returns a float in
    [0, 1]; 0 when any n-gram order has zero matches (standard smoothing-
    free corpus BLEU).
    """
    import collections
    import math

    clipped = [0] * max_n
    totals = [0] * max_n
    ref_len = hyp_len = 0
    for ref, hyp in zip(references, hypotheses):
        ref = [int(t) for t in ref]
        hyp = [int(t) for t in hyp]
        ref_len += len(ref)
        hyp_len += len(hyp)
        for n in range(1, max_n + 1):
            rc = collections.Counter(
                tuple(ref[i:i + n]) for i in range(len(ref) - n + 1))
            hc = collections.Counter(
                tuple(hyp[i:i + n]) for i in range(len(hyp) - n + 1))
            totals[n - 1] += max(sum(hc.values()), 0)
            clipped[n - 1] += sum(min(c, rc[g]) for g, c in hc.items())
    if hyp_len == 0 or any(t == 0 for t in totals) \
            or any(c == 0 for c in clipped):
        return 0.0
    log_p = sum(math.log(c / t) for c, t in zip(clipped, totals)) / max_n
    bp = 1.0 if hyp_len > ref_len else math.exp(1.0 - ref_len / hyp_len)
    return bp * math.exp(log_p)


def strip_special(tokens, specials=(PAD, BOS, EOS)):
    """Cut a decoded row at EOS and drop PAD/BOS (BLEU pre-processing)."""
    out = []
    for t in np.asarray(tokens).tolist():
        if t == EOS:
            break
        if t not in specials:
            out.append(int(t))
    return out

"""Vision Transformer image classifier.

Beyond-reference model family (the reference's vision models are the MNIST
MLP and ImageNet ResNet-50 — upstream `examples/{mnist,imagenet}`, SURVEY.md
§2.6): a ViT built from the same fused attention the Transformer LM uses,
giving the vision path an MXU-dominated alternative to convolutions.

TPU-first choices:
* patchify is a stride-`patch` conv (one big matmul per image — MXU work,
  not a gather);
* encoder attention is the Pallas flash kernel with ``causal=False``;
* bf16 compute / fp32 params via ``dtype`` like the other model families;
* static token count (no CLS-vs-sequence dynamic shapes; pooling is either
  a learned CLS token or global average, both shape-static).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.ops.flash_attention import (DEFAULT_BLOCKS,
                                               flash_attention)

__all__ = ["ViT", "ViTEncoderBlock"]


class ViTEncoderBlock(nn.Module):
    """Pre-LN encoder block: bidirectional attention + GELU MLP.

    ``train`` is a construction attribute, not a call argument, so
    ``nn.remat`` never traces it (a traced bool would crash the
    ``deterministic=not train`` branch)."""

    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    attention_blocks: Optional[tuple] = None
    train: bool = False

    @nn.compact
    def __call__(self, x):
        train = self.train
        b, l, d = x.shape
        dh = self.d_model // self.n_heads

        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.d_model, use_bias=False, dtype=self.dtype,
                       name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, l, self.n_heads, dh)
        k = k.reshape(b, l, self.n_heads, dh)
        v = v.reshape(b, l, self.n_heads, dh)
        bq, bk = self.attention_blocks or DEFAULT_BLOCKS
        att = flash_attention(q, k, v, causal=False, block_q=bq, block_k=bk)
        att = att.reshape(b, l, self.d_model).astype(self.dtype)
        att = nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                       name="attn_out")(att)
        if self.dropout_rate > 0.0:
            att = nn.Dropout(self.dropout_rate, deterministic=not train)(att)
        x = x + att

        h = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.d_ff, dtype=self.dtype, name="ffn_in")(h)
        y = nn.gelu(y)
        y = nn.Dense(self.d_model, dtype=self.dtype, name="ffn_out")(y)
        if self.dropout_rate > 0.0:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return x + y


class ViT(nn.Module):
    """images [B, H, W, C] → logits [B, num_classes] (fp32).

    ``pool='cls'`` prepends a learned class token; ``pool='gap'`` mean-pools
    the patch tokens (both static-shape). Defaults are ViT-S/16-ish scaled
    down; pass ``dtype=jnp.bfloat16`` for MXU-fed training (params stay
    fp32, logits are fp32 — same mixed-precision contract as ResNet50).
    """

    num_classes: int
    patch: int = 16
    d_model: int = 384
    n_layers: int = 12
    n_heads: int = 6
    d_ff: int = 1536
    pool: str = "gap"                  # 'gap' | 'cls'
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    attention_blocks: Optional[tuple] = None
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.pool not in ("gap", "cls"):
            raise ValueError(f"pool must be 'gap' or 'cls', got {self.pool!r}")
        b, hh, ww, c = x.shape
        if hh % self.patch or ww % self.patch:
            raise ValueError(
                f"image {hh}x{ww} not divisible by patch {self.patch}")
        x = nn.Conv(self.d_model, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, name="patchify")(x.astype(self.dtype))
        n_tok = (hh // self.patch) * (ww // self.patch)
        x = x.reshape(b, n_tok, self.d_model)

        if self.pool == "cls":
            cls = self.param("cls_token", nn.initializers.zeros,
                             (1, 1, self.d_model))
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, self.d_model)).astype(
                    self.dtype), x], axis=1)
            n_tok += 1
        pos = self.param("pos_emb", nn.initializers.normal(0.02),
                         (n_tok, self.d_model))
        x = x + pos.astype(self.dtype)[None]
        if self.dropout_rate > 0.0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)

        block_cls = nn.remat(ViTEncoderBlock) if self.remat \
            else ViTEncoderBlock
        for i in range(self.n_layers):
            x = block_cls(
                d_model=self.d_model, n_heads=self.n_heads, d_ff=self.d_ff,
                dtype=self.dtype, dropout_rate=self.dropout_rate,
                attention_blocks=self.attention_blocks, train=train,
                name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = x[:, 0] if self.pool == "cls" else jnp.mean(x, axis=1)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x).astype(jnp.float32)

"""Decoder-only Transformer LM — the long-context flagship.

Beyond-reference model family: the reference's sequence model is an LSTM
seq2seq (examples/seq2seq, SURVEY.md §2.6 records sequence parallelism as
absent upstream). This LM is where the rebuild's long-context machinery
composes into one model:

* **flash attention** (`ops.flash_attention`) — the Pallas fused kernel —
  as the default attention;
* **ring attention** (`parallel.ring_attention`) when the sequence axis is
  sharded over the mesh (``attention='ring'`` + ``seq_axis``): KV blocks
  rotate over the ICI ring via ``ppermute``, sequence length scales with
  the number of chips;
* **expert-parallel MoE FFN** (`parallel.ExpertParallelMLP`) when
  ``moe_experts_per_device > 0``: the FFN becomes a Switch layer with
  experts sharded over ``expert_axis``.

Plain usage (no sharded axes) is a standard pre-LN causal LM usable under
``pjit`` data parallelism; the sharded variants run under ``shard_map``.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from chainermn_tpu.ops.flash_attention import (DEFAULT_BLOCKS,
                                               flash_attention)
from chainermn_tpu.parallel.expert_parallel import ExpertParallelMLP
from chainermn_tpu.parallel.ring_attention import (
    local_attention_reference,
    ring_attention,
    ring_flash_attention,
)
from chainermn_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
    TensorParallelMLP,
    pmax_stop_gradient,
    vocab_parallel_cross_entropy,
)
from chainermn_tpu.parallel.ulysses import ulysses_attention
from chainermn_tpu.ops.rotary import apply_rope, apply_rope_bhld

__all__ = ["TransformerLM", "TransformerBlock", "generate",
           "lm_loss_with_aux", "tp_lm_loss", "bhld_to_blhd_params"]


class TransformerBlock(nn.Module):
    """Pre-LN block: causal attention + (dense | MoE) FFN.

    ``decode=True`` PRECONDITION: a multi-token apply (l > 1) is a PREFILL
    and, by default, requires an EMPTY cache — it attends only within the
    slab, so any previously cached tokens would be silently ignored
    (``pos`` is traced and cannot be asserted). ``generate()`` follows
    this contract.

    ``chunked_prefill=True`` lifts that restriction for the serving
    layer: an l > 1 apply at pos > 0 writes the slab at its true cache
    positions and attends over the FULL cache (prefix + slab) under an
    absolute-position causal mask, so a prompt can stream in as
    fixed-size chunks (serving/kv_cache.py::prefill_chunk_apply). The
    chunked contract assumes NO ring wrap during prefill (prompt length
    <= capacity — cache slot j holds absolute position j); garbage
    beyond each row's fill level is masked out, not read.
    """

    d_model: int
    n_heads: int
    d_ff: int
    n_kv_heads: Optional[int] = None   # < n_heads → GQA/MQA (flash path)
    dtype: Any = jnp.float32
    # 'flash' | 'ring' | 'ring_flash' | 'ulysses' | 'reference'
    attention: str = "flash"
    attention_window: Optional[int] = None  # sliding window (flash path)
    attention_blocks: Optional[tuple] = None  # (block_q, block_k) tune
    pos_emb: str = "learned"           # 'learned' (handled by the LM) | 'rope'
    rope_theta: float = 10000.0
    seq_axis: Optional[str] = None     # mesh axis for 'ring'
    tp_axis: Optional[str] = None      # Megatron-style intra-op TP axis
    moe_experts_per_device: int = 0
    expert_axis: str = "expert"
    capacity_factor: float = 1.25
    moe_top_k: int = 1                 # 1 = Switch, 2 = GShard top-2
    decode: bool = False               # single-token KV-cache decoding
    chunked_prefill: bool = False      # l > 1 decode applies may start at
    #                                    pos > 0 and attend prefix + slab
    #                                    (serving chunk path; see docstring)
    max_len: int = 2048                # cache capacity when decode=True
    qkv_layout: str = "blhd"           # 'bhld': head-major attention
    #                                    tensors end to end — projection
    #                                    einsums emit [B, H, L, D], the
    #                                    flash kernels consume it as a free
    #                                    reshape, and the ~15 ms/step of
    #                                    layout-pivot copies disappear
    #                                    (docs/lm_roofline.md §5; flash
    #                                    path only, no decode/tp)

    @nn.compact
    def __call__(self, x, pos_offset=0):
        b, l, d = x.shape
        dh = self.d_model // self.n_heads

        h = nn.LayerNorm(dtype=self.dtype)(x)
        hkv = self.n_kv_heads or self.n_heads
        if self.qkv_layout == "bhld":
            x = self._bhld_attention(x, h, b, l, d, dh, hkv, pos_offset)
            return self._ffn(x, b, l, d)
        n_heads, n_kv = self.n_heads, hkv  # per-shard head counts below
        if self.tp_axis is not None:
            # Megatron attention: heads sharded over the model axis —
            # column-parallel QKV (no collective), per-shard attention on
            # local heads, row-parallel out projection (one psum)
            if self.decode or self.moe_experts_per_device > 0:
                raise ValueError(
                    "tp_axis does not compose with decode or the MoE FFN")
            if self.attention not in ("flash", "reference"):
                raise ValueError(
                    "tp_axis supports the 'flash'/'reference' attention "
                    "paths")
            ntp = jax.lax.axis_size(self.tp_axis)
            if self.n_heads % ntp or hkv % ntp:
                raise ValueError(
                    f"heads ({self.n_heads}/{hkv}) must divide by the "
                    f"'{self.tp_axis}' axis size ({ntp})")
            n_heads, n_kv = self.n_heads // ntp, hkv // ntp
            q = ColumnParallelDense(self.d_model, self.tp_axis,
                                    use_bias=False, dtype=self.dtype,
                                    name="q_proj")(h)
            kv = ColumnParallelDense(2 * hkv * dh, self.tp_axis,
                                     use_bias=False, dtype=self.dtype,
                                     name="kv_proj")(h)
            k, v = jnp.split(kv, 2, axis=-1)
        elif hkv == self.n_heads:
            qkv = nn.Dense(3 * self.d_model, use_bias=False,
                           dtype=self.dtype, name="qkv")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:  # GQA/MQA: smaller KV projection
            if self.attention not in ("flash", "reference"):
                raise ValueError(
                    "n_kv_heads < n_heads is supported on the 'flash' and "
                    "'reference' attention paths")
            q = nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                         name="q_proj")(h)
            kv = nn.Dense(2 * hkv * dh, use_bias=False, dtype=self.dtype,
                          name="kv_proj")(h)
            k, v = jnp.split(kv, 2, axis=-1)
        q = q.reshape(b, l, n_heads, dh)
        k = k.reshape(b, l, n_kv, dh)
        v = v.reshape(b, l, n_kv, dh)
        if self.decode:
            # KV-cache step: x is a slab of l NEW tokens starting at the
            # cache fill level — l == 1 is autoregressive decoding, l > 1
            # is PREFILL (the whole prompt in one forward pass instead of
            # one sequential step per prompt token). Attention is a
            # [l, cached] product with causal masking inside the slab.
            if self.moe_experts_per_device > 0:
                raise ValueError("decode does not support the MoE FFN")
            ck = self.variable("cache", "k", jnp.zeros,
                               (b, self.max_len, hkv, dh), self.dtype)
            cv = self.variable("cache", "v", jnp.zeros,
                               (b, self.max_len, hkv, dh), self.dtype)
            idx = self.variable("cache", "idx",
                                lambda: jnp.zeros((), jnp.int32))
            # capacity comes from the SUPPLIED cache, not max_len: the
            # serving layer passes smaller ring-buffered pages
            # (serving/kv_cache.py) and writes wrap at `cap`
            cap = ck.value.shape[1]
            pos = idx.value
            # scalar cursor: generate()'s one-stream-per-row contract.
            # vector cursor [b]: serving slots — every row advances its
            # own position independently (continuous batching)
            per_slot = jnp.ndim(pos) == 1
            rows = (pos[:, None] if per_slot else pos) + jnp.arange(l)
            if self.pos_emb == "rope":
                q = apply_rope(q, rows, self.rope_theta)
                k = apply_rope(k, rows, self.rope_theta)
            start = pos % cap
            if self.chunked_prefill:
                # per-position scatter, not dynamic_update_slice: a chunk
                # whose window overhangs the page end would be CLAMPED to
                # cap - l and land at the wrong offset. Overhanging rows
                # (final-chunk padding — no wrap during prefill) drop.
                wrows = rows if per_slot else rows[None]
                safe = jnp.where(wrows < cap, wrows, cap)
                bidx = jnp.arange(b)[:, None]
                ck.value = ck.value.at[bidx, safe].set(
                    k.astype(self.dtype), mode="drop")
                cv.value = cv.value.at[bidx, safe].set(
                    v.astype(self.dtype), mode="drop")
            elif per_slot:
                ck.value = jax.vmap(
                    lambda c, u, s0: jax.lax.dynamic_update_slice(
                        c, u, (s0, 0, 0)))(
                    ck.value, k.astype(self.dtype), start)
                cv.value = jax.vmap(
                    lambda c, u, s0: jax.lax.dynamic_update_slice(
                        c, u, (s0, 0, 0)))(
                    cv.value, v.astype(self.dtype), start)
            else:
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k.astype(self.dtype), (0, start, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v.astype(self.dtype), (0, start, 0, 0))
            idx.value = pos + l
            if l > 1:
                # PREFILL slab. Default contract: nothing precedes it
                # (the cache starts empty), so attention is causal
                # self-attention over the slab itself. Flash path: no
                # dense [l, max_len] scores and no full-cache read — a
                # 32k-token prompt prefills at the training path's
                # memory cost. Reference models keep the reference
                # kernel so prefill logits are THE SAME PROGRAM as the
                # full forward (bitwise — the serving parity tests
                # depend on it).
                if self.chunked_prefill:
                    # CHUNKED prefill: the slab (already written above at
                    # its absolute positions) attends over the FULL cache
                    # — prefix + itself — under an absolute-position
                    # causal mask. Same einsum forms, scale, and f32
                    # casts as local_attention_reference: the only delta
                    # vs the monolithic slab is extra key lanes that are
                    # masked to exactly-zero softmax weight, which the
                    # zero-lane-absorption property (test_decode_bitwise)
                    # makes bitwise-invisible — chunked == monolithic,
                    # token for token AND cache byte for cache byte.
                    kc = ck.value.astype(jnp.float32)
                    vc = cv.value.astype(jnp.float32)
                    if hkv != self.n_heads:
                        kc = jnp.repeat(kc, self.n_heads // hkv, axis=2)
                        vc = jnp.repeat(vc, self.n_heads // hkv, axis=2)
                    s = jnp.einsum("bqhd,bkhd->bhqk",
                                   q.astype(jnp.float32), kc) * dh ** -0.5
                    keys = jnp.arange(cap)
                    # no-wrap contract: cache slot j holds absolute
                    # position j, so causality is just keys <= row; rows
                    # beyond each slot's fill hold garbage but only
                    # padding queries (ignored downstream) can see them
                    visible = keys <= rows[..., None]
                    if self.attention_window is not None:
                        visible &= keys > (rows[..., None]
                                           - self.attention_window)
                    vis = visible[:, None] if per_slot else visible[None, None]
                    s = jnp.where(vis, s, -jnp.inf)
                    att = jnp.einsum("bhqk,bkhd->bqhd",
                                     jax.nn.softmax(s, -1),
                                     vc).astype(q.dtype)
                elif self.attention == "reference":
                    kr, vr = k, v
                    if hkv != self.n_heads:
                        kr = jnp.repeat(kr, self.n_heads // hkv, axis=2)
                        vr = jnp.repeat(vr, self.n_heads // hkv, axis=2)
                    att = local_attention_reference(q, kr, vr, causal=True)
                else:
                    bq, bk = self.attention_blocks or DEFAULT_BLOCKS
                    att = flash_attention(q, k, v, causal=True, block_q=bq,
                                          block_k=bk,
                                          window=self.attention_window)
            else:
                kc = ck.value.astype(jnp.float32)
                vc = cv.value.astype(jnp.float32)
                if hkv != self.n_heads:
                    kc = jnp.repeat(kc, self.n_heads // hkv, axis=2)
                    vc = jnp.repeat(vc, self.n_heads // hkv, axis=2)
                # squeezed-q contractions: on XLA these are bitwise-equal
                # to the corresponding row of the full-forward [L, L]
                # attention; the q=1 "bqhd,bkhd->bhqk"/"bhqk,bkhd->bqhd"
                # pair is NOT (different reduction order). The serving
                # bitwise-parity guarantee lives or dies here —
                # docs/serving.md §numerics.
                s = jnp.einsum("bhd,bkhd->bhk",
                               q[:, 0].astype(jnp.float32),
                               kc) * dh ** -0.5
                row = rows[..., -1]              # [b] per-slot, else ()
                keys = jnp.arange(cap)
                # ring inversion: slot j holds token position
                # row - ((row - j) mod cap) — the newest position ≡ j
                # (mod cap) not exceeding row. Unwritten slots land
                # negative; wrapped-over history is unreachable by
                # construction. With cap == max_len and no wrap this
                # reduces exactly to the old `keys <= row` mask.
                kpos = row[..., None] - (row[..., None] - keys) % cap
                visible = kpos >= 0
                if self.attention_window is not None:
                    visible &= kpos > row[..., None] - self.attention_window
                vis = visible[:, None] if per_slot else visible[None, None]
                s = jnp.where(vis, s, -jnp.inf)
                att = jnp.einsum("bhk,bkhd->bhd",
                                 jax.nn.softmax(s, -1), vc)[:, None]
            # falls through to the SHARED projection/FFN tail below — the
            # decode path must never duplicate training-path math
        elif self.pos_emb == "rope":
            po = jnp.asarray(pos_offset)
            # scalar offset (sequence parallelism) or per-row [b] offset
            # (serving full-forward audit) — both yield global positions
            pos = (po[:, None] if po.ndim else po) + jnp.arange(l)
            q = apply_rope(q, pos, self.rope_theta)
            k = apply_rope(k, pos, self.rope_theta)
        if self.decode:
            pass  # att computed above from the KV cache
        elif (self.attention_window is not None
              and self.attention != "flash"):
            raise ValueError(
                "attention_window is supported on the 'flash' path")
        elif self.attention in ("ring", "ring_flash", "ulysses"):
            if self.seq_axis is None:
                raise ValueError(
                    f"attention={self.attention!r} requires seq_axis")
            seq_fn = {"ring": ring_attention,
                      "ring_flash": ring_flash_attention,
                      "ulysses": ulysses_attention}[self.attention]
            att = seq_fn(q, k, v, axis_name=self.seq_axis, causal=True)
        elif self.attention == "flash":
            bq, bk = self.attention_blocks or DEFAULT_BLOCKS
            att = flash_attention(q, k, v, causal=True, block_q=bq,
                                  block_k=bk, window=self.attention_window)
        else:
            if hkv != self.n_heads:
                k = jnp.repeat(k, self.n_heads // hkv, axis=2)
                v = jnp.repeat(v, self.n_heads // hkv, axis=2)
            att = local_attention_reference(q, k, v, causal=True)
        att = att.reshape(b, l, -1).astype(self.dtype)  # local heads if TP
        if self.tp_axis is not None:
            x = x + RowParallelDense(self.d_model, self.tp_axis,
                                     use_bias=False, dtype=self.dtype,
                                     name="attn_out")(att)
        else:
            x = x + nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                             name="attn_out")(att)
        return self._ffn(x, b, l, d)

    def _bhld_attention(self, x, h, b, l, d, dh, hkv, pos_offset):
        """Head-major attention: projections emit [B, H, L, Dh] directly
        (XLA folds the permutation into the matmul — measured free,
        2026-07-31), the flash kernel consumes/produces that layout with
        zero-cost reshapes, and the output projection contracts (h, e)
        straight back to [B, L, D]. No transpose copy exists anywhere on
        the attention path, forward or backward."""
        if (self.decode or self.tp_axis is not None
                or self.attention != "flash"):
            raise ValueError(
                "qkv_layout='bhld' supports the plain flash attention "
                "path (no decode, no tp_axis); use the default 'blhd' "
                "layout elsewhere")
        init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=0)
        hdt = h.astype(self.dtype)
        if hkv == self.n_heads:
            w = self.param("qkv_bhld", init,
                           (d, 3, self.n_heads, dh), jnp.float32)
            y = jnp.einsum("bld,dthe->tbhle", hdt, w.astype(self.dtype))
            q, k, v = y[0], y[1], y[2]
        else:
            wq = self.param("q_bhld", init,
                            (d, self.n_heads, dh), jnp.float32)
            wkv = self.param("kv_bhld", init,
                             (d, 2, hkv, dh), jnp.float32)
            q = jnp.einsum("bld,dhe->bhle", hdt, wq.astype(self.dtype))
            ykv = jnp.einsum("bld,dthe->tbhle", hdt,
                             wkv.astype(self.dtype))
            k, v = ykv[0], ykv[1]
        if self.pos_emb == "rope":
            po = jnp.asarray(pos_offset)
            pos = (po[:, None] if po.ndim else po) + jnp.arange(l)
            q = apply_rope_bhld(q, pos, self.rope_theta)
            k = apply_rope_bhld(k, pos, self.rope_theta)
        bq, bk = self.attention_blocks or DEFAULT_BLOCKS
        att = flash_attention(q, k, v, causal=True, block_q=bq,
                              block_k=bk, window=self.attention_window,
                              layout="bhld")
        wo = self.param("attn_out_bhld", nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=(0, 1)),
            (self.n_heads, dh, d), jnp.float32)
        return x + jnp.einsum("bhle,hed->bld", att.astype(self.dtype),
                              wo.astype(self.dtype))

    def _ffn(self, x, b, l, d):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.tp_axis is not None:
            x = x + TensorParallelMLP(self.d_ff, self.d_model, self.tp_axis,
                                      dtype=self.dtype, name="tp_ffn")(h)
        elif self.moe_experts_per_device > 0:
            y, aux = ExpertParallelMLP(
                hidden=self.d_ff,
                experts_per_device=self.moe_experts_per_device,
                axis_name=self.expert_axis,
                capacity_factor=self.capacity_factor,
                top_k=self.moe_top_k,
                dtype=self.dtype, name="moe",
            )(h.reshape(b * l, d))
            # surfaced through the 'losses' collection; see lm_loss_with_aux
            self.sow("losses", "moe_aux", aux,
                     reduce_fn=lambda a, b_: a + b_, init_fn=lambda: 0.0)
            x = x + y.reshape(b, l, d)
        else:
            y = nn.Dense(self.d_ff, dtype=self.dtype, name="ffn_in")(h)
            y = nn.gelu(y)
            x = x + nn.Dense(self.d_model, dtype=self.dtype,
                             name="ffn_out")(y)
        return x


class TransformerLM(nn.Module):
    """Causal LM: tokens [B, L] → logits [B, L, vocab] (fp32).

    ``pos_offset`` supports sequence parallelism: with tokens sharded on a
    mesh axis, each shard passes its global position offset
    (``axis_index * L_local``) so positional embeddings stay global.
    """

    vocab: int
    d_model: int = 256
    n_heads: int = 8
    n_kv_heads: Optional[int] = None   # < n_heads → GQA/MQA
    n_layers: int = 4
    d_ff: int = 1024
    max_len: int = 2048
    pos_emb: str = "learned"           # 'learned' | 'rope'
    rope_theta: float = 10000.0
    attention_window: Optional[int] = None
    attention_blocks: Optional[tuple] = None
    dtype: Any = jnp.float32
    attention: str = "flash"
    seq_axis: Optional[str] = None
    tp_axis: Optional[str] = None      # Megatron intra-op TP (see block)
    lm_head_tp: bool = False           # column-parallel head: returns
    #                                    VOCAB-SHARDED logits; consume with
    #                                    vocab_parallel_cross_entropy (the
    #                                    full [B, L, V] never materializes)
    moe_experts_per_device: int = 0
    expert_axis: str = "expert"
    capacity_factor: float = 1.25
    moe_top_k: int = 1                 # 1 = Switch, 2 = GShard top-2
    decode: bool = False               # single-token KV-cache decoding
    chunked_prefill: bool = False      # serving chunk path (see block)
    qkv_layout: str = "blhd"           # 'bhld': pivot-free head-major
    #                                    attention (see TransformerBlock)
    remat: bool = False                # rematerialize each block's
    #                                    activations in backward (trade
    #                                    FLOPs for HBM at long L)
    return_hidden: bool = False        # skip the head: return the final
    #                                    post-LN hidden states (the fused
    #                                    head+CE loss applies lm_head
    #                                    itself — ops/fused_ce.py)

    def block_config(self) -> dict:
        """The per-layer TransformerBlock constructor kwargs — ONE source
        of truth shared by ``__call__`` and
        :func:`make_lm_fsdp_scan_loss` (a field added here reaches both;
        hand-copied kwargs in two sites silently diverged otherwise)."""
        return dict(
            d_model=self.d_model, n_heads=self.n_heads, d_ff=self.d_ff,
            n_kv_heads=self.n_kv_heads, dtype=self.dtype,
            attention=self.attention,
            attention_window=self.attention_window,
            attention_blocks=self.attention_blocks,
            pos_emb=self.pos_emb, rope_theta=self.rope_theta,
            seq_axis=self.seq_axis, tp_axis=self.tp_axis,
            moe_experts_per_device=self.moe_experts_per_device,
            expert_axis=self.expert_axis,
            capacity_factor=self.capacity_factor,
            moe_top_k=self.moe_top_k, decode=self.decode,
            chunked_prefill=self.chunked_prefill,
            max_len=self.max_len, qkv_layout=self.qkv_layout)

    @nn.compact
    def __call__(self, tokens, pos_offset=0):
        b, l = tokens.shape
        emb = nn.Embed(self.vocab, self.d_model,
                       dtype=self.dtype, name="tok_emb")(tokens)
        if self.pos_emb == "learned":
            pos = self.param(
                "pos_emb", nn.initializers.normal(0.02),
                (self.max_len, self.d_model))
            po = jnp.asarray(pos_offset)
            # scalar offset → one shared position row (broadcast over b);
            # vector [b] offset → per-row positions (serving slots sit at
            # independent depths). take() clips out-of-range indices,
            # which only retired/idle slots ever produce.
            idx = (po[:, None] if po.ndim else po) + jnp.arange(l)
            pe = jnp.take(pos, idx, axis=0).astype(self.dtype)
            x = emb + (pe if po.ndim else pe[None])
        else:  # 'rope': positions enter inside each block's attention
            x = emb
        block_cls = (nn.remat(TransformerBlock)
                     if self.remat and not self.decode else TransformerBlock)
        for i in range(self.n_layers):
            x = block_cls(**self.block_config(),
                          name=f"block_{i}")(x, pos_offset=pos_offset)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if self.return_hidden:
            return x
        if self.lm_head_tp:
            if self.tp_axis is None:
                raise ValueError("lm_head_tp requires tp_axis")
            logits = ColumnParallelDense(
                self.vocab, self.tp_axis, use_bias=False,
                dtype=jnp.float32, name="lm_head")(x)
        else:
            logits = nn.Dense(self.vocab, use_bias=False, dtype=jnp.float32,
                              name="lm_head")(x)
        return logits.astype(jnp.float32)


def stack_lm_blocks(params):
    """TransformerLM params → the scanned-stack layout: the homogeneous
    ``block_i`` subtrees stacked leaf-wise on a leading layer dim under
    ``"blocks"``, everything else passed through. This is the parameter
    layout :func:`make_lm_fsdp_scan_loss` consumes (and
    ``optimizers.fsdp_scan_apply`` scans over); invert with
    :func:`unstack_lm_blocks` for checkpoints, ``generate``, or any
    per-layer tooling."""
    names = sorted((k for k in params if k.startswith("block_")),
                   key=lambda k: int(k.split("_")[1]))
    if not names:
        raise ValueError("no block_i subtrees found — not TransformerLM "
                         "params?")
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *[params[k] for k in names])
    return {"blocks": stacked, **rest}


def unstack_lm_blocks(packed):
    """Inverse of :func:`stack_lm_blocks`: ``{"blocks": [L, ...], ...}``
    → the original ``block_i`` per-layer tree."""
    blocks = packed["blocks"]
    n = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    out = {k: v for k, v in packed.items() if k != "blocks"}
    for i in range(n):
        out[f"block_{i}"] = jax.tree_util.tree_map(
            lambda l, i=i: l[i], blocks)
    return out


def make_lm_fsdp_scan_loss(model):
    """A step-factory ``loss_fn`` running TransformerLM's layer stack
    through ``optimizers.fsdp_scan_apply`` — the COMPILER-FORCED FSDP
    memory bound (peak gathered params ≈ one layer, re-gathered in
    backward) on the flagship model, with the fused head+CE loss
    (ops/fused_ce.py — the full logits never materialize).

    The forward is rebuilt from the model's OWN flax submodules applied
    piecewise (``nn.Embed``/``TransformerBlock``/``nn.LayerNorm`` with
    the extracted param subtrees) — embed/blocks/LN numerics are those
    of ``model.apply`` exactly, and the head follows ``fused_lm_loss``'s
    convention (the dot takes ``h.dtype`` inputs with f32 accumulation;
    for bf16 models that differs from the unfused head's f32-input
    Dense, exactly as the fused path always has). Asserted against the
    replicated step by the oracle test
    (tests/optimizers_tests/test_zero.py). Use with the stacked layout
    and a mixed sharding tree::

        packed = stack_lm_blocks(params)
        shardings = dict(fsdp_shardings(packed, comm),
                         blocks=fsdp_stack_shardings(packed, comm)["blocks"])
        step, state = make_fsdp_train_step(
            None, optimizer, comm, packed,
            loss_fn=make_lm_fsdp_scan_loss(model),
            param_shardings=shardings)

    Supported envelope: plain data-axis FSDP under jit — no
    ``tp_axis``/``seq_axis`` (those need shard_map axis context), no
    MoE (the load-balancing 'losses' collection cannot thread through
    the scan), no decode. The scan body is always rematerialized (the
    FSDP memory floor), independent of ``model.remat``.
    """
    if getattr(model, "moe_experts_per_device", 0):
        raise ValueError("MoE models: the load-balancing aux cannot "
                         "thread through the scan; use the per-layer "
                         "model with lm_loss_with_aux")
    if model.tp_axis is not None or model.seq_axis is not None:
        raise ValueError("tp_axis/seq_axis need shard_map axis context; "
                         "the FSDP scan step runs under plain jit")
    if model.decode or model.lm_head_tp:
        raise ValueError("decode/lm_head_tp unsupported in the FSDP "
                         "scan loss")
    from chainermn_tpu.ops.fused_ce import fused_ce_head

    block = TransformerBlock(**model.block_config())
    embed = nn.Embed(model.vocab, model.d_model, dtype=model.dtype)
    ln_f = nn.LayerNorm(dtype=model.dtype)

    def loss_fn(_model, p, x, y, train=True, **kw):
        from chainermn_tpu.optimizers import fsdp_scan_apply

        h = embed.apply({"params": p["tok_emb"]}, x)
        if model.pos_emb == "learned":
            idx = jnp.arange(x.shape[1])
            h = h + jnp.take(p["pos_emb"], idx, axis=0).astype(
                model.dtype)[None]
        h = fsdp_scan_apply(
            lambda pi, h: block.apply({"params": pi}, h), p["blocks"], h)
        h = ln_f.apply({"params": p["LayerNorm_0"]}, h)
        b, l, d = h.shape
        w = p["lm_head"]["kernel"].astype(h.dtype)
        # vocab tile: the largest kernel-legal tile dividing the vocab
        # (the kernel requires vocab % block_v == 0, and its dW pass
        # needs a dividing sub-tile — a 128-multiple keeps Mosaic's
        # lane tiling happy)
        bv = next((t for t in (2048, 1024, 512, 256, 128)
                   if model.vocab % t == 0), None)
        if bv is None:
            raise ValueError(
                f"vocab {model.vocab} has no 128-multiple tile divisor "
                "<= 2048; pad the vocabulary to a multiple of 128 for "
                "the fused-CE head")
        loss, acc = fused_ce_head(
            h.reshape(b * l, d), w, jnp.asarray(y, jnp.int32).reshape(-1),
            block_v=bv)
        return loss, (acc, {})

    return loss_fn


def bhld_to_blhd_params(model, params):
    """Convert a bhld-trained parameter tree to the blhd layout.

    The head-major einsum kernels are reshapes/concats of the Dense
    kernels the blhd path declares (same math, different factorization):
    ``qkv_bhld [d,3,h,e]`` → ``qkv/kernel [d,3·d_model]`` (q/k/v blocks
    concatenated the way ``jnp.split`` undoes), ``q_bhld``/``kv_bhld``
    likewise for GQA, ``attn_out_bhld [h,e,d]`` → ``attn_out/kernel
    [h·e,d]``. Everything else passes through. Use before
    :func:`generate` (the KV-cache decode path is blhd-only) or to hand
    a bhld-trained model to blhd-layout tooling.
    """
    d = model.d_model
    h = model.n_heads
    hkv = model.n_kv_heads or h
    e = d // h

    def convert_block(bp):
        out = {k: v for k, v in bp.items() if not k.endswith("_bhld")}
        if "qkv_bhld" in bp:
            w = jnp.asarray(bp["qkv_bhld"])          # [d, 3, h, e]
            out["qkv"] = {"kernel": jnp.concatenate(
                [w[:, t].reshape(d, h * e) for t in range(3)], axis=1)}
        if "q_bhld" in bp:
            out["q_proj"] = {"kernel":
                             jnp.asarray(bp["q_bhld"]).reshape(d, h * e)}
        if "kv_bhld" in bp:
            w = jnp.asarray(bp["kv_bhld"])           # [d, 2, hkv, e]
            out["kv_proj"] = {"kernel": jnp.concatenate(
                [w[:, t].reshape(d, hkv * e) for t in range(2)], axis=1)}
        if "attn_out_bhld" in bp:
            out["attn_out"] = {"kernel":
                               jnp.asarray(bp["attn_out_bhld"])
                               .reshape(h * e, d)}
        return out

    return {k: (convert_block(v) if k.startswith("block_") else v)
            for k, v in params.items()}


def generate(model, params, prompt, max_new_tokens: int,
             rng=None, temperature: float = 1.0, top_k: Optional[int] = None,
             eos_id: Optional[int] = None, pad_id: int = 0,
             use_cache: bool = True):
    """Autoregressive sampling over the serving KV cache.

    The prompt prefills ONCE (the only legal l > 1 apply — see
    :class:`TransformerBlock`'s decode precondition) into a
    ``serving/kv_cache.py`` page sized exactly to the stream, then
    decoding proceeds one token at a time against the cache — O(1)
    compiled programs regardless of length.

    model: the TRAINING TransformerLM (decode twin derived internally);
    prompt: int32 [B, Lp]; returns int32 [B, Lp + max_new_tokens].
    ``rng=None`` → greedy argmax; else categorical at ``temperature``
    (optionally truncated to the ``top_k`` highest logits). ``eos_id``
    enables per-sequence early stop: once a sequence samples it, every
    later position emits ``pad_id`` (shapes stay static — finished
    sequences idle through the remaining scan steps, the SPMD-friendly
    form of early exit).

    ``use_cache=False`` is the FULL-RECOMPUTE reference path: every step
    re-runs the complete forward over the growing prefix (one XLA
    program per prefix length — the cost the cache exists to delete).
    Both paths thread the SAME rng-split sequence, so at fixed rng the
    sampled tokens pin identical between them (tested); keep the slow
    path for auditing cache numerics, never for throughput.
    """
    if model.moe_experts_per_device > 0:
        raise ValueError("generate() does not support MoE models: the "
                         "decode path has no expert dispatch")
    if model.tp_axis is not None or model.lm_head_tp:
        raise ValueError("generate() runs the single-device decode path; "
                         "tp_axis/lm_head_tp models decode without TP "
                         "(clone with tp_axis=None, lm_head_tp=False and "
                         "gather the sharded weights)")
    if model.qkv_layout == "bhld":
        # the KV-cache decode path is blhd-only; fold the head-major
        # kernels back into Dense form (exact, see bhld_to_blhd_params)
        params = bhld_to_blhd_params(model, params)
        model = model.clone(qkv_layout="blhd")
    b, lp = prompt.shape
    total = lp + max_new_tokens
    if total > model.max_len:
        raise ValueError(
            f"prompt + max_new_tokens ({total}) exceeds max_len "
            f"({model.max_len})")
    prompt = jnp.asarray(prompt, jnp.int32)
    greedy = rng is None
    rng = jax.random.PRNGKey(0) if greedy else rng

    def sample(logits, rng):
        if greedy:
            return jnp.argmax(logits, -1).astype(jnp.int32)
        scaled = logits / jnp.maximum(temperature, 1e-6)
        if top_k is not None:
            kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
            scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
        return jax.random.categorical(rng, scaled).astype(jnp.int32)

    def mask_eos(nxt, done):
        if eos_id is None:
            return nxt, done
        nxt = jnp.where(done, jnp.int32(pad_id), nxt)
        return nxt, done | (nxt == eos_id)

    if max_new_tokens == 0:
        return prompt

    if not use_cache:
        # reference path: recompute the whole prefix each step (identical
        # rng threading to the cached path below — token-pinning contract)
        toks = prompt
        logits = model.apply({"params": params}, toks)[:, -1]
        rng, sub = jax.random.split(rng)
        tok = sample(logits, sub)
        done = (jnp.zeros((b,), bool) if eos_id is None
                else tok == eos_id)
        toks = jnp.concatenate([toks, tok[:, None]], axis=1)
        for _ in range(max_new_tokens - 1):
            logits = model.apply({"params": params}, toks)[:, -1]
            rng, sub = jax.random.split(rng)
            nxt, done = mask_eos(sample(logits, sub), done)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
            # 1-CORE SYNC: eager dispatch queues ahead; bound it per step
            nxt.block_until_ready()
        return toks

    from chainermn_tpu.serving.kv_cache import (decode_apply, init_cache,
                                                prefill_apply)

    dm = model.clone(decode=True)
    # page sized exactly to the stream: no ring wrap, and (with reference
    # attention) bitwise full-forward parity — tests/serving_tests
    cache0 = init_cache(model, b, total)

    # prefill: ONE forward over the whole prompt fills every layer's page
    # (lp sequential steps collapse into one compute-bound pass); the last
    # prompt position's logits seed the first sampled token
    logits_p, cache = prefill_apply(
        dm, params, cache0, prompt, jnp.full((b,), lp, jnp.int32),
        jnp.arange(b, dtype=jnp.int32))
    rng, sub = jax.random.split(rng)
    tok0 = sample(logits_p, sub)
    done0 = (jnp.zeros((b,), bool) if eos_id is None
             else tok0 == eos_id)

    def step(carry, _):
        cache, tok, rng, done = carry
        logits, cache = decode_apply(dm, params, cache, tok)
        rng, sub = jax.random.split(rng)
        nxt, done = mask_eos(sample(logits, sub), done)
        return (cache, nxt, rng, done), nxt

    # an empty scan (max_new_tokens == 1) returns the carry and 0 tokens
    (_, _, _, _), toks = jax.lax.scan(
        step, (cache, tok0, rng, done0), None, length=max_new_tokens - 1)
    return jnp.concatenate([prompt, tok0[:, None], toks.T], axis=1)


def tp_lm_loss(model, params, x, y, train=True, mutable=None,
               extra_vars=None, rngs=None):
    """Loss for ``lm_head_tp`` models: vocab-parallel cross-entropy over the
    sharded logits (communication O(B·L), the full vocab never gathers).
    Step-factory signature; accuracy is the global argmax assembled with
    pmax (the shard holding the global max logit contributes its index)."""
    from jax import lax

    if not getattr(model, "lm_head_tp", False):
        raise ValueError(
            "tp_lm_loss expects an lm_head_tp model (sharded logits); a "
            "replicated head would inflate the psum'd normalizer by the "
            "axis size and desynchronize gradients")
    variables = {"params": params, **(extra_vars or {})}
    logits = model.apply(variables, x, rngs=rngs)
    ax = model.tp_axis
    loss = vocab_parallel_cross_entropy(logits, y, ax).mean()
    # accuracy: global argmax = the shard holding the global max logit.
    # pmax has no differentiation rule; the metric needs no gradient, so
    # route it through the zero-cotangent custom_vjp
    vl = logits.shape[-1]
    lo = lax.axis_index(ax) * vl
    local_max = jnp.max(logits, -1)
    local_arg = (lo + jnp.argmax(logits, -1)).astype(jnp.float32)
    global_max = pmax_stop_gradient(local_max, ax)
    # the owning shard contributes its argmax (ties: highest shard wins)
    mine = local_max == global_max
    pred = pmax_stop_gradient(jnp.where(mine, local_arg, -1.0), ax)
    acc = jnp.mean((pred == y.astype(jnp.float32)).astype(jnp.float32))
    return loss, (acc, {})


def lm_loss_with_aux(model, params, x, y, train=True, mutable=None,
                     extra_vars=None, rngs=None, aux_weight: float = 0.01):
    """Next-token CE + MoE load-balancing aux, in the step-factory loss
    signature (training/step.py). ``x`` = input tokens, ``y`` = targets."""
    import optax

    variables = {"params": params, **(extra_vars or {})}
    logits, state = model.apply(variables, x, mutable=["losses"], rngs=rngs)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    aux_tree = state.get("losses", {})
    aux = sum(jax.tree_util.tree_leaves(aux_tree)) if aux_tree else 0.0
    acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    return loss + aux_weight * aux, (acc, {})

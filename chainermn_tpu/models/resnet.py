"""ResNet family — the throughput workhorse (BASELINE configs #2 and #3).

Reference: examples/imagenet/train_imagenet.py trains ResNet-50 under
data-parallel allreduce_grad (SURVEY.md §3.1); the CIFAR config exercises
MultiNodeBatchNormalization. This is a fresh flax implementation, TPU-first:
NHWC layout (the TPU-native conv layout), bfloat16 compute with fp32 params
and batch statistics, and an optional communicator that turns every BN into
a cross-replica MultiNodeBatchNormalization.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.links import MultiNodeBatchNormalization

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv block (ResNet-18/34 and CIFAR ResNets)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckResNetBlock(nn.Module):
    """1-3-1 bottleneck block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet.

    ``comm`` switches every norm layer to cross-replica statistics
    (MultiNodeBatchNormalization) — the reference's CIFAR config. ``dtype``
    bfloat16 keeps the MXU fed; params and BN stats stay fp32.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    comm: Any = None
    dtype: Any = jnp.float32
    small_inputs: bool = False   # CIFAR stem: 3x3 conv, no maxpool
    space_to_depth: bool = False  # MXU-friendly stem (see __call__)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        # both branches pin identical momentum/epsilon so toggling
        # cross-replica statistics is the ONLY difference between them
        if self.comm is not None:
            norm = functools.partial(
                MultiNodeBatchNormalization,
                comm=self.comm, use_running_average=not train,
                decay=0.9, eps=1e-5, dtype=self.dtype,
            )
        else:
            norm = functools.partial(
                nn.BatchNorm, use_running_average=not train,
                momentum=0.9, epsilon=1e-5, dtype=self.dtype,
            )

        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        elif self.space_to_depth:
            # A 7x7/s2 conv on 3 channels feeds the 128-lane MXU 3 lanes at
            # a time. Space-to-depth(2) reshapes [H,W,3] -> [H/2,W/2,12] and
            # a 4x4/s1 conv over it covers an 8x8/s2 input window — a
            # superset of the 7x7/s2 receptive field at 4x the MXU packing.
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    f"space_to_depth stem needs even H and W, got {(h, w)}; "
                    "pad/resize the input or set space_to_depth=False"
                )
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2,
                                                      4 * c)
            x = conv(self.num_filters, (4, 4), padding=[(1, 2), (1, 2)],
                     name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckResNetBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckResNetBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckResNetBlock)


def CifarResNet(num_classes: int = 100, depth: int = 20, comm=None,
                dtype=jnp.float32):
    """CIFAR-style ResNet (6n+2 layers, 3 stages) with optional
    cross-replica BN — BASELINE config #3's model."""
    assert (depth - 2) % 6 == 0, "depth must be 6n+2"
    n = (depth - 2) // 6
    return ResNet(
        stage_sizes=[n, n, n], block_cls=ResNetBlock,
        num_classes=num_classes, num_filters=16, comm=comm,
        dtype=dtype, small_inputs=True,
    )

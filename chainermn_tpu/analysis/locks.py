"""Concurrency pass: lock orderings and blocking calls (DL115 / DL116).

The fleet router, async snapshot writer, and serving frontend are the
repo's three multi-threaded planes, and they share one discipline
(docs/serving.md): locks protect *bookkeeping*, never *waiting*. This
pass verifies both halves of that discipline whole-program:

**DL115 lock-order-inversion** — walk every function with the set of
locks held (``with lock:`` scopes plus unbounded ``.acquire()`` calls),
following resolved calls through the :class:`~.callgraph.Project` to
:data:`~.callgraph.DEFAULT_CALL_DEPTH`. Every nested acquisition adds a
*held-before* edge; a cycle in that graph means two threads can grab
the same pair of locks in opposite orders and deadlock. A self-edge is
flagged only when the lock is provably a plain ``threading.Lock``
(non-reentrant re-entry is a guaranteed single-thread deadlock; for an
``RLock`` or an unknown constructor it's legal).

**DL116 blocking-call-under-lock** — while any lock is held, flag calls
that can block indefinitely: unbounded future/mailbox waits
(``.get()``/``.result()``/``.wait()`` with the same receiver-name and
deadline rules as DL111), unbounded thread ``.join()``, object-plane
collectives (pickle over the network), and ``barrier()`` (a cross-rank
rendezvous under a local lock couples lock latency to the slowest
rank). Bounded waits pass — slicing a wait at a deadline under a lock
is the router's own probe pattern. ``Condition.wait()`` on the lock
being held is NOT flagged: that wait *releases* the lock; it is the
standard condition-variable idiom.

Lock identity is intentionally name-structural, not alias-precise:

* ``self.X`` in a method of class ``C``       → ``("cls", module:C, X)``
* a module-level ``X = threading.Lock()``     → ``("mod", module, X)``
* a local ``X = threading.Lock()``            → ``("loc", qualname, X)``
* any other receiver ``r.X``                  → ``("obj", r, X)``

Two ``rep.lock`` expressions on different replicas therefore alias to
one identity. That is the useful direction for an ORDERING property:
per-instance locks of one class form one rank in the ordering, so
taking two instances' locks in both orders still shows up as a cycle.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from chainermn_tpu.analysis.ast_passes import (
    OBJ_PLANE_CALLS,
    _callee_name,
    _is_unbounded_wait,
    _wait_receiver_name,
    _WAIT_RECEIVER_HINTS,
)
from chainermn_tpu.analysis.callgraph import (
    DEFAULT_CALL_DEPTH,
    FunctionInfo,
    Project,
    _attr_chain,
)
from chainermn_tpu.analysis.core import Finding, Rule, register

_DOC = "docs/static_analysis.md"

#: threading/multiprocessing constructors that create a lock object
LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore"}

#: name fragments that mark a receiver as a lock even without seeing
#: its constructor (cross-module attributes)
_LOCK_NAME_HINTS = ("lock", "mutex")

#: thread-ish receiver fragments for the unbounded-join check
_JOIN_RECEIVER_HINTS = ("thread", "worker", "proc", "writer")

LockId = Tuple[str, str, str]


def _lock_ctor_name(value: ast.expr) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` → ``"Lock"``, else None."""
    if not isinstance(value, ast.Call):
        return None
    chain = _attr_chain(value.func)
    if chain and chain[-1] in LOCK_CTORS:
        return chain[-1]
    return None


def _name_is_lockish(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in _LOCK_NAME_HINTS)


class LockAnalysis:
    """Shared traversal for DL115/DL116 over one project."""

    def __init__(self, project: Project,
                 depth: int = DEFAULT_CALL_DEPTH):
        self.project = project
        self.depth = depth
        # ("cls", module:Class, attr) → ctor name, when seen
        self.ctors: Dict[LockId, str] = {}
        self._mod_locks: Dict[str, Set[str]] = {}
        self._harvest()
        # DL115 state
        self.edges: Dict[LockId, Set[LockId]] = {}
        self.anchors: Dict[Tuple[LockId, LockId],
                           Tuple[str, int, str]] = {}
        # DL116 findings accumulate during the same walk
        self.blocking: List[Finding] = []
        self._blocked_sites: Set[Tuple[str, int]] = set()
        self._local_lock_memo: Dict[str, Dict[str, str]] = {}

    # -- lock discovery ---------------------------------------------------

    def _harvest(self) -> None:
        for mod in self.project.modules.values():
            mod_locks: Set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    ctor = _lock_ctor_name(node.value)
                    if ctor is None:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            mod_locks.add(t.id)
                            self.ctors[("mod", mod.name, t.id)] = ctor
            self._mod_locks[mod.name] = mod_locks
            for ci in mod.classes.values():
                key_cls = f"{mod.name}:{ci.name}"
                for meth in ci.methods.values():
                    for n in ast.walk(meth.node):
                        if not isinstance(n, ast.Assign):
                            continue
                        ctor = _lock_ctor_name(n.value)
                        if ctor is None:
                            continue
                        for t in n.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                self.ctors[("cls", key_cls, t.attr)] \
                                    = ctor

    def _local_locks(self, func: FunctionInfo) -> Dict[str, str]:
        cached = self._local_lock_memo.get(func.qualname)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        for n in ast.walk(func.node):
            if isinstance(n, ast.Assign):
                ctor = _lock_ctor_name(n.value)
                if ctor is None:
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = ctor
        self._local_lock_memo[func.qualname] = out
        return out

    def lock_id(self, expr: ast.expr, func: FunctionInfo,
                local_locks: Dict[str, str]) -> Optional[LockId]:
        """Identity of a lock expression, or None when the expression
        is not recognizably a lock."""
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in local_locks:
                lid = ("loc", func.qualname, name)
                self.ctors.setdefault(lid, local_locks[name])
                return lid
            if name in self._mod_locks.get(func.module, ()):
                return ("mod", func.module, name)
            if _name_is_lockish(name):
                return ("loc", func.qualname, name)
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and func.cls:
                lid = ("cls", f"{func.module}:{func.cls}", attr)
                if lid in self.ctors or _name_is_lockish(attr):
                    return lid
                return None
            if not _name_is_lockish(attr):
                return None
            recv_chain = _attr_chain(expr.value)
            recv = recv_chain[-1] if recv_chain else "?"
            return ("obj", recv, attr)
        return None

    # -- the walk ---------------------------------------------------------

    def run(self) -> None:
        for qualname in sorted(self.project.functions):
            func = self.project.functions[qualname]
            self._walk_func(func, held=(), depth=self.depth,
                            stack=(qualname,), anchor=None)

    def _walk_func(self, func: FunctionInfo, held: Tuple[LockId, ...],
                   depth: int, stack: Tuple[str, ...],
                   anchor: Optional[Tuple[str, int, str]]) -> None:
        """Walk ``func``'s body with ``held`` locks. ``anchor``, when
        set, is the original (path, line, chain) call site in the
        FIRST function of the walk — interprocedural findings must be
        reported there, where the suppressing file can see them."""
        local_locks = self._local_locks(func)
        self._walk_stmts(func.node.body, func, held, depth, stack,
                         anchor, local_locks, None)

    def _walk_stmts(self, stmts: Sequence[ast.stmt], func: FunctionInfo,
                    held: Tuple[LockId, ...], depth: int,
                    stack: Tuple[str, ...],
                    anchor: Optional[Tuple[str, int, str]],
                    local_locks: Dict[str, str],
                    local_types: Dict[str, str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now = held
                for item in stmt.items:
                    lid = self.lock_id(item.context_expr, func,
                                       local_locks)
                    if lid is None and isinstance(item.context_expr,
                                                  ast.Call):
                        # ``with self._lock:`` vs ``with open(...)`` —
                        # an ``x.acquire_timeout()``-style helper or a
                        # Condition call; only plain lock expressions
                        # count as acquisitions
                        self._visit_calls(item.context_expr, func, now,
                                          depth, stack, anchor,
                                          local_locks, local_types)
                    if lid is not None:
                        self._acquire(now, lid, func, stmt.lineno,
                                      anchor)
                        now = now + (lid,)
                self._walk_stmts(stmt.body, func, now, depth, stack,
                                 anchor, local_locks, local_types)
                continue
            for name in ("body", "orelse", "finalbody"):
                blk = getattr(stmt, name, None)
                if isinstance(blk, list) and blk:
                    self._walk_stmts(blk, func, held, depth, stack,
                                     anchor, local_locks, local_types)
            for h in getattr(stmt, "handlers", []) or []:
                self._walk_stmts(h.body, func, held, depth, stack,
                                 anchor, local_locks, local_types)
            self._visit_header(stmt, func, held, depth, stack, anchor,
                               local_locks, local_types)

    def _visit_header(self, stmt: ast.stmt, func, held, depth, stack,
                      anchor, local_locks, local_types) -> None:
        """Visit the calls in a statement's own expressions (not its
        nested blocks, which :meth:`_walk_stmts` handles)."""
        for fieldname, value in ast.iter_fields(stmt):
            if fieldname in ("body", "orelse", "finalbody", "handlers"):
                continue
            vals = value if isinstance(value, list) else [value]
            for v in vals:
                if isinstance(v, ast.AST):
                    self._visit_calls(v, func, held, depth, stack,
                                      anchor, local_locks, local_types)

    def _visit_calls(self, root: ast.AST, func: FunctionInfo, held,
                     depth, stack, anchor, local_locks,
                     local_types) -> None:
        if not held:
            # nothing to learn outside a lock scope: edges need a held
            # lock, and callees are each walked as roots of their own
            return
        for n in ast.walk(root):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if not isinstance(n, ast.Call):
                continue
            self._visit_call(n, func, held, depth, stack, anchor,
                             local_locks, local_types)

    def _visit_call(self, call: ast.Call, func: FunctionInfo, held,
                    depth, stack, anchor, local_locks,
                    local_types) -> None:
        name = _callee_name(call)
        # explicit .acquire(): an ordering edge when unbounded
        if (name == "acquire" and isinstance(call.func, ast.Attribute)
                and _is_unbounded_wait(call)):
            lid = self.lock_id(call.func.value, func, local_locks)
            if lid is not None:
                self._acquire(held, lid, func, call.lineno, anchor)
                return
        if held:
            self._check_blocking(call, name, func, held, anchor)
        if local_types is None:
            local_types = self.project.local_types(func)
        resolved = self.project.resolve_call(call, func, local_types)
        if resolved is None or depth <= 0:
            return
        callee = resolved.qualname
        if callee in stack:
            return
        sub_anchor = anchor
        if held and sub_anchor is None:
            chain = resolved.name
            sub_anchor = (func.path, call.lineno, chain)
        elif held and sub_anchor is not None:
            sub_anchor = (sub_anchor[0], sub_anchor[1],
                          f"{sub_anchor[2]} -> {resolved.name}")
        self._walk_func(resolved, held, depth - 1, stack + (callee,),
                        sub_anchor)

    # -- DL115 edges ------------------------------------------------------

    def _acquire(self, held: Tuple[LockId, ...], lid: LockId,
                 func: FunctionInfo, line: int,
                 anchor: Optional[Tuple[str, int, str]]) -> None:
        site = anchor or (func.path, line, "")
        for h in held:
            self.edges.setdefault(h, set()).add(lid)
            self.anchors.setdefault((h, lid), site)

    # -- DL116 blocking ---------------------------------------------------

    def _check_blocking(self, call: ast.Call, name: Optional[str],
                        func: FunctionInfo, held: Tuple[LockId, ...],
                        anchor: Optional[Tuple[str, int, str]]) -> None:
        reason = None
        if name in OBJ_PLANE_CALLS:
            reason = (f"object-plane collective '{name}' (pickle over "
                      "the network)")
        elif name == "barrier":
            reason = ("cross-rank 'barrier()' — lock hold time becomes "
                      "the slowest rank's arrival time")
        elif name == "join" and _is_unbounded_wait(call) \
                and isinstance(call.func, ast.Attribute):
            recv = _wait_receiver_name_any(call)
            if recv and any(h in recv.lower()
                            for h in _JOIN_RECEIVER_HINTS):
                reason = f"unbounded '{recv}.join()'"
        else:
            recv = _wait_receiver_name(call)
            if recv is not None \
                    and any(h in recv.lower()
                            for h in _WAIT_RECEIVER_HINTS) \
                    and _is_unbounded_wait(call):
                # Condition.wait() on a HELD lock releases that lock —
                # the standard cv idiom, not a blocking hold
                if not (call.func.attr == "wait"
                        and any(h[2] == recv or h[2] == recv.lstrip("_")
                                for h in held)):
                    reason = (f"unbounded '{recv}.{call.func.attr}()' "
                              "wait")
        if reason is None:
            return
        if anchor is not None:
            path, line, chain = anchor
            msg = (f"call chain '{chain}' reaches {reason} at "
                   f"{func.path}:{call.lineno} while a lock acquired "
                   "here is still held")
        else:
            path, line = func.path, call.lineno
            msg = f"{reason} while holding a lock"
        key = (path, line)
        if key in self._blocked_sites:
            return
        self._blocked_sites.add(key)
        self.blocking.append(Finding(
            "DL116", path, line,
            f"{msg} — every other thread contending for the lock "
            "blocks for as long as the wait does (a dead peer makes "
            "that forever), freezing the whole plane. Move the wait "
            "outside the lock (snapshot state under the lock, wait "
            "after releasing, like checkpointing.AsyncSnapshotPlane) "
            f"or bound it with a timeout ({_DOC}#dl116)."))


def _wait_receiver_name_any(call: ast.Call) -> Optional[str]:
    """Terminal receiver name for any attribute call (no method-name
    filter — used for ``.join()``)."""
    recv = call.func.value
    if isinstance(recv, ast.Name):
        return recv.id
    if isinstance(recv, ast.Attribute):
        return recv.attr
    return None


def _fmt_lock(lid: LockId) -> str:
    kind, owner, name = lid
    if kind == "cls":
        return f"{owner.split(':', 1)[-1]}.{name}"
    if kind == "mod":
        return f"{owner}.{name}"
    if kind == "obj":
        return f"{owner}.{name}"
    return name


def _analysis_for(project: Project) -> LockAnalysis:
    cached = getattr(project, "_lock_analysis", None)
    if cached is None:
        cached = LockAnalysis(project)
        cached.run()
        project._lock_analysis = cached
    return cached


def check_lock_order_inversion(project: Project) -> List[Finding]:
    la = _analysis_for(project)
    findings: List[Finding] = []
    reported: Set[frozenset] = set()
    for a in sorted(la.edges, key=repr):
        for b in sorted(la.edges[a], key=repr):
            if a == b:
                # re-entry: only a deadlock for a plain Lock
                if la.ctors.get(a) == "Lock":
                    path, line, chain = la.anchors[(a, a)]
                    via = f" (via call chain '{chain}')" if chain else ""
                    findings.append(Finding(
                        "DL115", path, line,
                        f"non-reentrant lock '{_fmt_lock(a)}' is "
                        f"acquired again while already held{via} — "
                        "threading.Lock does not re-enter; this "
                        "thread deadlocks on itself. Use an RLock or "
                        "restructure so the inner path doesn't "
                        f"re-acquire ({_DOC}#dl115)."))
                continue
            if a not in la.edges.get(b, ()):  # need b→a too for a cycle
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            pa, la_line, ca = la.anchors[(a, b)]
            pb, lb_line, _cb = la.anchors[(b, a)]
            via = f" via '{ca}'" if ca else ""
            findings.append(Finding(
                "DL115", pa, la_line,
                f"lock-order inversion: '{_fmt_lock(a)}' is held while "
                f"acquiring '{_fmt_lock(b)}' here{via}, but "
                f"{pb}:{lb_line} acquires them in the opposite order — "
                "two threads interleaving those paths deadlock "
                "holding one lock each. Pick one global order "
                f"(docs/serving.md) and re-nest ({_DOC}#dl115)."))
    return findings


def check_blocking_call_under_lock(project: Project) -> List[Finding]:
    return list(_analysis_for(project).blocking)


register(Rule("DL115", "lock-order-inversion", f"{_DOC}#dl115",
              check_lock_order_inversion, kind="project"))
register(Rule("DL116", "blocking-call-under-lock", f"{_DOC}#dl116",
              check_blocking_call_under_lock, kind="project"))

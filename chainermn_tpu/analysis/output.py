"""dlint output layer: SARIF 2.1.0 emission and the findings baseline.

**SARIF** — one ``run`` with the full rule catalogue under
``tool.driver.rules`` and one ``result`` per finding, so CI viewers
(GitHub code scanning et al.) render findings inline. Paths are
emitted repo-relative with forward slashes, per the spec's
``uriBaseId`` convention.

**Baseline** — the ratchet that makes a whole-program linter adoptable
on a repo with pre-existing findings: ``--write-baseline`` records
today's findings as fingerprints; later runs with ``--baseline`` fail
only on findings NOT in the file, so new debt is blocked while old
debt burns down explicitly. Fingerprints are
``rule :: relative-path :: stripped-source-line-text :: occurrence-
index`` — anchored to the line's TEXT, not its number, so unrelated
edits above a finding don't churn the baseline; the occurrence index
disambiguates identical lines. A finding whose line text changes
deliberately re-surfaces, which is the behavior a ratchet wants.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from chainermn_tpu.analysis.core import RULES, Finding, Suppression

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
BASELINE_VERSION = 1


def _rel(path: str, root: Optional[str] = None) -> str:
    root = root or os.getcwd()
    try:
        rel = os.path.relpath(os.path.abspath(path), root)
    except ValueError:          # different drive (windows)
        rel = path
    if rel.startswith(".."):    # outside the root: keep as given
        rel = path
    return rel.replace(os.sep, "/")


# ---------------------------------------------------------------------------
# SARIF
# ---------------------------------------------------------------------------


def to_sarif(findings: Sequence[Finding],
             root: Optional[str] = None,
             suppressions: Optional[Sequence[Suppression]] = None
             ) -> dict:
    """A complete SARIF 2.1.0 log object for one lint run. When
    ``suppressions`` is given, the in-source ``# dlint: disable``
    comments the run honored are recorded under the run's
    ``properties.suppressions`` (path, line, rules, absorbed-finding
    count) so a SARIF consumer can audit what was silenced and why the
    result list is shorter than the raw finding count."""
    rules_meta = [
        {
            "id": rule.rule_id,
            "name": rule.name,
            "helpUri": rule.doc,
            "shortDescription": {"text": rule.name},
            "defaultConfiguration": {"level": "error"},
        }
        for rule_id, rule in sorted(RULES.items())
    ]
    index = {r["id"]: i for i, r in enumerate(rules_meta)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": _rel(f.path, root),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if f.rule in index:
            result["ruleIndex"] = index[f.rule]
        results.append(result)
    run: dict = {
        "tool": {
            "driver": {
                "name": "dlint",
                "informationUri": "docs/static_analysis.md",
                "rules": rules_meta,
            },
        },
        "originalUriBaseIds": {
            "SRCROOT": {"uri": "file:///" + _rel(
                root or os.getcwd(), "/").lstrip("/") + "/"},
        },
        "results": results,
    }
    if suppressions is not None:
        run["properties"] = {
            "suppressions": [
                {
                    "uri": _rel(s.path, root),
                    "line": s.line,
                    "rules": sorted(s.rules),
                    "hits": s.hits,
                }
                for s in sorted(suppressions,
                                key=lambda s: (s.path, s.line))
            ],
        }
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [run],
    }


def from_sarif(log: dict) -> Tuple[List[Finding], List[Suppression]]:
    """Inverse of :func:`to_sarif` up to path relativization: rebuild
    the findings (rule, uri, line, message) and recorded suppressions
    from a dlint SARIF log. Locations come back repo-relative — exactly
    what round-trip tests and CI tooling diffing two logs need."""
    if not isinstance(log, dict) or "runs" not in log:
        raise ValueError("not a SARIF log object")
    findings: List[Finding] = []
    suppressions: List[Suppression] = []
    for run in log["runs"]:
        for res in run.get("results", ()):
            loc = (res.get("locations") or [{}])[0]
            phys = loc.get("physicalLocation", {})
            uri = phys.get("artifactLocation", {}).get("uri", "")
            line = phys.get("region", {}).get("startLine", 1)
            findings.append(Finding(
                res.get("ruleId", ""), uri, int(line),
                res.get("message", {}).get("text", "")))
        for s in run.get("properties", {}).get("suppressions", ()):
            sup = Suppression(
                path=s.get("uri", ""), line=int(s.get("line", 0)),
                rules=set(s.get("rules", ())),
                start=int(s.get("line", 0)),
                end=int(s.get("line", 0)) + 1)
            sup.hits = int(s.get("hits", 0))
            suppressions.append(sup)
    return findings, suppressions


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def _line_text(path: str, line: int,
               cache: Dict[str, List[str]]) -> str:
    if path not in cache:
        try:
            with open(path, encoding="utf-8") as fh:
                cache[path] = fh.read().splitlines()
        except OSError:
            cache[path] = []
    lines = cache[path]
    if 1 <= line <= len(lines):
        return lines[line - 1].strip()
    return ""


def fingerprints(findings: Sequence[Finding],
                 root: Optional[str] = None) -> List[Tuple[Finding, str]]:
    """(finding, fingerprint) pairs; stable across line-number drift."""
    cache: Dict[str, List[str]] = {}
    counts: Dict[str, int] = {}
    out: List[Tuple[Finding, str]] = []
    # occurrence index assigned in (path, line, rule) order so two
    # identical lines fingerprint deterministically
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        base = "::".join((f.rule, _rel(f.path, root),
                          _line_text(f.path, f.line, cache)))
        n = counts.get(base, 0)
        counts[base] = n + 1
        out.append((f, f"{base}::{n}"))
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   root: Optional[str] = None) -> dict:
    data = {
        "version": BASELINE_VERSION,
        "tool": "dlint",
        "findings": sorted(fp for _, fp in fingerprints(findings, root)),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return data


def load_baseline(path: str) -> set:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a dlint baseline file")
    return set(data["findings"])


def filter_new(findings: Sequence[Finding], baseline: Iterable[str],
               root: Optional[str] = None) -> List[Finding]:
    """Findings whose fingerprint is NOT in the baseline — the only
    ones a baselined run gates on."""
    known = set(baseline)
    return [f for f, fp in fingerprints(findings, root)
            if fp not in known]

"""dlint AST passes: the distributed-correctness source rules.

Each pass is a function ``(tree, src, path) -> [Finding]`` registered in
:data:`chainermn_tpu.analysis.core.RULES`. The rules encode the failure
shapes this repo has actually hit or audits it has actually run:

* DL101 — a *collective* reachable under rank-dependent control flow is
  the classic deadlock shape: some ranks enter the collective, the rest
  never do, and everyone blocks (SURVEY.md §3.3's MPI order discipline).
* DL102 — eager-P2P channels are keyed ``(tag, src, dst)``
  (``XlaCommunicator._p2p_tag``); two subsystems registering the same
  key interleave their messages, and the ``eagergrad.*`` namespace is
  reserved for autograd's reverse transport (functions/eager_p2p.py).
* DL103 — two rank spaces exist: array-collective roots are communicator
  ranks (dense in ``[0, size)``), object-collective roots are *process*
  indices. Passing one where the other belongs addresses the wrong peer
  or exceeds the communicator (VERDICT r5 weak #6).
* DL104 — a loop dispatching compiled steps without a per-iteration sync
  piles up async executions until the collective rendezvous aborts
  (tests/conftest.py's 1-core rule; the productized round-5 audit).
* DL105 — the object plane converts a detected peer death into
  ``JobAbortedError`` (comm/object_plane.py's poison key + fail-fast
  probes). A ``try`` that swallows it around ``send_obj``/``recv_obj``/
  ``bcast_obj`` turns the bounded abort back into the infinite hang the
  resilience layer exists to prevent (docs/fault_tolerance.md).

Known limits, by design (documented in docs/static_analysis.md): the
passes are intra-file and intra-function — no cross-module call graph,
no dataflow beyond single-assignment taint — so they over-approximate
reachability (a flagged call may be dynamically dead) and miss
divergence routed through helper functions. Suppress intentional sites
with ``# dlint: disable=RULE`` plus a rationale.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from chainermn_tpu.analysis.core import Finding, Rule, register

_DOC = "docs/static_analysis.md"

# -- what counts as rank-dependent ------------------------------------------

#: attribute reads that differ per rank/process (sizes deliberately
#: excluded: size/inter_size/intra_size are equal on every rank)
RANK_ATTRS = {
    "rank", "inter_rank", "intra_rank", "global_index", "is_master",
    "process_index",
}

#: calls whose value differs per rank/process
RANK_CALLS = {"process_index", "axis_index"}

# -- what counts as a collective --------------------------------------------

#: symmetric collectives: EVERY rank of the communicator must call them
SYMMETRIC_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pbroadcast",
    "allreduce", "allreduce_grad", "allgather", "alltoall",
    "bcast", "bcast_data", "gather", "scatter", "barrier",
    "bcast_obj", "gather_obj", "allgather_obj", "allreduce_obj",
    "scatter_obj",
    "broadcast_one_to_all", "sync_global_devices", "process_allgather",
}

#: point-to-point: pairwise, so a rank-dependent branch is fine as long
#: as the *sibling* branch also communicates (the send/recv pattern)
P2P_CALLS = {"send", "recv", "send_obj", "recv_obj",
             "eager_send", "eager_recv"}

#: sync markers that retire a dispatched step (DL104)
SYNC_CALLS = {
    "float", "int", "asarray", "array", "block_until_ready",
    "device_get", "item", "tolist", "barrier", "sync_global_devices",
    "wait_until_ready", "effects_barrier", "copy_to_host_async",
}


def _callee_name(call: ast.Call) -> Optional[str]:
    """Terminal name of the called thing: ``comm.send`` -> ``send``."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _walk_excluding_defs(nodes: Iterable[ast.AST]):
    """Walk statements/expressions, NOT descending into nested function
    or class definitions (their bodies run at some other time)."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _contains_rank_source(node: ast.AST, tainted: Set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in RANK_ATTRS:
            return True
        if isinstance(n, ast.Call):
            name = _callee_name(n)
            if name in RANK_CALLS:
                return True
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
    return False


def _tainted_names(func_body: List[ast.stmt]) -> Set[str]:
    """Single-assignment taint: local names whose RHS reads a rank
    source. One pass, then a propagation sweep so chains like
    ``r = comm.rank; me = r`` taint both."""
    tainted: Set[str] = set()
    assigns: List[Tuple[Set[str], ast.AST]] = []
    for node in _walk_excluding_defs(func_body):
        targets: List[ast.expr] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        if value is None:
            continue
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if names:
            assigns.append((names, value))
    changed = True
    while changed:
        changed = False
        for names, value in assigns:
            if names <= tainted:
                continue
            if _contains_rank_source(value, tainted):
                tainted |= names
                changed = True
    return tainted


def _collective_calls(nodes: List[ast.stmt]) -> List[Tuple[str, ast.Call]]:
    out = []
    for n in _walk_excluding_defs(nodes):
        if isinstance(n, ast.Call):
            name = _callee_name(n)
            if name in SYMMETRIC_COLLECTIVES or name in P2P_CALLS:
                out.append((name, n))
    return out


def _function_scopes(tree: ast.AST):
    """Yield (body, is_module) for the module and each function —
    the taint scope DL101 analyzes within."""
    yield list(getattr(tree, "body", [])), True
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body, False


# ---------------------------------------------------------------------------
# DL101 — divergent collective under rank-dependent control flow
# ---------------------------------------------------------------------------


_TERMINATORS = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _terminates(stmts: List[ast.stmt]) -> bool:
    """Does the branch end by leaving the enclosing block? A terminating
    rank guard (``if rank == root: ...; return``) makes the code AFTER
    the If the implicit else branch — the fallthrough only runs on the
    other ranks."""
    return bool(stmts) and isinstance(stmts[-1], _TERMINATORS)


def _child_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """Statement lists nested directly under ``stmt`` (loop/with/try/if
    bodies), NOT descending into function or class definitions."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    blocks = []
    for name in ("body", "orelse", "finalbody"):
        b = getattr(stmt, name, None)
        if isinstance(b, list) and b:
            blocks.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        blocks.append(h.body)
    return blocks


def _check_branch(calls, other, path, findings):
    other_names = {n for n, _ in other}
    other_has_p2p = bool(other_names & P2P_CALLS)
    for name, call in calls:
        if name in SYMMETRIC_COLLECTIVES:
            # symmetric: every rank must reach the SAME collective —
            # the sibling branch must call it too
            ok = name in other_names
            shape = (f"collective '{name}' is only reached by some "
                     "ranks (the sibling branch never calls it)")
        else:
            # P2P: pairwise — the sibling branch (or, after a
            # terminating guard, the fallthrough) must communicate at
            # all (send<->recv pairing)
            ok = other_has_p2p
            shape = (f"point-to-point '{name}' has no matching "
                     "send/recv on the sibling path, so the peer "
                     "rank never enters the transport")
        if not ok:
            findings.append(Finding(
                "DL101", path, call.lineno,
                f"{shape}; ranks that skip it leave the others "
                "blocked in the rendezvous (deadlock). Hoist the call "
                "out of the rank-dependent branch, or make every "
                f"branch call it (see {_DOC}#dl101).",
            ))


def _visit_block(stmts, tainted, path, findings):
    for i, stmt in enumerate(stmts):
        if (isinstance(stmt, ast.If)
                and _contains_rank_source(stmt.test, tainted)):
            remainder = stmts[i + 1:]
            body_calls = _collective_calls(stmt.body)
            orelse_calls = _collective_calls(stmt.orelse)
            rem_calls = _collective_calls(remainder)
            _check_branch(
                body_calls,
                orelse_calls + (rem_calls if _terminates(stmt.body)
                                else []),
                path, findings)
            _check_branch(
                orelse_calls,
                body_calls + (rem_calls if _terminates(stmt.orelse)
                              else []),
                path, findings)
        for block in _child_blocks(stmt):
            _visit_block(block, tainted, path, findings)


def check_divergent_collective(tree, src, path) -> List[Finding]:
    findings: List[Finding] = []
    for body, _ in _function_scopes(tree):
        tainted = _tainted_names(body)
        _visit_block(body, tainted, path, findings)
    return findings


register(Rule("DL101", "divergent-collective", f"{_DOC}#dl101",
              check_divergent_collective))


# ---------------------------------------------------------------------------
# DL102 — eager-P2P channel-tag collision
# ---------------------------------------------------------------------------

_GRAD_NS = "eagergrad"


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _literal(node: Optional[ast.expr]):
    """The literal value of a Constant node (including a negated numeric
    one — ``-1`` parses as ``UnaryOp(USub, Constant(1))``), else None."""
    if isinstance(node, ast.Constant):
        return node.value
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, (int, float))):
        return -node.operand.value
    return None


def _arg_or_kw(call: ast.Call, pos: int, name: str) -> Optional[ast.expr]:
    kw = _kw(call, name)
    if kw is not None:
        return kw
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _enclosing_scope_id(func_of_line, lineno: int):
    return func_of_line.get(lineno, "<module>")


def check_channel_tag_collision(tree, src, path) -> List[Finding]:
    findings: List[Finding] = []
    # map each line to its innermost enclosing function (for scope
    # grouping: two sends in ONE function are sequential on an ordered
    # channel — fine; the same channel from two different scopes is a
    # concurrency hazard)
    func_of_line: Dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            for ln in range(node.lineno, end + 1):
                # innermost wins: later (deeper) defs overwrite
                func_of_line[ln] = f"{node.name}@{node.lineno}" \
                    if func_of_line.get(ln) is None or True else \
                    func_of_line[ln]
    # registrations: channel key -> list of (scope, call, kind)
    sends: Dict[tuple, List[tuple]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node)
        tag_node = None
        endpoint = None  # the literal dest/src/rank, if any
        kind = None
        if name in ("send", "recv"):
            ep_name = "dest" if name == "send" else "src"
            ep = _arg_or_kw(node, 1 if name == "send" else 0, ep_name)
            tag_node = _kw(node, "tag")
            if tag_node is None and name == "send" and len(node.args) > 2:
                tag_node = node.args[2]
            if tag_node is None and name == "recv" and len(node.args) > 1:
                tag_node = node.args[1]
            # gate: plain socket/generator .send/.recv carry neither a
            # tag nor a dest/src keyword — require one to claim the call
            if tag_node is None and not any(
                    kw.arg in ("dest", "src", "as_rank") for kw in
                    node.keywords):
                continue
            endpoint = _literal(ep)
            kind = "array"
        elif name in ("send_obj", "recv_obj"):
            ep = _arg_or_kw(node, 1 if name == "send_obj" else 0,
                            "dest" if name == "send_obj" else "src")
            tag_node = _arg_or_kw(node, 2 if name == "send_obj" else 1,
                                  "tag")
            endpoint = _literal(ep)
            kind = "obj"
        elif name in ("eager_send", "eager_recv"):
            # eager_send(x, comm, rank, tag=..) / eager_recv(comm, rank,
            # ..., tag=..) — both lower onto comm.send/recv channels,
            # so they share the "array" channel space
            ep = _arg_or_kw(node, 2 if name == "eager_send" else 1,
                            "rank")
            tag_node = _kw(node, "tag")
            endpoint = _literal(ep)
            kind = "eager"
        else:
            continue
        tag = _literal(tag_node) if tag_node is not None else (
            0 if _kw(node, "tag") is None and tag_node is None else None)
        if isinstance(tag, str) and tag.split(".")[0] == _GRAD_NS:
            findings.append(Finding(
                "DL102", path, node.lineno,
                f"tag {tag!r} enters the reserved '{_GRAD_NS}.*' channel "
                "namespace — autograd's reverse transport for "
                "eager_send/eager_recv rides it "
                "(functions/eager_p2p.py); user traffic there corrupts "
                f"backward transfers. Pick another tag ({_DOC}#dl102).",
            ))
            continue
        if tag is None or endpoint is None:
            continue  # not statically known — nothing to compare
        direction = "send" if name in ("send", "send_obj",
                                       "eager_send") else "recv"
        space = "obj" if kind == "obj" else "array"
        key = (space, direction, tag, endpoint)
        scope = func_of_line.get(node.lineno, "<module>")
        sends.setdefault(key, []).append((scope, node, kind))
    for (space, direction, tag, endpoint), regs in sends.items():
        if len(regs) < 2:
            continue
        scopes = {s for s, _, _ in regs}
        kinds = {k for _, _, k in regs}
        # same channel from two scopes, or mixed raw/autograd use of one
        # channel, is a collision; N calls in one scope are sequential
        # messages on one ordered channel — legitimate
        if len(scopes) < 2 and not (kinds == {"array", "eager"}):
            continue
        first = min(regs, key=lambda r: r[1].lineno)
        for scope, call, kind in regs:
            if call is first[1]:
                continue
            findings.append(Finding(
                "DL102", path, call.lineno,
                f"channel (tag={tag!r}, "
                f"{'dst' if direction == 'send' else 'src'}={endpoint}) "
                f"is already registered at line {first[1].lineno}"
                + (" by the autograd eager-P2P path"
                   if "eager" in kinds and kind != "eager" else "")
                + "; concurrent traffic on one ordered channel "
                "interleaves messages between consumers. Use a distinct "
                f"tag per subsystem ({_DOC}#dl102).",
            ))
    return findings


register(Rule("DL102", "channel-tag-collision", f"{_DOC}#dl102",
              check_channel_tag_collision))


# ---------------------------------------------------------------------------
# DL103 — root argument from the wrong rank space
# ---------------------------------------------------------------------------

#: roots here are COMMUNICATOR ranks, dense in [0, size)
ARRAY_ROOT_CALLS = {"bcast", "gather", "scatter", "bcast_data"}
#: roots here are PROCESS indices (the object plane's world)
OBJ_ROOT_CALLS = {"bcast_obj", "gather_obj", "scatter_obj"}

#: rank-space sources that are NOT communicator ranks
_NON_COMM_RANK = {"global_index", "inter_rank", "process_index"}
#: rank-space sources that are NOT process indices
_NON_PROC_RANK = {"rank", "global_index", "axis_index", "intra_rank"}


def _root_expr(call: ast.Call) -> Optional[ast.expr]:
    kw = _kw(call, "root")
    if kw is not None:
        return kw
    if len(call.args) > 1:
        return call.args[1]
    return None


def check_root_invariant(tree, src, path) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node)
        if name in ARRAY_ROOT_CALLS:
            bad_attrs, space, right = (
                _NON_COMM_RANK, "communicator-rank",
                "comm.rank (dense in [0, size)) or a literal below size")
        elif name in OBJ_ROOT_CALLS:
            bad_attrs, space, right = (
                _NON_PROC_RANK, "process-index",
                "comm.inter_rank / jax.process_index()")
        else:
            continue
        root = _root_expr(node)
        if root is None:
            continue
        lit = _literal(root)
        if isinstance(lit, int) and lit < 0:
            findings.append(Finding(
                "DL103", path, node.lineno,
                f"negative root {lit} passed to {name}() — roots are "
                f"{space} values, never negative ({_DOC}#dl103)."))
            continue
        for n in ast.walk(root):
            bad = None
            if isinstance(n, ast.Attribute) and n.attr in bad_attrs:
                bad = n.attr
            elif (isinstance(n, ast.Call)
                  and _callee_name(n) in bad_attrs):
                bad = _callee_name(n)
            if bad is not None:
                findings.append(Finding(
                    "DL103", path, node.lineno,
                    f"root of {name}() is derived from '{bad}', which is "
                    f"not a {space} value — on a sub-axis or multi-device-"
                    "per-process communicator it can exceed the valid "
                    f"root range or address the wrong peer. Use {right} "
                    f"({_DOC}#dl103)."))
                break
    return findings


register(Rule("DL103", "root-rank-space", f"{_DOC}#dl103",
              check_root_invariant))


# ---------------------------------------------------------------------------
# DL104 — step-dispatch loop without a per-iteration sync
# ---------------------------------------------------------------------------


#: factories RETURN a step function; calling one dispatches nothing
_FACTORY_PREFIXES = ("make_", "build_", "create_", "get_")


def _is_step_call(call: ast.Call) -> bool:
    name = _callee_name(call)
    if name is None:
        return False
    if name.startswith(_FACTORY_PREFIXES):
        return False
    if name.startswith("on_"):
        return False  # event hooks (chaos.on_step) dispatch no compute
    return (name in ("step", "step_fn", "train_step")
            or name.endswith("_step"))


def _has_sync(nodes: List[ast.stmt]) -> bool:
    for n in _walk_excluding_defs(nodes):
        if isinstance(n, ast.Call) and _callee_name(n) in SYNC_CALLS:
            return True
    return False


def check_unsynced_step_loop(tree, src, path) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        step_calls = [
            n for n in _walk_excluding_defs(node.body)
            if isinstance(n, ast.Call) and _is_step_call(n)
        ]
        if not step_calls:
            continue
        if _has_sync(node.body):
            continue
        first = min(step_calls, key=lambda c: c.lineno)
        findings.append(Finding(
            "DL104", path, first.lineno,
            "loop dispatches a compiled step with no per-iteration sync "
            "(float(metric), jax.block_until_ready, np.asarray, ...): "
            "async executions pile up until the collective rendezvous "
            "aborts the process (tests/conftest.py 1-CORE SYNC RULE; "
            "the round-5 suite flake). Pull a scalar or "
            f"block_until_ready inside the loop ({_DOC}#dl104)."))
    return findings


register(Rule("DL104", "unsynced-step-loop", f"{_DOC}#dl104",
              check_unsynced_step_loop))


# ---------------------------------------------------------------------------
# DL105 — unguarded object-plane call (handler swallows JobAbortedError)
# ---------------------------------------------------------------------------

#: object-plane entry points whose guarded waits raise JobAbortedError on
#: peer death / coordinator loss
OBJ_PLANE_CALLS = {
    "send_obj", "recv_obj", "bcast_obj", "gather_obj", "allgather_obj",
    "allreduce_obj", "scatter_obj",
}

#: exception names that catch JobAbortedError: itself, or any ancestor on
#: its MRO (JobAbortedError -> RuntimeError -> Exception -> BaseException)
_ABORT_CATCHERS = {
    "JobAbortedError", "RuntimeError", "Exception", "BaseException",
}


def _handler_catches_abort(handler: ast.ExceptHandler) -> bool:
    """Does this handler's type clause catch JobAbortedError? A bare
    ``except:`` does; so does any name on its MRO or a tuple containing
    one."""
    t = handler.type
    if t is None:
        return True  # bare except
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name in _ABORT_CATCHERS:
            return True
    return False


def _walk_statements(stmts: List[ast.stmt]):
    """Like :func:`_walk_excluding_defs`, but also skips defs appearing
    DIRECTLY in ``stmts`` (their bodies run at some other time)."""
    live = [s for s in stmts
            if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda))]
    return _walk_excluding_defs(live)


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """A handler swallows the abort when no path through its body leaves
    by raising — a ``raise`` anywhere in the body (re-raise or wrap)
    counts as propagating. Over-approximation: a conditional raise is
    treated as propagating."""
    for n in _walk_statements(handler.body):
        if isinstance(n, ast.Raise):
            return False
    return True


def check_unguarded_object_plane(tree, src, path) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        swallowing = [
            h for h in node.handlers
            if _handler_catches_abort(h) and _handler_swallows(h)
        ]
        if not swallowing:
            continue
        for n in _walk_statements(node.body):
            if not isinstance(n, ast.Call):
                continue
            name = _callee_name(n)
            if name not in OBJ_PLANE_CALLS:
                continue
            h = swallowing[0]
            catches = ("bare 'except:'" if h.type is None else
                       f"'except {ast.unparse(h.type)}' at line "
                       f"{h.lineno}")
            findings.append(Finding(
                "DL105", path, n.lineno,
                f"object-plane call '{name}' sits in a try whose "
                f"{catches} swallows JobAbortedError — the abort a "
                "watchdog or poison key raises when a peer dies. "
                "Swallowing it turns detected peer death back into a "
                "silent hang (the surviving ranks keep waiting at the "
                "next collective). Re-raise JobAbortedError, or narrow "
                f"the except clause ({_DOC}#dl105)."))
    return findings


register(Rule("DL105", "unguarded-object-plane-call", f"{_DOC}#dl105",
              check_unguarded_object_plane))


# ---------------------------------------------------------------------------
# DL106 — hand-rolled gradient collective bypassing GradReducer
# ---------------------------------------------------------------------------

#: raw reduction primitives a train step should route through a
#: GradReducer (pmean/all_gather excluded: metrics and param gathers)
GRAD_COLLECTIVES = {"psum", "psum_scatter"}

#: gradient producers: assignments whose RHS calls these taint targets
_GRAD_SOURCES = {"grad", "value_and_grad"}


def _grad_tainted_names(func: ast.AST) -> Set[str]:
    """Names holding gradients inside one step function's subtree
    (nested closures included — the scan/micro bodies gradients flow
    into). Sources are ``jax.grad``/``jax.value_and_grad`` results; for
    the 2-tuple ``value_and_grad`` unpack only the gradient half taints
    (the loss/aux half feeds metric psums legitimately). Propagates
    through assignments, for-loops, and comprehension binders."""
    tainted: Set[str] = set()
    flows: List[Tuple[Set[str], ast.AST]] = []
    for node in ast.walk(func):
        targets: List[ast.expr] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        elif isinstance(node, ast.For):
            targets, value = [node.target], node.iter
        elif isinstance(node, (ast.ListComp, ast.SetComp,
                               ast.GeneratorExp, ast.DictComp)):
            for comp in node.generators:
                names = {n.id for n in ast.walk(comp.target)
                         if isinstance(n, ast.Name)}
                if names:
                    flows.append((names, comp.iter))
            continue
        elif isinstance(node, ast.Call):
            # tree_map(lambda g: ..., grads): the mapped-over tree's
            # taint enters through the lambda's parameters
            lams = [a for a in node.args if isinstance(a, ast.Lambda)]
            others = ([a for a in node.args
                       if not isinstance(a, ast.Lambda)]
                      + [kw.value for kw in node.keywords])
            if lams and others:
                carrier = ast.Tuple(elts=others, ctx=ast.Load())
                for lam in lams:
                    names = {a.arg for a in lam.args.args}
                    if names:
                        flows.append((names, carrier))
            continue
        if value is None:
            continue
        src_kind = next(
            (_callee_name(n) for n in ast.walk(value)
             if isinstance(n, ast.Call)
             and _callee_name(n) in _GRAD_SOURCES), None)
        if src_kind is not None:
            grad_targets = targets
            if (src_kind == "value_and_grad" and len(targets) == 1
                    and isinstance(targets[0], ast.Tuple)
                    and len(targets[0].elts) == 2):
                grad_targets = [targets[0].elts[1]]
            for t in grad_targets:
                tainted |= {n.id for n in ast.walk(t)
                            if isinstance(n, ast.Name)}
            continue
        names = {n.id for t in targets for n in ast.walk(t)
                 if isinstance(n, ast.Name)}
        if names:
            flows.append((names, value))

    def _reads_tainted(expr: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in ast.walk(expr))

    changed = True
    while changed:
        changed = False
        for names, value in flows:
            if names <= tainted:
                continue
            if _reads_tainted(value):
                tainted |= names
                changed = True
    return tainted


def check_handrolled_grad_collective(tree, src, path) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if "step" not in node.name:
            continue
        tainted = _grad_tainted_names(node)
        if not tainted:
            continue
        for n in ast.walk(node):
            if not (isinstance(n, ast.Call)
                    and _callee_name(n) in GRAD_COLLECTIVES):
                continue
            exprs = list(n.args) + [kw.value for kw in n.keywords]
            if any(isinstance(x, ast.Name) and x.id in tainted
                   for e in exprs for x in ast.walk(e)):
                findings.append(Finding(
                    "DL106", path, n.lineno,
                    f"hand-rolled '{_callee_name(n)}' on a gradient "
                    "inside a train step bypasses the GradReducer "
                    "strategy registry: the reduction algorithm stops "
                    "being selectable (hierarchical/quantized/auto), "
                    "invisible to ReductionReport, and numerically "
                    "unaudited against the flat reference. Route it "
                    "through grad_reducer= / reducer.reduce() or "
                    "reducer.reduce_scatter_flat() "
                    f"({_DOC}#dl106)."))
    return findings


register(Rule("DL106", "handrolled-grad-collective", f"{_DOC}#dl106",
              check_handrolled_grad_collective))


# ---------------------------------------------------------------------------
# DL107 — stale-schedule-profile
# ---------------------------------------------------------------------------

#: ProfileDB lookups whose first argument is the topology (fingerprint)
_PROFILE_LOOKUPS = {"plan_for", "measured_for"}


def check_stale_schedule_profile(tree, src, path) -> List[Finding]:
    """A profile-DB lookup keyed by a HARD-CODED fingerprint string.

    The schedtune profile DB (docs/tuning.md) keys plans by
    ``Topology.fingerprint()`` — platform, device kind, per-tier sizes.
    ``db.plan_for("tpu:v5e/ici:4+dcn:2")`` pins the lookup to the
    machine the string was copied from: on any other mesh it either
    misses (silently untuned) or, worse, returns a plan tuned for
    different hardware, and bucket sizes/strategy mis-tune with no
    error. Derive the key from the live mesh —
    ``db.plan_for(Topology.from_comm(comm))`` — or let
    ``create_multi_node_optimizer(tune=...)`` resolve it, which also
    REFUSES a fingerprint mismatch at runtime. Intra-function only: a
    literal laundered through a variable is not tracked (documented
    limit, ``{_DOC}#dl107``).
    """
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _callee_name(node) in _PROFILE_LOOKUPS):
            continue
        arg = _arg_or_kw(node, 0, "topology")
        val = _literal(arg)
        if isinstance(val, str):
            findings.append(Finding(
                "DL107", path, node.lineno,
                f"profile lookup '{_callee_name(node)}' keyed by the "
                f"hard-coded topology fingerprint {val!r}: a profile "
                "tuned on one machine silently mis-tunes any other "
                "mesh. Build the key from the live communicator "
                "(Topology.from_comm(comm)) or use "
                "create_multi_node_optimizer(tune=...), which verifies "
                f"the fingerprint at runtime ({_DOC}#dl107)."))
    return findings


register(Rule("DL107", "stale-schedule-profile", f"{_DOC}#dl107",
              check_stale_schedule_profile))


# ---------------------------------------------------------------------------
# DL108 — decode-step-recompile
# ---------------------------------------------------------------------------

#: wrappers that compile their argument into a fresh executable
_JIT_WRAPPERS = {"jit", "pmap", "pjit"}


def _loop_induction_names(loop: ast.AST) -> Set[str]:
    """Names that take a new value every iteration: the ``for`` target,
    plus anything aug-assigned in the body (the ``while`` counter)."""
    names: Set[str] = set()
    if isinstance(loop, ast.For):
        for n in ast.walk(loop.target):
            if isinstance(n, ast.Name):
                names.add(n.id)
    for n in _walk_excluding_defs(loop.body):
        if isinstance(n, ast.AugAssign) and isinstance(n.target, ast.Name):
            names.add(n.target.id)
    return names


def _slice_bounded_by(node: ast.expr, names: Set[str]) -> bool:
    """True when ``node`` contains a Subscript whose *slice extent* (a
    ``lower``/``upper`` bound) reads one of ``names`` — the shape of the
    sliced value then changes every iteration. Plain indexing
    (``buf[i]``) keeps a fixed shape and is NOT flagged."""
    for n in ast.walk(node):
        if not isinstance(n, ast.Subscript):
            continue
        parts = [n.slice]
        if isinstance(n.slice, ast.Tuple):
            parts = list(n.slice.elts)
        for part in parts:
            if not isinstance(part, ast.Slice):
                continue
            for bound in (part.lower, part.upper):
                if bound is None:
                    continue
                for leaf in ast.walk(bound):
                    if isinstance(leaf, ast.Name) and leaf.id in names:
                        return True
    return False


def _loop_bound_names(loop: ast.AST) -> Set[str]:
    """Names (re)bound inside the loop body: assignment targets and
    nested ``def``s. A jitted program that *reads* one of these is a
    different program each iteration — compiling it per iteration is
    the point (autotune candidates, per-strategy kernels), not a bug."""
    names = _loop_induction_names(loop)
    for n in _walk_excluding_defs(loop.body):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(n.name)
    return names


def _jit_bound_names(tree: ast.AST) -> Set[str]:
    """Names assigned from a ``jit``/``pmap``/``pjit`` wrapper anywhere
    in the file — the compiled steps DL108's shape check applies to."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if any(isinstance(n, ast.Call)
               and _callee_name(n) in _JIT_WRAPPERS
               for n in ast.walk(node.value)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def check_decode_step_recompile(tree, src, path) -> List[Finding]:
    """A token loop that recompiles its step every iteration.

    The serving invariant (docs/serving.md#dl108): after warmup, a
    decode loop executes ONE compiled program per step — XLA executable
    reuse is where continuous batching's throughput comes from. Two
    source shapes silently break it:

    * building the executable inside the loop — ``f = jax.jit(step)``
      per iteration constructs a fresh wrapper whose trace cache starts
      empty, so every step retraces and recompiles. Exempt when the
      wrapped program reads a name bound in the loop (a *different*
      program per iteration — autotune candidates, per-strategy
      kernels — where per-iteration compiles are the point);
    * feeding a jit-bound step (``step = jax.jit(...)``) an argument
      whose *slice extent* is the loop counter — ``step(toks[:, :t])``
      changes shape every iteration, and shape-polymorphic dispatch
      means one compile per sequence length (the full-recompute decode
      that ``tools/bench_serve.py`` exists to measure against).

    Fix: hoist the ``jit`` out of the loop and decode from a
    fixed-capacity cache (``serving/kv_cache.py``) so every step sees
    the same shapes. Intra-file, like every pass here: a wrapper built
    in a helper module, or bound via anything but a plain assignment,
    is not tracked.
    """
    findings: List[Finding] = []
    jitted = _jit_bound_names(tree)
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        induction = _loop_induction_names(loop)
        rebound = _loop_bound_names(loop)
        for n in _walk_excluding_defs(loop.body):
            if not isinstance(n, ast.Call):
                continue
            name = _callee_name(n)
            if name in _JIT_WRAPPERS:
                reads = {leaf.id for a in n.args + [k.value
                                                    for k in n.keywords]
                         for leaf in ast.walk(a)
                         if isinstance(leaf, ast.Name)}
                if reads & rebound:
                    continue        # fresh program per iteration
                findings.append(Finding(
                    "DL108", path, n.lineno,
                    f"'{name}' called inside a loop builds a fresh "
                    "compiled wrapper every iteration — its trace cache "
                    "starts empty, so each step retraces and recompiles. "
                    "Hoist the wrapper above the loop and call the same "
                    f"object every iteration ({_DOC}#dl108)."))
            elif (name in jitted and induction
                  and any(_slice_bounded_by(arg, induction)
                          for arg in list(n.args)
                          + [kw.value for kw in n.keywords])):
                findings.append(Finding(
                    "DL108", path, n.lineno,
                    f"compiled step '{name}' is fed a slice bounded by "
                    "the loop counter: the argument shape grows every "
                    "iteration, so the step compiles once PER SEQUENCE "
                    "LENGTH instead of once. Decode from a "
                    "fixed-capacity KV cache (serving/kv_cache.py) so "
                    f"every step sees the same shapes ({_DOC}#dl108)."))
    return findings


register(Rule("DL108", "decode-step-recompile", f"{_DOC}#dl108",
              check_decode_step_recompile))

# ---------------------------------------------------------------------------
# DL109 — blocking-save-in-step-loop
# ---------------------------------------------------------------------------

#: constructors whose result is a SYNCHRONOUS checkpointer (save() runs
#: device-get + serialize + fsync + SHA-256 on the calling thread)
_CKPT_FACTORIES = {"create_multi_node_checkpointer",
                   "MultiNodeCheckpointer"}


def _async_plane_available() -> bool:
    """Is the async snapshot plane shipped alongside this analysis
    package? File-existence probe on purpose — importing
    ``chainermn_tpu.checkpointing`` would drag jax into a pass suite
    that deliberately runs on bare ASTs."""
    import os

    pkg = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "checkpointing", "async_plane.py")
    return os.path.exists(pkg)


def _ckpt_bound_names(tree: ast.AST) -> Set[str]:
    """Names assigned DIRECTLY from a synchronous-checkpointer
    constructor anywhere in the file (same intra-file tracking contract
    as :func:`_jit_bound_names`). Only the OUTERMOST call counts:
    ``plane = AsyncSnapshotPlane(MultiNodeCheckpointer(...))`` binds a
    plane, not a checkpointer — that IS the fix this rule points at."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if (isinstance(node.value, ast.Call)
                and _callee_name(node.value) in _CKPT_FACTORIES):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def check_blocking_save_in_step_loop(tree, src, path) -> List[Finding]:
    """A synchronous ``checkpointer.save(...)`` on the step path.

    The sync save spends device-get + serialize + fsync + SHA-256 on
    the step thread — at a checkpoint cadence dense enough to survive
    preemption, that stall dominates the step
    (docs/fault_tolerance.md#checkpoint-cadence). Flagged when a name
    bound from ``create_multi_node_checkpointer`` /
    ``MultiNodeCheckpointer`` has ``.save(...)`` called inside a
    ``for``/``while`` loop that ALSO dispatches a training step (a call
    to a jit-bound name, or an ``.update()`` method call) — a plain
    save loop (tests, offline conversion) is not a step loop and stays
    clean. Fix: wrap the checkpointer in
    ``checkpointing.AsyncSnapshotPlane`` and call ``plane.save(...)``
    (or extend the plane on the Trainer); names bound from
    ``AsyncSnapshotPlane(...)`` are not tracked, so the fixed code
    passes. The rule only fires when the async plane ships next to this
    package (``chainermn_tpu/checkpointing/``) — there is no fix to
    point at otherwise. Intra-file, like every pass here. Suppress a
    deliberate sync save (e.g. benchmarking the stall itself) with
    ``# dlint: disable=DL109``.
    """
    if not _async_plane_available():
        return []
    findings: List[Finding] = []
    ckpts = _ckpt_bound_names(tree)
    if not ckpts:
        return findings
    jitted = _jit_bound_names(tree)
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        calls = [n for n in _walk_excluding_defs(loop.body)
                 if isinstance(n, ast.Call)]
        steps = any(
            (_callee_name(n) in jitted)
            or (isinstance(n.func, ast.Attribute)
                and n.func.attr == "update")
            for n in calls)
        if not steps:
            continue
        for n in calls:
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "save"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in ckpts):
                findings.append(Finding(
                    "DL109", path, n.lineno,
                    f"synchronous '{n.func.value.id}.save(...)' inside "
                    "a step loop: the device-get + serialize + fsync + "
                    "SHA-256 all stall the step thread. Wrap the "
                    "checkpointer in checkpointing.AsyncSnapshotPlane "
                    "and save through the plane — the write pipeline "
                    "moves off the critical path and the stall drops "
                    "to a device-side copy dispatch "
                    f"({_DOC}#dl109)."))
    return findings


register(Rule("DL109", "blocking-save-in-step-loop", f"{_DOC}#dl109",
              check_blocking_save_in_step_loop))


# ---------------------------------------------------------------------------
# DL110 — per-token-host-sync
# ---------------------------------------------------------------------------

#: host materializers: calling one of these ON decode output pulls the
#: whole array across the device boundary
_HOST_PULLS = {"asarray", "device_get", "array"}

#: callee-name fragments that mark a decode dispatch... and the exempt
#: fixed path: ``decode_k`` returns int32 token IDS (4 bytes/token) —
#: pulling those is the fix DL110 points at, not the bug
_DECODE_FRAGMENT = "decode"
_DECODE_EXEMPT = "decode_k"


def _is_decode_dispatch(call: ast.Call) -> bool:
    name = _callee_name(call)
    return (name is not None and _DECODE_FRAGMENT in name
            and _DECODE_EXEMPT not in name)


def _strip_subscripts(node: ast.expr) -> ast.expr:
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def check_per_token_host_sync(tree, src, path) -> List[Finding]:
    """Full decode logits pulled to the host inside a token loop.

    The serving invariant DL108's sibling (docs/serving.md): the decode
    hot loop's device→host traffic must not scale with the vocabulary.
    ``np.asarray(steps.decode(cur))`` (or ``jax.device_get`` /
    ``np.array`` of the same) inside a ``for``/``while`` loop ships the
    ``[n_slots, vocab]`` f32 logits across PCIe once per generated
    token — the transfer the on-device sampler
    (``serving/sampling.py``) exists to eliminate. Flagged shapes:

    * a direct pull — ``np.asarray(steps.decode(cur))`` — including
      through subscripts (``np.asarray(steps.decode(cur)[0])`` pulls
      the whole buffer before slicing);
    * a pull of a name assigned from a decode dispatch in the SAME loop
      (single-assignment taint, as everywhere in this suite).

    NOT flagged: reducing on device first and pulling the result —
    ``np.asarray(jnp.argmax(steps.decode(cur), -1))`` moves int32 ids
    only — and any callee whose name contains ``decode_k``: the
    multi-token program already returns token ids, so materializing its
    output IS the fixed pattern. Parity oracles that legitimately
    compare full logit rows (bitwise tests) suppress with
    ``# dlint: disable=DL110`` plus a rationale.
    """
    findings: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()   # dedup nested-loop double walks
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        tainted: Set[str] = set()
        for n in _walk_excluding_defs(loop.body):
            if (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)
                    and _is_decode_dispatch(n.value)):
                tainted |= {t.id for t in n.targets
                            if isinstance(t, ast.Name)}
        for n in _walk_excluding_defs(loop.body):
            if not isinstance(n, ast.Call) or not n.args:
                continue
            if _callee_name(n) not in _HOST_PULLS:
                continue
            arg = _strip_subscripts(n.args[0])
            direct = isinstance(arg, ast.Call) and _is_decode_dispatch(arg)
            named = isinstance(arg, ast.Name) and arg.id in tainted
            if not (direct or named):
                continue
            key = (n.lineno, n.col_offset)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "DL110", path, n.lineno,
                f"'{_callee_name(n)}' materializes decode output on the "
                "host inside a token loop — the [n_slots, vocab] f32 "
                "logits cross PCIe once per generated token (vocab × 4 "
                "bytes/token; bench.py gates the decode path at ≤ 8). "
                "Sample on device (serving/sampling.py) and pull int32 "
                "ids via ServingStep.decode_k, or at least reduce "
                "on device first — np.asarray(jnp.argmax(...)) moves "
                f"ids only ({_DOC}#dl110)."))
    return findings


register(Rule("DL110", "per-token-host-sync", f"{_DOC}#dl110",
              check_per_token_host_sync))


# ---------------------------------------------------------------------------
# DL111 — blocking-rpc-in-router-loop
# ---------------------------------------------------------------------------

#: blocking-wait methods a dispatch loop can wedge on
_WAIT_METHODS = {"result", "get", "wait"}

#: receiver-name fragments that mark a future/mailbox wait (``fut``
#: covers ``future``/``futures``; ``mail`` covers ``mailbox``); plain
#: ``dict.get(key)``-style calls don't match because they carry a
#: positional argument, and ``os.path.join``-alikes use other methods
_WAIT_RECEIVER_HINTS = ("queue", "mail", "fut", "inbox", "mbox")


def _wait_receiver_name(call: ast.Call) -> Optional[str]:
    """Terminal receiver name of ``<recv>.result()/.get()/.wait()``:
    ``fut.result`` → ``fut``, ``self._mail.get`` → ``_mail``."""
    if not isinstance(call.func, ast.Attribute):
        return None
    if call.func.attr not in _WAIT_METHODS:
        return None
    recv = call.func.value
    if isinstance(recv, ast.Name):
        return recv.id
    if isinstance(recv, ast.Attribute):
        return recv.attr
    return None


def _is_unbounded_wait(call: ast.Call) -> bool:
    """Unbounded = no positional deadline and no ``timeout=`` kwarg (or
    an explicit ``timeout=None``). ``get_nowait()``, ``result(
    timeout=probe)``, and ``join(timeout=30)`` all pass."""
    if call.args:
        return False
    for kw in call.keywords:
        if kw.arg == "timeout":
            return (isinstance(kw.value, ast.Constant)
                    and kw.value.value is None)
    return True


def check_blocking_rpc_in_router_loop(tree, src, path) -> List[Finding]:
    """Unbounded future/mailbox wait inside a dispatch loop.

    The fleet-router discipline (docs/serving.md): every wait inside a
    ``for``/``while`` dispatch loop must carry a deadline, because the
    thing being waited on is another replica — and replicas die. One
    ``inbox.get()`` or ``fut.result()`` with no timeout turns a single
    replica death into a frozen fleet: the loop never comes back to the
    health sweep that would have re-queued the dead replica's work.
    Flagged shape: ``<recv>.result()/.get()/.wait()`` where the
    receiver name names a future or mailbox (``queue``/``mail``/
    ``fut``/``inbox``/``mbox``) and the call is unbounded — no
    positional deadline, no ``timeout=`` kwarg, or an explicit
    ``timeout=None``.

    NOT flagged: ``get_nowait()`` (never blocks), any wait with a
    finite ``timeout=``, waits on receivers that aren't futures or
    mailboxes, and waits outside loops (a one-shot join at teardown is
    not a dispatch loop). The fixed patterns are ``fleet/router.py``'s:
    drain mailboxes with ``get_nowait()`` + idle sleep, and slice
    future waits at ``RpcPolicy.probe_ms``.
    """
    findings: List[Finding] = []
    seen: Set[Tuple[int, int]] = set()   # dedup nested-loop double walks
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for n in _walk_excluding_defs(loop.body):
            if not isinstance(n, ast.Call):
                continue
            recv = _wait_receiver_name(n)
            if recv is None:
                continue
            if not any(h in recv.lower() for h in _WAIT_RECEIVER_HINTS):
                continue
            if not _is_unbounded_wait(n):
                continue
            key = (n.lineno, n.col_offset)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "DL111", path, n.lineno,
                f"'{recv}.{n.func.attr}()' blocks without a deadline "
                "inside a dispatch loop — if the producer is a dead "
                "replica this wait never returns and the loop never "
                "reaches the health sweep that would re-queue its "
                "work. Bound it: get_nowait() + idle sleep for "
                "mailboxes, or slice the wait at RpcPolicy.probe_ms "
                f"like fleet.Router.result ({_DOC}#dl111)."))
    return findings


register(Rule("DL111", "blocking-rpc-in-router-loop", f"{_DOC}#dl111",
              check_blocking_rpc_in_router_loop))


# ---------------------------------------------------------------------------
# DL112 — asymmetric-tier-collective
# ---------------------------------------------------------------------------

#: jax.lax-style collectives whose second argument / ``axis_name=``
#: kwarg names the mesh axis the traffic moves over
_AXIS_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pbroadcast",
}


def _declared_tier_names(tree: ast.AST) -> Set[str]:
    """Names of every ``Tier("<name>", ...)`` declared in the module
    (string-constant first argument or ``name=`` kwarg only — a
    variable tier name can't be checked statically)."""
    out: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and _callee_name(n) == "Tier":
            name = _literal(_arg_or_kw(n, 0, "name"))
            if isinstance(name, str):
                out.add(name)
    return out


def _axis_name_constants(node: Optional[ast.expr]) -> List[str]:
    """String-constant axis names in an axis_name argument: a bare
    string, or every string element of a tuple/list of them."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def check_asymmetric_tier_collective(tree, src, path) -> List[Finding]:
    """Collective over an axis the module's declared tiers don't name.

    The synthesis/tuning discipline (docs/tuning.md): a module that
    describes its machine as explicit ``Tier(...)`` levels has promised
    that ALL collective traffic moves over those tiers — that promise
    is what makes the per-tier cost model and the synthesized-program
    wire accounting (``program_wire_bytes``) truthful. A hard-coded
    ``lax.psum(x, 'dcn2')`` next to ``Tier('ici', ...)``/
    ``Tier('dcn', ...)`` declarations moves bytes over an axis the
    topology doesn't know: the tuner prices it at zero, the wire
    report under-counts, and a program validated against the declared
    tiers runs asymmetric traffic beside it. Flagged shape: a
    string-constant axis name (or tuple element) passed to a lax-style
    collective, in a module that declares at least one
    ``Tier("<name>", ...)``, where the axis is not a declared tier
    name.

    NOT flagged: modules with no ``Tier`` declarations (nothing is
    promised), non-constant axis names (the tier map resolving names
    at run time is the fixed pattern — synthesis/compiler.py routes
    every step through its ``_TierMap``), and axis names that match a
    declared tier.
    """
    tiers = _declared_tier_names(tree)
    if not tiers:
        return []
    findings: List[Finding] = []
    for n in ast.walk(tree):
        if (not isinstance(n, ast.Call)
                or _callee_name(n) not in _AXIS_COLLECTIVES):
            continue
        for axis in _axis_name_constants(_arg_or_kw(n, 1, "axis_name")):
            if axis in tiers:
                continue
            findings.append(Finding(
                "DL112", path, n.lineno,
                f"'{_callee_name(n)}' moves traffic over axis "
                f"{axis!r} but this module declares tiers "
                f"{sorted(tiers)} — collectives outside the declared "
                "topology escape the per-tier cost model and the "
                "synthesized-program wire accounting. Name the axis "
                "as a Tier, or resolve axes through the tier map at "
                "run time like synthesis/compiler.py "
                f"({_DOC}#dl112)."))
    return findings


register(Rule("DL112", "asymmetric-tier-collective", f"{_DOC}#dl112",
              check_asymmetric_tier_collective))


# ---------------------------------------------------------------------------
# DL117 — unbounded-retry-loop
# ---------------------------------------------------------------------------

#: callee names that mark one attempt against a remote peer — the
#: RPC/transport operations a retry loop is presumably absorbing
#: failures of (object-plane ops, coordinator KV primitives, generic
#: wire verbs)
_RETRY_RPC_CALLS = OBJ_PLANE_CALLS | {
    "try_recv_obj", "blocking_key_value_get",
    "blocking_key_value_get_bytes", "key_value_set",
    "key_value_set_bytes", "wait_at_barrier", "send", "recv",
    "rpc", "request", "urlopen",
}

#: calls that are bounding evidence on their own: the RpcPolicy retry
#: ladder (a loop sleeping the jittered ladder is policy-driven)
_BACKOFF_CALLS = {"backoff_ms", "backoffs_ms"}

#: clock reads whose presence in the loop marks deadline math
_CLOCK_CALLS = {"monotonic", "perf_counter"}

#: name fragments that mark an attempt/deadline bound when they appear
#: in a comparison — or on the receiver/name of a call — inside the loop
_BOUND_NAME_HINTS = ("deadline", "attempt", "budget", "waited",
                     "remaining", "left", "tries", "retries",
                     "policy", "exhausted")


def _retry_handler_swallows(handler: ast.ExceptHandler) -> bool:
    """For DL117 a handler bounds the loop if ANY path through it
    raises, returns, or breaks — each one exits the retry. Only a
    handler that always falls back into the loop (``pass``/
    ``continue``/log-and-go) swallows."""
    for n in _walk_statements(handler.body):
        if isinstance(n, (ast.Raise, ast.Return, ast.Break)):
            return False
    return True


def _names_in(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr


def _loop_is_bounded(loop: ast.While) -> bool:
    """Bounding evidence anywhere in the loop body (over-approximate on
    purpose — a misfire on bounded code is noise; the fix for a true
    positive is mechanical): a policy backoff call, a clock read
    (deadline math), or a comparison over an attempt/deadline-named
    quantity."""
    for n in _walk_excluding_defs(loop.body):
        if isinstance(n, ast.Call):
            name = _callee_name(n)
            if name in _BACKOFF_CALLS or name in _CLOCK_CALLS:
                return True
            # the OBJECT form of the same evidence: a method call on a
            # budget/policy-named receiver (``budget.exhausted()``,
            # ``pol.remaining_ms()`` — the fleet/transport.py retry
            # shape, where the bound lives behind an RpcPolicy budget
            # object instead of a literal count)
            for part in _names_in(n.func):
                if any(h in part.lower() for h in _BOUND_NAME_HINTS):
                    return True
        if isinstance(n, ast.Compare):
            for name in _names_in(n):
                if any(h in name.lower() for h in _BOUND_NAME_HINTS):
                    return True
    return False


def check_unbounded_retry_loop(tree, src, path) -> List[Finding]:
    """Retry-forever around an RPC/transport call.

    The resilience discipline (docs/fault_tolerance.md): every retry
    against a remote peer must be bounded by a deadline, an attempt
    cap, or the :class:`~chainermn_tpu.resilience.policy.RpcPolicy`
    backoff ladder — a bare ``while True: try: rpc() except: continue``
    retries against a DEAD coordinator forever, which is exactly the
    silent hang the watchdog/poison-key machinery exists to prevent.
    Flagged shape: a ``while True``-style loop (constant-true
    condition) whose try body calls an RPC/transport operation
    (``send_obj``/``recv_obj``/``try_recv_obj``/KV-store primitives/
    generic wire verbs) with a handler that always falls back into the
    loop, and NO bounding evidence in the loop body.

    NOT flagged: ``for`` loops and non-constant ``while`` conditions
    (inherently bounded); handlers that raise/return/break on any path
    (the exit is the bound); loops containing ``RpcPolicy.backoff_ms``/
    ``backoffs_ms`` calls, a ``time.monotonic()``/``perf_counter()``
    read (deadline math), a comparison over an attempt/deadline-
    named quantity, or a method call on a budget/policy-named receiver
    (the RpcPolicy budget-object form: ``budget.exhausted()``,
    ``pol.remaining_ms()``). The fixed patterns are ``comm/object_plane.py``'s
    ``_sliced_get`` (budget-sliced, raises on exhaustion) and
    ``fleet/transport.py``'s ack wait (per-attempt ``handoff_ack_ms``
    deadline under a ``max_attempts`` cap).
    """
    findings: List[Finding] = []
    for loop in ast.walk(tree):
        if not isinstance(loop, ast.While):
            continue
        test = loop.test
        if not (isinstance(test, ast.Constant) and test.value):
            continue                      # non-constant condition = bound
        if _loop_is_bounded(loop):
            continue
        for node in _walk_excluding_defs(loop.body):
            if not isinstance(node, ast.Try):
                continue
            if not any(_retry_handler_swallows(h) for h in node.handlers):
                continue
            for n in _walk_statements(node.body):
                if not isinstance(n, ast.Call):
                    continue
                name = _callee_name(n)
                if name not in _RETRY_RPC_CALLS:
                    continue
                findings.append(Finding(
                    "DL117", path, n.lineno,
                    f"'{name}' is retried in a 'while True' loop whose "
                    "handler always falls back into the loop, with no "
                    "deadline, attempt cap, or backoff in sight — "
                    "against a dead peer this retries forever, the "
                    "silent hang the fail-fast machinery exists to "
                    "prevent. Bound it: slice the wait against an "
                    "RpcPolicy budget and raise on exhaustion "
                    "(comm/object_plane.py _sliced_get), or cap "
                    "attempts with backoff_ms between re-sends "
                    f"(fleet/transport.py) ({_DOC}#dl117)."))
                break                     # one finding per try block
    return findings


register(Rule("DL117", "unbounded-retry-loop", f"{_DOC}#dl117",
              check_unbounded_retry_loop))


# ---------------------------------------------------------------------------
# DL123 — socket-without-timeout
# ---------------------------------------------------------------------------

#: calls that mint a socket object worth tracking: constructors, the
#: dial helper, and ``accept()`` (whose returned conn is a NEW socket
#: that does NOT inherit a deadline discipline worth relying on)
_SOCKET_CREATORS = {"socket", "create_connection", "create_server",
                    "accept"}

#: operations on a socket that block until the peer acts — each one is
#: an indefinite hang against a half-open peer unless a timeout is set
_SOCKET_BLOCKING_OPS = {"recv", "recv_into", "recvfrom", "accept",
                        "connect", "sendall", "send", "makefile"}


def _sock_name(node: ast.expr) -> Optional[str]:
    """The trackable name of a socket receiver/target: a bare ``Name``
    or the final attribute of ``self.x``-style access."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def check_socket_without_timeout(tree, src, path) -> List[Finding]:
    """A blocking socket op on a socket that never got a timeout.

    TCP gives no notification for a peer that is SIGKILLed, wedged, or
    partitioned mid-connection: a ``recv``/``accept``/``connect`` on a
    default (blocking, no-timeout) socket hangs FOREVER — the network
    twin of the DL117 unbounded retry. The discipline
    (``comm/socket_plane.py``): every socket gets ``settimeout`` right
    after creation, sized from the ``RpcPolicy`` probe budget, so every
    wire wait is a bounded probe slice that re-checks liveness.

    Flagged shape: a name assigned from ``socket()``/
    ``create_connection()``/``create_server()`` or an ``accept()``
    result, later used for a blocking op (``recv``/``accept``/
    ``connect``/``sendall``/...) with no ``settimeout``/
    ``setblocking`` call on that name anywhere in the file. One
    finding per socket name, at its first blocking use.

    NOT flagged: ``create_connection(addr, timeout)`` /
    ``timeout=`` (the dial is bounded at birth — but the returned
    socket still needs ``settimeout`` for its LATER reads, so only the
    tracked dial itself is excused when the timeout rides along);
    files that call ``socket.setdefaulttimeout`` (a process-wide
    bound); names that ``setblocking(False)`` (non-blocking I/O has
    its own readiness discipline). Tracking is per-file and by name —
    over-approximate on purpose, same trade as DL117.
    """
    for n in ast.walk(tree):
        if (isinstance(n, ast.Call)
                and _callee_name(n) == "setdefaulttimeout"):
            return []                   # process-wide bound
    created: Dict[str, int] = {}        # name → creation line
    safe: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            callee = _callee_name(n.value)
            if callee not in _SOCKET_CREATORS or len(n.targets) != 1:
                continue
            target = n.targets[0]
            if callee == "accept" and isinstance(target, ast.Tuple):
                target = target.elts[0] if target.elts else target
            tname = _sock_name(target)
            if tname is None:
                continue
            created.setdefault(tname, n.lineno)
            if callee == "create_connection" and (
                    len(n.value.args) >= 2
                    or any(kw.arg == "timeout"
                           for kw in n.value.keywords)):
                safe.add(tname)         # bounded at birth
        elif (isinstance(n, ast.Call)
              and isinstance(n.func, ast.Attribute)
              and n.func.attr in ("settimeout", "setblocking")):
            tname = _sock_name(n.func.value)
            if tname is not None:
                safe.add(tname)
    findings: List[Finding] = []
    reported: Set[str] = set()
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _SOCKET_BLOCKING_OPS):
            continue
        tname = _sock_name(n.func.value)
        if (tname is None or tname not in created or tname in safe
                or tname in reported):
            continue
        reported.add(tname)
        findings.append(Finding(
            "DL123", path, n.lineno,
            f"'{tname}.{n.func.attr}' blocks on a socket that never "
            f"got a timeout (created at line {created[tname]}) — "
            "against a SIGKILLed or partitioned peer this waits "
            "forever, the network twin of the DL117 unbounded retry. "
            "Call settimeout right after creating it, sized from the "
            "RpcPolicy probe budget (comm/socket_plane.py), so every "
            f"wire wait is a bounded probe slice ({_DOC}#dl123)."))
    findings.sort(key=lambda f: f.line)
    return findings


register(Rule("DL123", "socket-without-timeout", f"{_DOC}#dl123",
              check_socket_without_timeout))


# ---------------------------------------------------------------------------
# DL124 — unverified-weight-load
# ---------------------------------------------------------------------------

#: calls that deserialize bytes into arrays/objects — the moment a
#: torn or tampered snapshot becomes live params if nothing checked it
_DESERIALIZER_CALLS = {"load", "fromfile"}

#: a weight/snapshot-load-shaped function name: it must say WHAT it
#: loads (weights or a snapshot) and that it LOADS it
_WEIGHTY = ("weight", "snapshot")
_LOADY = ("load", "read", "decode", "restore")


def _is_verifyish(name: Optional[str]) -> bool:
    """A callee name that smells like integrity checking.

    ``sha`` only counts on a token boundary (``sha256``, ``_sha``),
    so ``read_weight_shards`` is still a loader, not a verifier.
    """
    if not name:
        return False
    low = name.lower()
    if any(tok in low for tok in ("verify", "digest", "checksum")):
        return True
    for part in low.replace(".", "_").split("_"):
        if part == "sha" or part.startswith(("sha1", "sha2",
                                             "sha3", "sha5")):
            return True
    return False


def check_unverified_weight_load(tree, src, path) -> List[Finding]:
    """A weight/snapshot loader that deserializes without verifying.

    Weights are the one artifact every replica trusts blindly: a torn
    ``publish_weights`` rename, a corrupt relay chunk, or a stale ring
    replica that loads unchecked becomes silently wrong LOGITS — no
    crash, no NaN, just a fleet bitwise-diverging from its oracle. The
    discipline (``serving/weights.py``): every snapshot travels with a
    SHA-256 + byte-count manifest, and every loader calls ``_verify``
    (or checks the digest inline) BEFORE ``np.load`` touches the
    payload — a failed check falls back to the next candidate or
    raises ``WeightsError``, it never half-loads.

    Flagged shape: a function whose name says it loads weights or a
    snapshot (``load``/``read``/``decode``/``restore`` ×
    ``weight``/``snapshot``) calling ``np.load``/``fromfile`` while
    neither calling anything verify-ish (``verify``/``sha``/
    ``digest``/``checksum``) itself nor calling an in-file helper that
    does (one level of resolution — the ``load_weights`` → ``_verify``
    shape). One finding per function, at the deserializing call.

    NOT flagged: functions named like verifiers (they ARE the check);
    deserialization in functions with other names (checkpoint iterators
    and manifest peeks have their own disciplines — this rule guards
    the load-weights face specifically, the trade every DL1xx rule
    makes: catch the shape that burned us, over-approximate nowhere).
    """
    # per-function direct-callee sets, for the one-level resolution
    callees: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names = set()
            for n in _walk_excluding_defs(node.body):
                if isinstance(n, ast.Call):
                    cn = _callee_name(n)
                    if cn:
                        names.add(cn)
            callees.setdefault(node.name, set()).update(names)

    def _verifies(fname: str, depth: int = 1) -> bool:
        called = callees.get(fname, set())
        if any(_is_verifyish(c) for c in called):
            return True
        if depth > 0:
            return any(c in callees and _verifies(c, depth - 1)
                       for c in called)
        return False

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        low = node.name.lower()
        if _is_verifyish(node.name):
            continue                    # the function IS the check
        if not (any(w in low for w in _WEIGHTY)
                and any(l in low for l in _LOADY)):
            continue
        if _verifies(node.name):
            continue
        for n in _walk_excluding_defs(node.body):
            if (isinstance(n, ast.Call)
                    and _callee_name(n) in _DESERIALIZER_CALLS):
                findings.append(Finding(
                    "DL124", path, n.lineno,
                    f"'{node.name}' deserializes a weight/snapshot "
                    "payload with no integrity check in sight — a torn "
                    "publish, a corrupt relay chunk, or a stale replica "
                    "loads as silently wrong logits, the failure no "
                    "crash ever reports. Verify the SHA-256 manifest "
                    "first (serving/weights.py _verify, or "
                    "decode_weights' inline digest) and fall back or "
                    "raise WeightsError on mismatch "
                    f"({_DOC}#dl124)."))
                break                   # one finding per function
    findings.sort(key=lambda f: f.line)
    return findings


register(Rule("DL124", "unverified-weight-load", f"{_DOC}#dl124",
              check_unverified_weight_load))

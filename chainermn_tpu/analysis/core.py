"""dlint core: findings, the rule registry, suppressions, and the driver.

Two pass shapes share one registry:

* **AST passes** (``kind="ast"``) — ``(tree, src, path) ->
  list[Finding]``, run per file with zero cross-module visibility;
* **project passes** (``kind="project"``) — ``(Project) ->
  list[Finding]``, run ONCE over a :class:`~.callgraph.Project` built
  from every parsed file in the run, for the interprocedural rules
  (DL113–DL116). ``lint_source`` builds a single-file project so
  in-string fixtures exercise them too.

The driver parses each file once, collects ``# dlint: disable=RULE``
comments from the token stream (so string literals containing the
marker cannot suppress anything), runs every requested pass, and drops
suppressed findings. A disable comment covers:

* its own line and the line below (the trailing-comment and
  comment-above idioms), and
* when it sits on the FIRST line of a statement — where "first"
  includes a decorator line — the statement's whole ``end_lineno``
  range, so one disable on a ``def``/``with``/multi-line call
  suppresses findings anchored anywhere inside it.

Every suppression records how many findings it absorbed;
:func:`run_lint` returns them so ``tools/dlint.py
--report-suppressions`` can list the dead ones (zero hits) before they
rot.
"""

from __future__ import annotations

import ast
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# matches ``dlint: disable=DL101``, ``disable=DL101,DL104``, and
# ``disable=all`` comment markers (hash prefix implied by the token)
_DISABLE_RE = re.compile(r"#\s*dlint:\s*disable=([\w,\s]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str          # stable ID, e.g. "DL101"
    path: str          # file the finding is in
    line: int          # 1-indexed line of the offending node
    message: str       # what is wrong + the fix-it, citing docs

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Rule:
    """Registry entry: a pass plus its catalogue metadata."""

    rule_id: str
    name: str
    doc: str           # docs/static_analysis.md anchor for the fix-it
    check: Callable    # (tree, src, path) | (Project) -> List[Finding]
    kind: str = "ast"  # "ast" | "project" | "hlo"


#: rule_id -> Rule. AST and project passes register themselves on import
#: (see :mod:`.ast_passes` / :mod:`.sequence` / :mod:`.locks`); HLO
#: rules register metadata only — they run on compiled HLO text via
#: :mod:`.hlo_passes`, not on source files.
RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.rule_id in RULES:
        raise ValueError(f"duplicate dlint rule id {rule.rule_id}")
    RULES[rule.rule_id] = rule
    return rule


def _load_passes() -> None:
    """Import every pass module so the registry is complete no matter
    which entry point ran first."""
    from chainermn_tpu.analysis import ast_passes  # noqa: F401
    from chainermn_tpu.analysis import dataflow_rules  # noqa: F401
    from chainermn_tpu.analysis import locks  # noqa: F401
    from chainermn_tpu.analysis import sequence  # noqa: F401


@dataclass
class Suppression:
    """One ``# dlint: disable=...`` comment and what it absorbed."""

    path: str
    line: int               # line the comment is on
    rules: set              # rule IDs it disables ({"all"} = wildcard)
    start: int              # first finding line it covers
    end: int                # last finding line it covers (inclusive)
    hits: int = 0           # findings it suppressed in this run

    def covers(self, f: Finding) -> bool:
        return (self.start <= f.line <= self.end
                and (f.rule in self.rules or "all" in self.rules))

    def format(self) -> str:
        rules = ",".join(sorted(self.rules))
        return f"{self.path}:{self.line}: disable={rules}"


@dataclass
class LintRun:
    """Everything one driver invocation produced."""

    findings: List[Finding] = field(default_factory=list)
    suppressions: List[Suppression] = field(default_factory=list)
    #: wall-clock seconds per pass ("DL113", …) plus the fixed-cost
    #: phases ("parse", "project-build") — ``tools/dlint.py --timings``
    #: serializes this so CI can watch the verify-budget headroom
    timings: Dict[str, float] = field(default_factory=dict)

    @property
    def dead_suppressions(self) -> List[Suppression]:
        return [s for s in self.suppressions if s.hits == 0]


def suppressed_lines(src: str) -> Dict[int, set]:
    """line -> set of rule IDs disabled there (``{"all"}`` disables all).

    Read from the TOKEN stream, not a regex over raw lines: a string
    literal that happens to contain the marker (e.g. this module's own
    docstrings, or a test fixture embedded as a string) must not
    suppress anything.
    """
    out: Dict[int, set] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                out.setdefault(tok.start[0], set()).update(rules)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def _statement_ranges(tree: ast.AST) -> Dict[int, int]:
    """first-line -> last end_lineno of any statement starting there.

    "First line" counts decorators: a disable on the ``@decorator``
    line of a decorated def covers the whole def. When several nested
    statements start on one line (``if x: y()``), the outermost —
    largest — range wins, which is the direction suppression should
    err in: the comment visibly sits on that whole construct.
    """
    ranges: Dict[int, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        first = node.lineno
        for dec in getattr(node, "decorator_list", None) or []:
            first = min(first, dec.lineno)
        end = getattr(node, "end_lineno", None) or node.lineno
        if end > ranges.get(first, 0):
            ranges[first] = end
    return ranges


def collect_suppressions(src: str, path: str,
                         tree: Optional[ast.AST] = None
                         ) -> List[Suppression]:
    disables = suppressed_lines(src)
    if not disables:
        return []
    ranges = _statement_ranges(tree) if tree is not None else {}
    out = []
    for line in sorted(disables):
        # own line + line below (legacy), widened to the full range of
        # a statement whose first line carries (or sits under) it
        end = max(line + 1, ranges.get(line, 0), ranges.get(line + 1, 0))
        out.append(Suppression(path, line, disables[line], line, end))
    return out


def _apply_suppressions(findings: List[Finding],
                        sups: Dict[str, List[Suppression]]
                        ) -> List[Finding]:
    kept: List[Finding] = []
    for f in findings:
        hit = None
        for s in sups.get(f.path, ()):
            if s.covers(f):
                hit = s
                break
        if hit is not None:
            hit.hits += 1
        else:
            kept.append(f)
    return kept


def run_lint_sources(sources: Dict[str, str],
                     rules: Optional[Sequence[str]] = None) -> LintRun:
    """The driver: run AST passes per file and project passes over the
    whole set. ``sources``: path -> source text."""
    _load_passes()
    from chainermn_tpu.analysis.callgraph import Project

    run = LintRun()
    findings: List[Finding] = []
    sups: Dict[str, List[Suppression]] = {}
    parsed: Dict[str, Tuple[ast.AST, str]] = {}
    timings = run.timings
    for path in sorted(sources):
        src = sources[path]
        t0 = time.perf_counter()
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(Finding(
                "DL000", path, e.lineno or 1,
                f"syntax error blocks analysis: {e.msg}"))
            sups[path] = collect_suppressions(src, path)
            continue
        finally:
            timings["parse"] = timings.get("parse", 0.0) \
                + time.perf_counter() - t0
        parsed[path] = (tree, src)
        sups[path] = collect_suppressions(src, path, tree)
        for rule in RULES.values():
            if rule.kind != "ast":
                continue
            if rules is not None and rule.rule_id not in rules:
                continue
            t0 = time.perf_counter()
            findings.extend(rule.check(tree, src, path))
            timings[rule.rule_id] = timings.get(rule.rule_id, 0.0) \
                + time.perf_counter() - t0

    project_rules = [r for r in RULES.values() if r.kind == "project"
                     and (rules is None or r.rule_id in rules)]
    if project_rules and parsed:
        t0 = time.perf_counter()
        project = Project.build(parsed)
        timings["project-build"] = time.perf_counter() - t0
        for rule in project_rules:
            t0 = time.perf_counter()
            findings.extend(rule.check(project))
            timings[rule.rule_id] = time.perf_counter() - t0

    # a call nested under two rank-dependent Ifs can be reported by both
    # evaluations; one report per (rule, path, line) is enough — dedup
    # BEFORE suppression accounting so duplicates don't inflate hits
    findings = sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.rule))
    findings = _apply_suppressions(findings, sups)
    run.findings = findings
    run.suppressions = [s for path in sorted(sups) for s in sups[path]]
    return run


def lint_source(src: str, path: str = "<string>",
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the passes over one source string (project passes see a
    single-file project). ``rules`` restricts to the given IDs
    (default: every registered source rule)."""
    return run_lint_sources({path: src}, rules=rules).findings


def lint_file(path: str,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    return lint_source(src, path, rules=rules)


_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
              ".eggs", "node_modules"}


def iter_python_files(roots: Iterable[str]) -> List[str]:
    """Every .py under the given files/directories, sorted, deduped."""
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def run_lint(paths: Iterable[str],
             rules: Optional[Sequence[str]] = None,
             only: Optional[Iterable[str]] = None) -> LintRun:
    """Lint every .py under ``paths``. ``only``, when given, restricts
    REPORTING to those files while the whole-program passes still see
    everything (the ``--changed`` contract: context stays global, the
    gate is local)."""
    sources: Dict[str, str] = {}
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8") as fh:
            sources[path] = fh.read()
    run = run_lint_sources(sources, rules=rules)
    if only is not None:
        keep = {os.path.abspath(p) for p in only}
        run.findings = [f for f in run.findings
                        if os.path.abspath(f.path) in keep]
        run.suppressions = [s for s in run.suppressions
                            if os.path.abspath(s.path) in keep]
    return run


def lint_paths(paths: Iterable[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the source passes over every .py file under ``paths``."""
    return run_lint(paths, rules=rules).findings

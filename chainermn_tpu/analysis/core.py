"""dlint core: findings, the rule registry, suppressions, and the driver.

The shape every pass shares: a pass is a function
``(tree, src, path) -> list[Finding]`` registered under a stable rule ID.
The driver parses each file once, collects ``# dlint: disable=RULE``
comments from the token stream (so string literals containing the marker
cannot suppress anything), runs every requested pass, and drops findings
whose line — or the line directly above, for multi-line calls and
statement-level suppressions — carries a matching disable comment.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence

# ``# dlint: disable=DL101`` or ``# dlint: disable=DL101,DL104`` or
# ``# dlint: disable=all``
_DISABLE_RE = re.compile(r"#\s*dlint:\s*disable=([\w,\s]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str          # stable ID, e.g. "DL101"
    path: str          # file the finding is in
    line: int          # 1-indexed line of the offending node
    message: str       # what is wrong + the fix-it, citing docs

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Rule:
    """Registry entry: a pass plus its catalogue metadata."""

    rule_id: str
    name: str
    doc: str           # docs/static_analysis.md anchor for the fix-it
    check: Callable    # (tree, src, path) -> List[Finding]
    kind: str = "ast"  # "ast" | "hlo" (hlo rules are not file passes)


#: rule_id -> Rule. AST passes register themselves on import
#: (see :mod:`.ast_passes`); HLO rules register metadata only — they run
#: on compiled HLO text via :mod:`.hlo_passes`, not on source files.
RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.rule_id in RULES:
        raise ValueError(f"duplicate dlint rule id {rule.rule_id}")
    RULES[rule.rule_id] = rule
    return rule


def suppressed_lines(src: str) -> Dict[int, set]:
    """line -> set of rule IDs disabled there (``{"all"}`` disables all).

    Read from the TOKEN stream, not a regex over raw lines: a string
    literal that happens to contain the marker (e.g. this module's own
    docstrings, or a test fixture embedded as a string) must not
    suppress anything.
    """
    out: Dict[int, set] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                out.setdefault(tok.start[0], set()).update(rules)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def _is_suppressed(f: Finding, disables: Dict[int, set]) -> bool:
    for line in (f.line, f.line - 1):
        rules = disables.get(line)
        if rules and (f.rule in rules or "all" in rules):
            return True
    return False


def lint_source(src: str, path: str = "<string>",
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the AST passes over one source string. ``rules`` restricts to
    the given IDs (default: every registered AST rule)."""
    # passes register on import; import here so `import analysis.core`
    # alone never yields an empty registry
    from chainermn_tpu.analysis import ast_passes  # noqa: F401

    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("DL000", path, e.lineno or 1,
                        f"syntax error blocks analysis: {e.msg}")]
    disables = suppressed_lines(src)
    findings: List[Finding] = []
    for rule in RULES.values():
        if rule.kind != "ast":
            continue
        if rules is not None and rule.rule_id not in rules:
            continue
        findings.extend(rule.check(tree, src, path))
    findings = [f for f in findings if not _is_suppressed(f, disables)]
    # a call nested under two rank-dependent Ifs can be reported by both
    # evaluations; one report per (rule, line) is enough
    findings = sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_file(path: str,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    return lint_source(src, path, rules=rules)


_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
              ".eggs", "node_modules"}


def iter_python_files(roots: Iterable[str]) -> List[str]:
    """Every .py under the given files/directories, sorted, deduped."""
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def lint_paths(paths: Iterable[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the AST passes over every .py file under ``paths``."""
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, rules=rules))
    return findings

"""dlint HLO passes: schedule-level distributed-correctness checks.

These run on *compiled* HLO text (``compiled.as_text()`` of a lowered
computation, or a saved dump) — the generalized form of
``tools/check_overlap_schedule.py``, which is now a thin wrapper over
this module. Source-level rules (DL1xx, :mod:`.ast_passes`) can only
prove a program *says* the right thing; these prove the compiler
*scheduled* the right thing:

* ``DL201`` :func:`check_dp_overlap` — in a latency-hiding-scheduled
  module, the FIRST gradient all-reduce must be placed before the LAST
  backward op (ops carrying ``transpose(jvp`` metadata), i.e. gradient
  collectives issue while backward compute remains rather than
  serializing after it (docs/scaling_model.md §2).
* ``DL202`` :func:`check_collective_budget` — the scheduled entry (or a
  named computation) must not exceed a per-step collective-op budget;
  a bucketing/combining regression shows up as a collective-count jump
  long before it shows up in step time.
* ``DL203`` :func:`check_pipeline_permute_overlap` — 1F1B wire
  ppermutes must lower to async collective-permute-start/done pairs
  with ≥1 real compute op inside EVERY pair's own window and no
  synchronous collective-permute fallback (docs/scaling_model.md §6).
* ``DL204`` :func:`check_fsdp_gather_liveness` — FSDP parameter
  all-gathers must not all be live at once: if the peak number of
  concurrently-live gathered buffers is ~every layer, sharding only
  saved optimizer memory and the prefetch is degenerate (the
  ``make_fsdp_train_step`` 0.93×-full-params peak of VERDICT weak #2;
  the scan path pins the bound instead).
* ``DL205`` :func:`check_quantized_wire_dtype` — when a compiled step
  claims a quantized wire (``wire_format=``/``param_wire=``), the
  DOMINANT-by-bytes collective must actually carry a narrow dtype
  (integer codes or sub-f32 float); a quantize that the partitioner
  hoisted BEHIND the collective leaves the full f32 payload on the
  wire while the byte accounting reports compression.

Every checker returns a dict with at least ``{"ok": bool, ...}``
evidence fields; ``ok=None`` with a ``skip`` key means the input had
nothing to check (e.g. an unscheduled module).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from chainermn_tpu.analysis.core import Rule, register

_DOC = "docs/static_analysis.md"

for _rid, _name in (("DL201", "dp-allreduce-overlap"),
                    ("DL202", "collective-budget"),
                    ("DL203", "pipeline-permute-overlap"),
                    ("DL204", "fsdp-gather-liveness"),
                    ("DL205", "quantized-wire-dtype")):
    register(Rule(_rid, _name, f"{_DOC}#{_rid.lower()}",
                  check=None, kind="hlo"))


#: collective op kinds counted by the budget pass (start/done async
#: halves count once, via the -start form; the bare form is the sync op)
COLLECTIVE_OPS = (
    "all-reduce", "all-reduce-start", "all-gather", "all-gather-start",
    "reduce-scatter", "all-to-all", "collective-permute",
    "collective-permute-start", "collective-broadcast",
)


def scheduled_entry_ops(hlo_text: str) -> List[Tuple[str, str]]:
    """(op_kind, full_line) per instruction of the ENTRY computation, in
    schedule order (meaningful when the module is ``is_scheduled=true``)."""
    ops = []
    in_entry = False
    for ln in hlo_text.splitlines():
        if ln.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if ln.startswith("}"):
                break
            s = ln.strip()
            if s.startswith("ROOT "):
                s = s[len("ROOT "):]
            if not re.match(r"%?[\w.-]+ = ", s):
                continue
            # the opcode is the token right before the operand list:
            # the leftmost space-preceded lowercase token directly
            # followed by "(". Result types can't shadow it — tuple
            # types open with "= (", and the tile/memory annotations
            # inside them ("T(8,128)", "S(1)") are uppercase. Operands
            # may carry full types ("all-reduce(f32[...] %x, ...)"),
            # so nothing stricter than the bare paren can be anchored.
            m = re.search(r" ([a-z][\w-]*)\(", s)
            if m:
                ops.append((m.group(1), s))
    return ops


def parse_computations(
        hlo_text: str) -> Dict[str, List[Tuple[str, str, List[str]]]]:
    """name -> [(op_kind, result_name, [operand_names])] per HLO
    computation, in textual (= schedule, when scheduled) order."""
    comps: Dict[str, List[Tuple[str, str, List[str]]]] = {}
    cur: Optional[str] = None
    for ln in hlo_text.splitlines():
        m = re.match(r"^(?:ENTRY )?%?([\w.-]+) \(.*\{\s*$", ln)
        if m:
            cur = m.group(1)
            comps[cur] = []
            continue
        if cur is not None:
            if ln.startswith("}"):
                cur = None
                continue
            s = ln.strip()
            if s.startswith("ROOT "):
                s = s[len("ROOT "):]
            mm = re.match(r"%?([\w.-]+) = .*? ([a-z][\w-]*)\((.*)", s)
            if mm:
                operands = re.findall(r"%([\w.-]+)", mm.group(3))
                comps[cur].append((mm.group(2), mm.group(1), operands))
    return comps


# ---------------------------------------------------------------------------
# DL201 — gradient all-reduce must overlap backward compute
# ---------------------------------------------------------------------------


def check_dp_overlap(hlo_text: str) -> dict:
    """Does the scheduled entry start gradient all-reduces before
    backward compute ends?"""
    ops = scheduled_entry_ops(hlo_text)
    ar = [i for i, (k, _) in enumerate(ops)
          if k in ("all-reduce", "all-reduce-start")]
    bwd = [i for i, (_, s) in enumerate(ops) if "transpose(jvp" in s]
    out = {
        "rule": "DL201",
        "is_scheduled": "is_scheduled=true" in hlo_text,
        "n_sched_ops": len(ops),
        "n_allreduce": len(ar),
        "first_allreduce": min(ar) if ar else None,
        "last_backward": max(bwd) if bwd else None,
        "backward_ops_after_first_allreduce": (
            sum(1 for i in bwd if i > min(ar)) if ar else 0),
        "async_pairs": bool(re.search(r"all-reduce-start", hlo_text)),
    }
    out["overlap_fraction"] = (
        out["backward_ops_after_first_allreduce"] / len(bwd)
        if out["is_scheduled"] and ar and bwd else 0.0)
    out["ok"] = bool(
        out["is_scheduled"] and ar and bwd and min(ar) < max(bwd))
    if not out["ok"]:
        out["fix"] = (
            "compile with xla_tpu_enable_latency_hiding_scheduler=true "
            "and xla_enable_async_all_reduce=true so gradient "
            f"all-reduces hide in the backward window ({_DOC}#dl201)")
    return out


def dp_overlap_fraction(hlo_text: str) -> float:
    """DL201 as a scalar SCORE, not just a verdict: the fraction of
    backward ops scheduled after the first gradient all-reduce issues —
    i.e. the share of the backward window available to hide gradient
    collectives in. 0.0 for an unscheduled module, no all-reduce, or no
    backward ops; 1.0 when every backward op follows the first issue
    (the double-buffered/prev-step-grads shape). This is the objective
    the schedule autotuner (:mod:`chainermn_tpu.tuning`) maximizes and
    ``tools/check_overlap_schedule.py --assert-min-overlap`` gates."""
    return check_dp_overlap(hlo_text)["overlap_fraction"]


# ---------------------------------------------------------------------------
# DL202 — per-step collective-count budget
# ---------------------------------------------------------------------------


def check_collective_budget(hlo_text: str, budget: int,
                            computation: Optional[str] = None) -> dict:
    """At most ``budget`` collective ops per step.

    Counts :data:`COLLECTIVE_OPS` in the scheduled entry (or in a named
    computation, e.g. a pipeline while-body). A combiner/bucketing
    regression multiplies the per-step collective count — catch it at
    compile time, not in the profile.
    """
    if computation is None:
        kinds = [k for k, _ in scheduled_entry_ops(hlo_text)]
    else:
        comps = parse_computations(hlo_text)
        if computation not in comps:
            return {"rule": "DL202", "ok": None,
                    "skip": f"no computation named {computation!r}"}
        kinds = [k for k, _, _ in comps[computation]]
    counts: Dict[str, int] = {}
    for k in kinds:
        if k in COLLECTIVE_OPS:
            counts[k] = counts.get(k, 0) + 1
    total = sum(counts.values())
    out = {"rule": "DL202", "n_collectives": total, "budget": budget,
           "by_kind": counts, "ok": total <= budget}
    if not out["ok"]:
        out["fix"] = (
            f"{total} collectives exceed the per-step budget of {budget}; "
            "check dcn_bucket_bytes / the XLA all-reduce combiner "
            f"threshold before profiling ({_DOC}#dl202)")
    return out


# ---------------------------------------------------------------------------
# DL203 — 1F1B wire permutes must be async with compute inside
# ---------------------------------------------------------------------------


def check_pipeline_permute_overlap(hlo_text: str) -> dict:
    """Every collective-permute must be an async start/done pair with
    ≥1 real compute op (fusion/dot/custom-call) scheduled inside ITS OWN
    window, and no op may fall back to a synchronous collective-permute.

    Scans every computation and reports the one with the most permute
    pairs (the pipeline while-body); compute counted inside an unrelated
    pair's gap must not certify an individually-serialized hop, so each
    start is matched to the done consuming its result.
    """
    best = None
    for name, ops in parse_computations(hlo_text).items():
        starts = [(i, res) for i, (k, res, _) in enumerate(ops)
                  if k == "collective-permute-start"]
        if not starts:
            continue
        fusions = [i for i, (k, _, _) in enumerate(ops)
                   if k in ("fusion", "dot", "custom-call")]
        pairs = []
        for si, res in starts:
            done = next((i for i, (k, _, opr) in enumerate(ops)
                         if i > si and k == "collective-permute-done"
                         and res in opr), None)
            if done is not None:
                pairs.append(
                    (si, done, sum(1 for f in fusions if si < f < done)))
        if not pairs:
            continue
        cand = {
            "body": name,
            "n_body_ops": len(ops),
            "n_permute_pairs": len(pairs),
            "pairs": [{"start": s, "done": d, "compute_inside": c}
                      for s, d, c in pairs],
            "min_compute_inside_any_pair": min(c for _, _, c in pairs),
            "n_compute": len(fusions),
        }
        if best is None or cand["n_permute_pairs"] > best["n_permute_pairs"]:
            best = cand

    out = best or {"n_permute_pairs": 0}
    out["rule"] = "DL203"
    out["sync_permutes"] = len(
        re.findall(r"= *\S* *collective-permute\(", hlo_text))
    # ok = both rings async, EVERY hop hides >=1 real compute op inside
    # its own start->done window, and nothing fell back to a synchronous
    # collective-permute
    out["ok"] = bool(best and best["n_permute_pairs"] >= 2
                     and best["min_compute_inside_any_pair"] >= 1
                     and out["sync_permutes"] == 0)
    if not out["ok"]:
        out["fix"] = (
            "the wire hop is serialized with tick compute; enable the "
            "latency-hiding scheduler and keep per-tick compute large "
            f"enough to hide the permute ({_DOC}#dl203)")
    return out


# ---------------------------------------------------------------------------
# DL204 — degenerate FSDP all-gather prefetch
# ---------------------------------------------------------------------------


def check_fsdp_gather_liveness(hlo_text: str,
                               max_live: int = 2,
                               computation: Optional[str] = None) -> dict:
    """Peak number of concurrently-live all-gathered buffers.

    For each all-gather (sync, or async via its -start/-done pair) in
    the computation, the gathered value is live from its definition to
    its last textual use. If nearly all of them overlap — peak live ≈
    total gathers — XLA prefetched EVERY layer's parameters up front:
    peak memory is back to the unsharded model and FSDP only sharded
    optimizer state (the degenerate ``make_fsdp_train_step`` shape;
    ``fsdp_scan_apply`` pins the bound to one layer instead).

    ``max_live`` is the allowed peak (2 admits the standard
    prefetch-one-layer-ahead pipeline).
    """
    comps = parse_computations(hlo_text)
    if computation is not None:
        if computation not in comps:
            return {"rule": "DL204", "ok": None,
                    "skip": f"no computation named {computation!r}"}
        selected = {computation: comps[computation]}
    else:
        selected = comps

    # pick the computation with the most all-gathers (entry for the
    # degenerate case, the scan/while body for the pinned case)
    best_name, best_ops, best_n = None, None, 0
    for name, ops in selected.items():
        n = sum(1 for k, _, _ in ops
                if k in ("all-gather", "all-gather-start"))
        if n > best_n:
            best_name, best_ops, best_n = name, ops, n
    if best_ops is None:
        return {"rule": "DL204", "ok": None, "skip": "no all-gathers"}

    last_use = {}
    for i, (_, _, operands) in enumerate(best_ops):
        for o in operands:
            last_use[o] = i
    intervals = []
    for i, (k, res, operands) in enumerate(best_ops):
        if k == "all-gather":
            intervals.append((i, last_use.get(res, i)))
        elif k == "all-gather-start":
            # live from the start; the value consumers use is the done's
            # result — extend to ITS last use
            done = next(
                ((j, r) for j, (kk, r, opr) in enumerate(best_ops)
                 if j > i and kk == "all-gather-done" and res in opr),
                None)
            end = last_use.get(done[1], done[0]) if done else \
                last_use.get(res, i)
            intervals.append((i, end))
    peak = 0
    for i in range(len(best_ops)):
        live = sum(1 for s, e in intervals if s <= i <= e)
        peak = max(peak, live)
    out = {
        "rule": "DL204",
        "computation": best_name,
        "n_gathers": len(intervals),
        "peak_live_gathers": peak,
        "max_live": max_live,
        "ok": peak <= max_live,
    }
    if not out["ok"]:
        out["fix"] = (
            f"{peak} of {len(intervals)} gathered parameter buffers are "
            "live at once — the prefetch is degenerate and peak memory "
            "is back at the unsharded model. Stack the layers and use "
            "fsdp_scan_apply + fsdp_stack_shardings to pin the bound "
            f"({_DOC}#dl204)")
    return out


# ---------------------------------------------------------------------------
# DL205 — quantized wire must put a narrow dtype on the collective
# ---------------------------------------------------------------------------

#: dtypes that count as a quantized wire: integer codes (the int8/int4
#: paths accumulate in s32 — EQuARX-style; still evidence the payload
#: left f32) and sub-f32 floats. f32/f64 payloads are the wide wire.
NARROW_WIRE_DTYPES = frozenset(
    ("s4", "u4", "s8", "u8", "s16", "u16", "s32", "u32", "bf16", "f16"))

#: dtype -> bytes/element for the dominance ranking
_DTYPE_BYTES = {"pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
                "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8}

_WIRE_COLLECTIVES = ("all-reduce", "all-reduce-start", "reduce-scatter",
                     "all-gather", "all-gather-start")

_COLLECTIVE_RE = re.compile(
    r"= \(?(\w+)\[([\d,]*)\][^\n]*? "
    r"(all-reduce-start|all-reduce|reduce-scatter|"
    r"all-gather-start|all-gather)\(")


def check_quantized_wire_dtype(hlo_text: str,
                               expect_quantized: bool = False) -> dict:
    """DL205: the dominant-by-bytes collective carries a narrow dtype.

    The quantized wire formats (``wire_format=``/``param_wire=``,
    docs/collectives.md#quantized-wire-formats) only save bandwidth if
    the COMPILED collective moves the narrow representation — integer
    codes (int8/int4 paths; accumulation is s32) or bf16 — not a
    dequantized f32 tensor. A sharding constraint pins layout, not
    placement, so GSPMD can legally hoist the dequantize (or the
    gather) and put f32 back on the wire while host-side byte
    accounting still reports 4×. This pass reads the collectives out of
    the compiled text and checks the LARGEST payload's dtype; the f32
    scale sidecars of the blockwise formats are collectives too, which
    is why only the dominant one must be narrow.

    Without any narrow collective the module shows no quantization
    evidence: ``ok=None`` (skip) unless ``expect_quantized=True``, so
    the argument-free ``dlint --hlo`` run stays silent on ordinary
    unquantized programs.
    """
    found = []
    for dt, shape, kind in _COLLECTIVE_RE.findall(hlo_text):
        elems = 1
        for d in shape.split(","):
            if d.strip():
                elems *= int(d)
        found.append({"op": kind, "dtype": dt, "elements": elems,
                      "bytes": elems * _DTYPE_BYTES.get(dt, 4)})
    if not found:
        return {"rule": "DL205", "ok": None, "skip": "no collectives"}

    def _is_narrow(f):
        if f["dtype"] in ("s32", "u32"):
            # s32 only counts on REDUCING collectives (the int8/int4
            # paths accumulate their codes in s32); an s32 all-gather
            # is just wide integer data
            return f["op"].startswith(("all-reduce", "reduce-scatter"))
        return f["dtype"] in NARROW_WIRE_DTYPES

    # dominance is judged PER FAMILY (reduces vs gathers): FSDP's
    # param_wire quantizes the gather while its gradients legitimately
    # reduce in f32, and a quantized grad reducer is the converse
    fams = {
        "reduce": [f for f in found
                   if f["op"].startswith(("all-reduce", "reduce-scatter"))],
        "gather": [f for f in found if f["op"].startswith("all-gather")],
    }
    evidence, failed, dominants = 0, [], {}
    for fam, ops in fams.items():
        # sub-block-size narrow collectives (loop counters, flag
        # psums) are not evidence anyone quantized a payload
        narrow = [f for f in ops
                  if _is_narrow(f) and f["elements"] >= 256]
        if not narrow:
            continue
        evidence += 1
        dominant = max(ops, key=lambda f: f["bytes"])
        dominants[fam] = dominant
        if dominant not in narrow:
            failed.append((fam, dominant, len(narrow)))
    if not evidence:
        if expect_quantized:
            return {
                "rule": "DL205", "ok": False, "collectives": found,
                "fix": ("the step was built with a quantized wire but "
                        "no collective carries a narrow dtype — the "
                        "quantize was hoisted behind (or dropped from) "
                        "every collective and the full f32 payload "
                        f"crosses the wire ({_DOC}#dl205)")}
        return {"rule": "DL205", "ok": None,
                "skip": "no quantized-wire evidence"}
    out = {
        "rule": "DL205",
        "n_collectives": len(found),
        "dominant": dominants,
        "ok": not failed,
    }
    if failed:
        fam, dominant, n_narrow = failed[0]
        out["fix"] = (
            f"the largest {fam} collective ({dominant['op']} "
            f"{dominant['dtype']}[{dominant['elements']}], "
            f"{dominant['bytes']:,} B) is still wide while "
            f"{n_narrow} smaller one(s) are narrow — the main "
            "payload's quantize did not survive to the wire (sharding "
            "constraints pin layout, not placement; use the shard_map "
            "gather path or check the reducer actually wraps this "
            f"tensor) ({_DOC}#dl205)")
    return out

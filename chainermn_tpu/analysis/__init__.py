"""dlint — distributed-correctness static analysis for the whole stack.

Distributed training fails in ways single-process code never does:
mismatched collectives deadlock, rank-dependent control flow diverges,
channel tags collide, and overlap regressions silently serialize comms.
Compiler-level collective tooling (GC3, arxiv 2201.11840; TACCL, arxiv
2111.04867) shows collective programs are tractable objects for static
checking; this package brings that discipline in-repo as a permanent
analysis subsystem instead of per-round manual audits (the round-5
unsynced-step-loop flake was caught only by a manual AST pass — that
audit is now rule DL104).

Two pass families, one CLI (``tools/dlint.py``):

* **AST passes** (:mod:`.ast_passes`) run over Python sources —
  ``chainermn_tpu/``, ``examples/``, ``tests/``, ``tools/``:

  - ``DL101`` divergent collective under rank-dependent control flow
  - ``DL102`` eager-P2P channel-tag collision / reserved-namespace use
  - ``DL103`` root argument from the wrong rank space
  - ``DL104`` step-dispatch loop without a per-iteration sync

* **Project passes** (:mod:`.sequence`, :mod:`.locks`) run ONCE over a
  whole-program :class:`~.callgraph.Project` (symbol table + call
  graph built from every file in the run), so they see through call
  chains the per-file passes cannot:

  - ``DL113`` interprocedural divergent collective (DL101 through any
    resolved call chain)
  - ``DL114`` send/recv channel cycles and unmatched endpoints
  - ``DL115`` lock-order inversion across the threaded planes
  - ``DL116`` blocking call while holding a lock

* **Dataflow passes** (:mod:`.dataflow_rules`) are project passes on
  the value-level engine in :mod:`.dataflow` — reaching definitions
  and def-use chains per function, composed interprocedurally through
  the call graph by per-function summaries (which params are consumed
  or donated):

  - ``DL118`` PRNG-key reuse, or a discarded ``split``/``fold_in``
    result (the one-split-per-sampled-token reproducibility contract)
  - ``DL119`` use-after-donation (a value handed to a
    ``donate_argnums`` position — directly or through a callee — read
    again afterwards)
  - ``DL120`` ``set`` iteration feeding collective construction,
    channel-tag assignment, or trace-signature tuples
  - ``DL121`` host-device sync (``.item()``, ``np.asarray``,
    ``float()``) on values derived from the data arguments of a
    ``decode_k``/``ServingStep`` hot path
  - ``DL122`` trace-count instability — Python branching on
    request-dependent values inside jit-compiled functions (the static
    twin of DL108's runtime check)

* **Compiled-HLO passes** (:mod:`.hlo_passes`) run over scheduled HLO
  text (``compiled.as_text()``) — the generalized form of
  ``tools/check_overlap_schedule.py``, which is now a thin wrapper:

  - ``DL201`` gradient all-reduce must overlap backward compute
  - ``DL202`` per-step collective-count budget
  - ``DL203`` 1F1B wire permutes must be async with compute inside
  - ``DL204`` degenerate FSDP all-gather prefetch (gathered layers co-live)
  - ``DL205`` quantized wire: dominant collective must carry a narrow dtype

Every rule has a stable ID, a fix-it message citing the docs
(docs/static_analysis.md catalogues each with a minimal failing
example), and positive/negative fixture tests under
``tests/analysis_tests/``. Findings are suppressed in source with a
``# dlint: disable=RULE`` comment on the flagged line (or the line
directly above it; on a statement's first line it covers the whole
statement, decorators included) — suppressions should carry a
rationale, and ``tools/dlint.py --report-suppressions`` lists the dead
ones. ``--format sarif`` / ``--baseline`` / ``--changed`` make the CLI
CI-grade (:mod:`.output`).
"""

from chainermn_tpu.analysis import ast_passes  # noqa: F401  (registers DL1xx)
from chainermn_tpu.analysis import dataflow_rules  # noqa: F401  (DL118–DL122)
from chainermn_tpu.analysis import locks  # noqa: F401  (DL115/DL116)
from chainermn_tpu.analysis import sequence  # noqa: F401  (DL113/DL114)
from chainermn_tpu.analysis.callgraph import (  # noqa: F401
    DEFAULT_CALL_DEPTH,
    Project,
)
from chainermn_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintRun,
    RULES,
    Suppression,
    iter_python_files,
    lint_file,
    lint_paths,
    lint_source,
    run_lint,
    run_lint_sources,
)
from chainermn_tpu.analysis.output import (  # noqa: F401
    filter_new,
    fingerprints,
    from_sarif,
    load_baseline,
    to_sarif,
    write_baseline,
)
from chainermn_tpu.analysis.dataflow import (  # noqa: F401
    Analysis,
    DefUse,
    Definition,
    FlowWalker,
    ParamSummary,
)
from chainermn_tpu.analysis.hlo_passes import (  # noqa: F401
    check_collective_budget,
    check_dp_overlap,
    check_fsdp_gather_liveness,
    check_pipeline_permute_overlap,
    check_quantized_wire_dtype,
    dp_overlap_fraction,
    parse_computations,
    scheduled_entry_ops,
)

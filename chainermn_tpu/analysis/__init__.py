"""dlint — distributed-correctness static analysis for the whole stack.

Distributed training fails in ways single-process code never does:
mismatched collectives deadlock, rank-dependent control flow diverges,
channel tags collide, and overlap regressions silently serialize comms.
Compiler-level collective tooling (GC3, arxiv 2201.11840; TACCL, arxiv
2111.04867) shows collective programs are tractable objects for static
checking; this package brings that discipline in-repo as a permanent
analysis subsystem instead of per-round manual audits (the round-5
unsynced-step-loop flake was caught only by a manual AST pass — that
audit is now rule DL104).

Two pass families, one CLI (``tools/dlint.py``):

* **AST passes** (:mod:`.ast_passes`) run over Python sources —
  ``chainermn_tpu/``, ``examples/``, ``tests/``, ``tools/``:

  - ``DL101`` divergent collective under rank-dependent control flow
  - ``DL102`` eager-P2P channel-tag collision / reserved-namespace use
  - ``DL103`` root argument from the wrong rank space
  - ``DL104`` step-dispatch loop without a per-iteration sync

* **Compiled-HLO passes** (:mod:`.hlo_passes`) run over scheduled HLO
  text (``compiled.as_text()``) — the generalized form of
  ``tools/check_overlap_schedule.py``, which is now a thin wrapper:

  - ``DL201`` gradient all-reduce must overlap backward compute
  - ``DL202`` per-step collective-count budget
  - ``DL203`` 1F1B wire permutes must be async with compute inside
  - ``DL204`` degenerate FSDP all-gather prefetch (gathered layers co-live)
  - ``DL205`` quantized wire: dominant collective must carry a narrow dtype

Every rule has a stable ID, a fix-it message citing the docs
(docs/static_analysis.md catalogues each with a minimal failing
example), and positive/negative fixture tests under
``tests/analysis_tests/``. Findings are suppressed in source with a
``# dlint: disable=RULE`` comment on the flagged line (or the line
directly above it) — suppressions should carry a rationale.
"""

from chainermn_tpu.analysis import ast_passes  # noqa: F401  (registers DL1xx)
from chainermn_tpu.analysis.core import (  # noqa: F401
    Finding,
    RULES,
    iter_python_files,
    lint_file,
    lint_paths,
    lint_source,
)
from chainermn_tpu.analysis.hlo_passes import (  # noqa: F401
    check_collective_budget,
    check_dp_overlap,
    check_fsdp_gather_liveness,
    check_pipeline_permute_overlap,
    check_quantized_wire_dtype,
    dp_overlap_fraction,
    parse_computations,
    scheduled_entry_ops,
)

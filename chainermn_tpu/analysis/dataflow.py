"""Value-level dataflow for the dlint project passes.

The sequence/lock passes (PR 13) reason about *event order*; the rules
this module powers (DL118–DL122, :mod:`.dataflow_rules`) reason about
*values*: which definition a name refers to at a use site, whether a
buffer that was donated is read again, whether a PRNG key reaches two
consumers. Three layers:

* :class:`FlowWalker` — a flow-sensitive abstract interpreter over one
  function (or module) body. It executes statements in program order
  keeping an environment ``name -> frozenset[Definition]`` (reaching
  definitions): ``if``/``try`` branches interpret each arm on a copy
  and merge, loops interpret the body twice (entry pass + back-edge
  pass) so loop-carried reuse is observed, and nested ``def``/
  ``class``/``lambda`` bodies are not descended into (they run at some
  other time — a nested def only binds its name). Subclasses hook
  :meth:`~FlowWalker.on_load` / :meth:`~FlowWalker.on_call` and may
  thread a rule-specific auxiliary state through the same branch
  topology (copied at forks, merged at joins) — that is how DL118/119
  stay *path*-sensitive (a key consumed in one arm of an ``if`` is not
  "already consumed" after the join unless both arms consumed it).

* :class:`DefUse` — the vanilla subclass collecting def-use chains:
  every ``Name`` load with the definitions that reach it, every call in
  execution order, return expressions, and bare expression statements
  (for discarded-result checks). :meth:`DefUse.derived_from` closes a
  seed set of definitions over value expressions (``b = f(a)`` makes
  ``b`` derived from ``a``), optionally refusing to propagate through
  static attribute reads (``n = x.shape[0]`` does NOT make ``n``
  data-derived — shapes are trace-time constants).

* :func:`param_summary` — the interprocedural layer: per function,
  which parameters flow to its returns and which are *consumed*
  (handed to a consumer call — PRNG split/sample, a donating jit —
  directly or through further resolved calls). Summaries compose
  through :meth:`~.callgraph.Project.resolve_call` down to
  :data:`~.callgraph.DEFAULT_CALL_DEPTH` with a cycle guard and are
  memoized per :class:`Analysis`, so a lint run visits each function
  once per rule family.

Precision stance (same contract as the rest of the package,
docs/static_analysis.md#whole-program-engine): reaching definitions
are an over-approximation (a merge keeps both arms' defs) while the
rule-facing *judgments* stay under-approximate — DL118/119 only fire
when EVERY definition reaching a use is consumed/donated, so an
uncertain path silences the finding instead of raising noise.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, \
    Sequence, Set, Tuple

from chainermn_tpu.analysis.callgraph import (
    DEFAULT_CALL_DEPTH,
    FunctionInfo,
    Project,
    _attr_chain,
)

#: attribute reads that yield trace-time constants, not data — a value
#: derived only through these is NOT data-derived (DL121/DL122)
STATIC_ATTRS = ("shape", "dtype", "ndim", "size")


@dataclass(frozen=True)
class Definition:
    """One binding of a name, created per *execution* of the binding
    statement (the loop back-edge pass mints fresh definitions, which
    is what lets loop-carried rebinding read as clean)."""

    uid: int                 # unique within one walker
    name: str
    line: int
    kind: str                # "param"|"assign"|"aug"|"for"|"with"|...
    index: Optional[int] = None   # position in a tuple-unpack target


Env = Dict[str, FrozenSet[Definition]]


def walk_skipping_attrs(node: ast.AST, skip_attrs: Sequence[str] = ()):
    """``ast.walk`` that does not descend into ``x.<attr>`` reads for
    the given attribute names (nor into nested def/class/lambda)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, ast.Attribute) and n.attr in skip_attrs:
            continue
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


class FlowWalker:
    """Flow-sensitive interpreter over one scope (see module docstring).

    ``scope`` is a ``FunctionDef``/``AsyncFunctionDef`` (parameters are
    seeded as definitions), a ``Module`` (script-level statements —
    example scripts live there), or a ``Lambda``.
    """

    def __init__(self, scope: ast.AST):
        self.scope = scope
        self._next_uid = 0
        self.env: Env = {}
        self.state = self.initial_state()
        self.params: Dict[str, Definition] = {}
        self.param_names: List[str] = []         # positional order
        self.defaulted_params: Set[str] = set()  # bound at def time
        #: uid -> the value expression the definition was bound from
        self.def_value: Dict[int, Optional[ast.expr]] = {}

    # -- subclass hooks ---------------------------------------------------

    def initial_state(self):
        return None

    def copy_state(self, state):
        return state

    def merge_states(self, a, b):
        return a

    def on_load(self, node: ast.Name, defs: FrozenSet[Definition]) -> None:
        pass

    def on_call(self, call: ast.Call) -> None:
        """Fires after the call's func/args/keywords were evaluated."""

    def on_def(self, d: Definition) -> None:
        pass

    def on_expr_statement(self, value: ast.expr) -> None:
        pass

    def on_return(self, value: Optional[ast.expr]) -> None:
        pass

    # -- driving ----------------------------------------------------------

    def run(self) -> "FlowWalker":
        if isinstance(self.scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._seed_params(self.scope.args)
            self._exec_block(self.scope.body)
        elif isinstance(self.scope, ast.Lambda):
            self._seed_params(self.scope.args)
            self._eval(self.scope.body)
        else:
            self._exec_block(getattr(self.scope, "body", []))
        return self

    def _seed_params(self, args: ast.arguments) -> None:
        positional = list(args.posonlyargs) + list(args.args)
        n_defaults = len(args.defaults)
        for i, a in enumerate(positional):
            d = self._bind(a.arg, a.lineno, "param")
            self.params[a.arg] = d
            self.param_names.append(a.arg)
            if n_defaults and i >= len(positional) - n_defaults:
                self.defaulted_params.add(a.arg)
        for a, default in zip(args.kwonlyargs, args.kw_defaults):
            d = self._bind(a.arg, a.lineno, "param")
            self.params[a.arg] = d
            if default is not None:
                self.defaulted_params.add(a.arg)
        for a in (args.vararg, args.kwarg):
            if a is not None:
                self.params[a.arg] = self._bind(a.arg, a.lineno, "param")

    def _bind(self, name: str, line: int, kind: str,
              value: Optional[ast.expr] = None,
              index: Optional[int] = None) -> Definition:
        d = Definition(self._next_uid, name, line, kind, index)
        self._next_uid += 1
        self.def_value[d.uid] = value
        self.env[name] = frozenset((d,))
        self.on_def(d)
        return d

    def _snapshot(self):
        return dict(self.env), self.copy_state(self.state)

    def _restore(self, env: Env, state) -> None:
        self.env, self.state = env, state

    @staticmethod
    def _merge_env(a: Env, b: Env) -> Env:
        out = dict(a)
        for name, defs in b.items():
            out[name] = out.get(name, frozenset()) | defs
        return out

    def _merge_into(self, snaps) -> None:
        """Join the non-terminated branch exits in ``snaps``."""
        env, state = snaps[0]
        for e, s in snaps[1:]:
            env = self._merge_env(env, e)
            state = self.merge_states(state, s)
        self._restore(env, state)

    # -- statements -------------------------------------------------------

    def _exec_block(self, stmts: Iterable[ast.stmt]) -> bool:
        for st in stmts:
            if self._exec_stmt(st):
                return True
        return False

    def _exec_stmt(self, st: ast.stmt) -> bool:
        """Interpret one statement; True when the path terminates here
        (return/raise/break/continue or an If whose arms all do)."""
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in st.decorator_list:
                self._eval(dec)
            for dflt in list(st.args.defaults) + \
                    [d for d in st.args.kw_defaults if d is not None]:
                self._eval(dflt)
            self._bind(st.name, st.lineno, "def")
        elif isinstance(st, ast.ClassDef):
            for dec in st.decorator_list:
                self._eval(dec)
            for b in list(st.bases) + [k.value for k in st.keywords]:
                self._eval(b)
            self._bind(st.name, st.lineno, "def")
        elif isinstance(st, ast.Assign):
            self._eval(st.value)
            for t in st.targets:
                self._bind_target(t, st.value, "assign")
        elif isinstance(st, ast.AugAssign):
            if isinstance(st.target, ast.Name):
                self.on_load(st.target,
                             self.env.get(st.target.id, frozenset()))
            else:
                self._eval_store_base(st.target)
            self._eval(st.value)
            if isinstance(st.target, ast.Name):
                self._bind(st.target.id, st.lineno, "aug", st.value)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._eval(st.value)
                self._bind_target(st.target, st.value, "assign")
        elif isinstance(st, ast.Expr):
            self._eval(st.value)
            self.on_expr_statement(st.value)
        elif isinstance(st, ast.Return):
            self._eval(st.value)
            self.on_return(st.value)
            return True
        elif isinstance(st, ast.Raise):
            self._eval(st.exc)
            self._eval(st.cause)
            return True
        elif isinstance(st, (ast.Break, ast.Continue)):
            return True
        elif isinstance(st, ast.If):
            return self._exec_if(st)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._exec_loop(st, iter_expr=st.iter, target=st.target)
        elif isinstance(st, ast.While):
            self._exec_loop(st, test_expr=st.test)
        elif isinstance(st, ast.Try):
            return self._exec_try(st)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars,
                                      item.context_expr, "with")
            return self._exec_block(st.body)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self.env.pop(t.id, None)
                else:
                    self._eval_store_base(t)
        elif isinstance(st, (ast.Import, ast.ImportFrom)):
            for alias in st.names:
                local = alias.asname or alias.name.split(".")[0]
                if local != "*":
                    self._bind(local, st.lineno, "import")
        elif isinstance(st, ast.Assert):
            self._eval(st.test)
            self._eval(st.msg)
        elif isinstance(st, (ast.Global, ast.Nonlocal, ast.Pass)):
            pass
        else:
            # unknown statement kind (e.g. Match): over-approximate —
            # evaluate child expressions, run child blocks sequentially
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._eval(child)
            for field in ("body", "orelse", "finalbody", "cases"):
                sub = getattr(st, field, None)
                for item in sub or []:
                    if isinstance(item, ast.stmt):
                        self._exec_stmt(item)
                    else:                      # match_case
                        for s in getattr(item, "body", []) or []:
                            self._exec_stmt(s)
        return False

    def _exec_if(self, st: ast.If) -> bool:
        self._eval(st.test)
        fork = self._snapshot()
        t_body = self._exec_block(st.body)
        body_exit = self._snapshot()
        self._restore(*fork)
        t_else = self._exec_block(st.orelse)
        else_exit = self._snapshot()
        if t_body and t_else:
            return True
        if t_body:
            self._restore(*else_exit)
        elif t_else:
            self._restore(*body_exit)
        else:
            self._merge_into([body_exit, else_exit])
        return False

    def _exec_loop(self, st, iter_expr: Optional[ast.expr] = None,
                   target: Optional[ast.expr] = None,
                   test_expr: Optional[ast.expr] = None) -> None:
        if iter_expr is not None:
            self._eval(iter_expr)
        if test_expr is not None:
            self._eval(test_expr)
        entry = self._snapshot()
        if target is not None:
            self._bind_target(target, iter_expr, "for")
        self._exec_block(st.body)
        once = self._snapshot()
        # back-edge pass: reaching defs join entry ∪ first-iteration
        # exit, while the aux state continues from the first iteration
        # (iteration 2 definitely followed iteration 1 — that is how a
        # key consumed in iteration 1 and reused in iteration 2 is seen)
        self._restore(self._merge_env(entry[0], once[0]),
                      self.copy_state(once[1]))
        if target is not None:
            self._bind_target(target, iter_expr, "for")
        self._exec_block(st.body)
        twice = self._snapshot()
        # after the loop: zero, one, or more iterations all reach here
        self._merge_into([entry, once, twice])
        self._exec_block(st.orelse)

    def _exec_try(self, st: ast.Try) -> bool:
        entry = self._snapshot()
        t_body = self._exec_block(st.body)
        body_exit = self._snapshot()
        exits = []
        if not t_body:
            t_else = self._exec_block(st.orelse)
            if not t_else:
                exits.append(self._snapshot())
        # an exception may fire anywhere in the body: handlers start
        # from the join of entry and body-complete
        handler_entry = (self._merge_env(entry[0], body_exit[0]),
                         self.merge_states(self.copy_state(entry[1]),
                                           self.copy_state(body_exit[1])))
        for h in st.handlers:
            self._restore(dict(handler_entry[0]),
                          self.copy_state(handler_entry[1]))
            if h.type is not None:
                self._eval(h.type)
            if h.name:
                self._bind(h.name, h.lineno, "except")
            if not self._exec_block(h.body):
                exits.append(self._snapshot())
        if not exits:
            # every path raised/returned; run finalbody for its effects
            self._restore(*handler_entry)
            self._exec_block(st.finalbody)
            return True
        self._merge_into(exits)
        terminated = self._exec_block(st.finalbody)
        return terminated

    # -- binding targets --------------------------------------------------

    def _bind_target(self, target: ast.expr, value: Optional[ast.expr],
                     kind: str, index: Optional[int] = None) -> None:
        if isinstance(target, ast.Name):
            self._bind(target.id, target.lineno, kind, value, index)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for i, elt in enumerate(target.elts):
                self._bind_target(elt, value, kind,
                                  index=i if index is None else None)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, value, kind)
        else:                       # x.attr = ... / x[i] = ...: the base
            self._eval_store_base(target)   # is READ, nothing is bound

    def _eval_store_base(self, target: ast.expr) -> None:
        if isinstance(target, ast.Attribute):
            self._eval(target.value)
        elif isinstance(target, ast.Subscript):
            self._eval(target.value)
            self._eval(target.slice)

    # -- expressions ------------------------------------------------------

    def _eval(self, expr: Optional[ast.expr]) -> None:
        if expr is None:
            return
        if isinstance(expr, ast.Name):
            if isinstance(expr.ctx, ast.Load):
                self.on_load(expr, self.env.get(expr.id, frozenset()))
            return
        if isinstance(expr, ast.Call):
            self._eval(expr.func)
            for a in expr.args:
                self._eval(a)
            for kw in expr.keywords:
                self._eval(kw.value)
            self.on_call(expr)
            return
        if isinstance(expr, ast.Lambda):
            for dflt in list(expr.args.defaults) + \
                    [d for d in expr.args.kw_defaults if d is not None]:
                self._eval(dflt)
            saved = self._snapshot()
            for a in (list(expr.args.posonlyargs) + list(expr.args.args)
                      + list(expr.args.kwonlyargs)
                      + [x for x in (expr.args.vararg, expr.args.kwarg)
                         if x is not None]):
                self._bind(a.arg, expr.lineno, "param")
            self._eval(expr.body)
            self._restore(*saved)
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            saved = self._snapshot()
            for gen in expr.generators:
                self._eval(gen.iter)
                self._bind_target(gen.target, gen.iter, "comp")
                for cond in gen.ifs:
                    self._eval(cond)
            if isinstance(expr, ast.DictComp):
                self._eval(expr.key)
                self._eval(expr.value)
            else:
                self._eval(expr.elt)
            self.env = saved[0]     # comp targets scope out; keep state
            return
        if isinstance(expr, ast.IfExp):
            self._eval(expr.test)
            fork = self._snapshot()
            self._eval(expr.body)
            body_exit = self._snapshot()
            self._restore(*fork)
            self._eval(expr.orelse)
            self._merge_into([body_exit, self._snapshot()])
            return
        if isinstance(expr, ast.NamedExpr):
            self._eval(expr.value)
            self._bind_target(expr.target, expr.value, "assign")
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._eval(child)
            elif isinstance(child, ast.comprehension):  # unreachable
                self._eval(child.iter)


class DefUse(FlowWalker):
    """Def-use chains for one scope: every load with its reaching
    definitions, calls/returns/bare-expressions in execution order."""

    def __init__(self, scope: ast.AST):
        super().__init__(scope)
        self._loads: Dict[int, Tuple[ast.Name, Set[Definition]]] = {}
        self.calls: List[ast.Call] = []
        self._seen_calls: Set[int] = set()
        self.expr_statements: List[ast.expr] = []
        self.returns: List[Optional[ast.expr]] = []
        self.defs: List[Definition] = []

    @classmethod
    def of(cls, scope: ast.AST) -> "DefUse":
        return cls(scope).run()     # type: ignore[return-value]

    def on_load(self, node, defs):
        slot = self._loads.setdefault(id(node), (node, set()))
        slot[1].update(defs)

    def on_call(self, call):
        if id(call) not in self._seen_calls:
            self._seen_calls.add(id(call))
            self.calls.append(call)

    def on_def(self, d):
        self.defs.append(d)

    def on_expr_statement(self, value):
        if value not in self.expr_statements:
            self.expr_statements.append(value)

    def on_return(self, value):
        self.returns.append(value)

    # -- chain queries ----------------------------------------------------

    def defs_of(self, name_node: ast.Name) -> FrozenSet[Definition]:
        slot = self._loads.get(id(name_node))
        return frozenset(slot[1]) if slot else frozenset()

    def loads_in(self, expr: Optional[ast.AST],
                 skip_attrs: Sequence[str] = ()) -> Set[Definition]:
        """Definitions reaching any ``Name`` load inside ``expr``."""
        out: Set[Definition] = set()
        if expr is None:
            return out
        for n in walk_skipping_attrs(expr, skip_attrs):
            if isinstance(n, ast.Name):
                slot = self._loads.get(id(n))
                if slot:
                    out.update(slot[1])
        return out

    def derived_from(self, seeds: Iterable[Definition],
                     skip_attrs: Sequence[str] = ()) -> Set[Definition]:
        """Close ``seeds`` over value expressions: a definition whose
        bound expression reads a derived definition is derived."""
        derived = set(seeds)
        changed = True
        while changed:
            changed = False
            for d in self.defs:
                if d in derived:
                    continue
                value = self.def_value.get(d.uid)
                if value is not None and \
                        self.loads_in(value, skip_attrs) & derived:
                    derived.add(d)
                    changed = True
        return derived

    def alias_origins(self, param_indices: Dict[str, int]
                      ) -> Dict[int, Set[int]]:
        """uid -> parameter indices, propagated ONLY through pure
        aliases (``b = a``; the matching element of ``a, b = x, y``).
        This is the consumption-tracking map: a value merely *derived*
        from a parameter (``x = jnp.zeros((n,)))`` is a fresh object —
        consuming/donating it does not consume the parameter."""
        origins: Dict[int, Set[int]] = {}
        for name, idx in param_indices.items():
            d = self.params.get(name)
            if d is not None:
                origins[d.uid] = {idx}
        changed = True
        while changed:
            changed = False
            for d in self.defs:
                value = self.def_value.get(d.uid)
                if (d.index is not None
                        and isinstance(value, (ast.Tuple, ast.List))
                        and d.index < len(value.elts)):
                    value = value.elts[d.index]
                if not isinstance(value, ast.Name):
                    continue
                merged: Set[int] = set()
                for src in self.loads_in(value):
                    merged |= origins.get(src.uid, set())
                if merged - origins.get(d.uid, set()):
                    origins[d.uid] = origins.get(d.uid, set()) | merged
                    changed = True
        return origins

    def param_origins(self, param_indices: Dict[str, int],
                      skip_attrs: Sequence[str] = ()
                      ) -> Dict[int, Set[int]]:
        """uid -> set of parameter indices the definition derives from."""
        origins: Dict[int, Set[int]] = {}
        for name, idx in param_indices.items():
            d = self.params.get(name)
            if d is not None:
                origins[d.uid] = {idx}
        changed = True
        while changed:
            changed = False
            for d in self.defs:
                value = self.def_value.get(d.uid)
                if value is None:
                    continue
                merged: Set[int] = set()
                for src in self.loads_in(value, skip_attrs):
                    merged |= origins.get(src.uid, set())
                if merged - origins.get(d.uid, set()):
                    origins[d.uid] = origins.get(d.uid, set()) | merged
                    changed = True
        return origins


# ---------------------------------------------------------------------------
# interprocedural parameter summaries
# ---------------------------------------------------------------------------


@dataclass
class ParamSummary:
    """What one function does with its parameters, as seen by dataflow."""

    returned: Set[int]               # param indices flowing to a return
    consumed: Dict[int, str]         # param index -> reason text


#: a rule-supplied detector: (defuse, call, func) -> [(arg_expr, reason)]
#: for the call's arguments the rule considers consumed at that site
ConsumeDetector = Callable[[DefUse, ast.Call, FunctionInfo],
                           List[Tuple[ast.expr, str]]]


def positional_param_indices(func_node: ast.AST) -> Dict[str, int]:
    """name -> positional index for a function's parameters."""
    args = func_node.args
    names = [a.arg for a in list(args.posonlyargs) + list(args.args)]
    return {n: i for i, n in enumerate(names)}


def map_args_to_params(call: ast.Call, callee: FunctionInfo
                       ) -> Dict[int, ast.expr]:
    """callee positional-param index -> caller argument expression,
    accounting for the implicit ``self`` when a method is called
    through an attribute receiver."""
    args = callee.node.args
    names = [a.arg for a in list(args.posonlyargs) + list(args.args)]
    offset = 1 if (callee.cls is not None
                   and isinstance(call.func, ast.Attribute)) else 0
    out: Dict[int, ast.expr] = {}
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        idx = i + offset
        if idx < len(names):
            out[idx] = a
    by_name = {n: i for i, n in enumerate(names)}
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in by_name:
            out[by_name[kw.arg]] = kw.value
    return out


class Analysis:
    """Memoized dataflow over one :class:`Project`: shared
    :class:`DefUse` per scope plus per-detector parameter summaries."""

    def __init__(self, project: Project):
        self.project = project
        self._defuse: Dict[int, DefUse] = {}
        self._summaries: Dict[Tuple[int, str], ParamSummary] = {}

    @classmethod
    def of(cls, project: Project) -> "Analysis":
        """One shared instance per project, so the five dataflow rules
        interpret each function once, not five times."""
        cached = getattr(project, "_dataflow_analysis", None)
        if cached is None:
            cached = cls(project)
            project._dataflow_analysis = cached   # type: ignore[attr-defined]
        return cached

    def defuse(self, scope: ast.AST) -> DefUse:
        du = self._defuse.get(id(scope))
        if du is None:
            du = DefUse.of(scope)
            self._defuse[id(scope)] = du
        return du

    def summary(self, func: FunctionInfo, detector: ConsumeDetector,
                detector_key: str, depth: int = 0,
                _stack: Optional[Set[str]] = None) -> ParamSummary:
        """Which of ``func``'s parameters are consumed (per
        ``detector``, composed through resolved calls) or returned."""
        key = (id(func.node), detector_key)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached
        summary = ParamSummary(returned=set(), consumed={})
        self._summaries[key] = summary       # cycle guard: publish early
        stack = _stack if _stack is not None else set()
        stack.add(func.qualname)
        du = self.defuse(func.node)
        indices = positional_param_indices(func.node)
        # alias-only on purpose: "consumed" must mean THIS value was
        # handed over, not a fresh value computed from it
        origins = du.alias_origins(indices)

        def params_of(expr: ast.expr) -> Set[int]:
            out: Set[int] = set()
            for d in du.loads_in(expr):
                out |= origins.get(d.uid, set())
            return out

        for call in du.calls:
            for arg_expr, reason in detector(du, call, func):
                for p in params_of(arg_expr):
                    summary.consumed.setdefault(p, reason)
            if depth >= DEFAULT_CALL_DEPTH:
                continue
            callee = self.project.resolve_call(call, func)
            if callee is None or callee.qualname in stack:
                continue
            sub = self.summary(callee, detector, detector_key,
                               depth + 1, stack)
            if sub.consumed:
                arg_map = map_args_to_params(call, callee)
                for cidx, reason in sub.consumed.items():
                    if cidx in arg_map:
                        for p in params_of(arg_map[cidx]):
                            summary.consumed.setdefault(
                                p, f"{reason} (via {callee.name})")
        for ret in du.returns:
            if ret is not None:
                summary.returned |= params_of(ret)
        stack.discard(func.qualname)
        return summary


def scopes_in(tree: ast.AST) -> List[ast.AST]:
    """The dataflow scopes of one module: the module body itself
    (example scripts run there) plus every function/method, nested
    defs included — each analyzed independently."""
    out: List[ast.AST] = [tree]
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(n)
    return out

"""Collective-sequence abstract interpretation (DL113 / DL114).

Every function is summarized ONCE as an ordered event list:

* ``Op`` — a collective or point-to-point call, with its rule-relevant
  facts (symmetric vs P2P, direction, literal tag/peer when present)
  and source anchor;
* ``Branch`` — an ``if``, carrying whether its test is rank-dependent
  (the path condition the cross-rank checks care about) and the event
  lists of both sides. A terminating rank guard (``if rank == 0: ...;
  return``) folds the statement's fallthrough into the implicit else,
  exactly like DL101;
* ``CallSite`` — a call resolved through the :class:`~.callgraph.
  Project`; expansion happens lazily, bounded by
  :data:`~.callgraph.DEFAULT_CALL_DEPTH` with a cycle guard, so the
  summaries compose interprocedurally without exponential blowup.

Two project rules interpret the summaries:

**DL113 interprocedural-divergent-collective** — at every
rank-dependent branch, the symmetric collectives reachable from one
side (THROUGH any resolved call chain) must also be reachable from the
sibling, and a side that communicates point-to-point needs a sibling
that communicates at all. This is DL101's cross-rank agreement check
lifted over the call graph; to keep one finding per defect, DL113 only
reports divergence that crosses at least one call boundary — the
zero-hop case is DL101's, and stays there.

**DL114 send-recv-cycle** — the eager point-to-point channel graph,
built from every ``send``/``recv``-family call with a statically-known
tag across ALL modules. Two checks:

* *unmatched endpoints*: a tag that is only ever sent (or only ever
  received) anywhere in the analyzed sources strands its peer in the
  transport;
* *cycles*: within each rank path (rank-dependent branches split the
  path — the two sides run on different ranks), a ``recv(tag=a)``
  ordered before a ``send(tag=b)`` means producing ``b`` waits on
  ``a``. A strongly-connected component of that waits-before relation
  in which EVERY send of every member tag sits behind a member recv has
  no rank that can send first: circular wait, runtime deadlock.

Path conditions are tracked exactly as far as the checks need: splits
happen only at rank-dependent branches (data-dependent branches
contribute both sides to one path, an over-approximation of order),
and the per-function path count is capped (:data:`MAX_PATHS`) so
branch-heavy code cannot explode the analysis.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from chainermn_tpu.analysis.ast_passes import (
    P2P_CALLS,
    SYMMETRIC_COLLECTIVES,
    _arg_or_kw,
    _callee_name,
    _contains_rank_source,
    _kw,
    _literal,
    _tainted_names,
    _terminates,
)
from chainermn_tpu.analysis.callgraph import (
    DEFAULT_CALL_DEPTH,
    FunctionInfo,
    Project,
)
from chainermn_tpu.analysis.core import Finding, Rule, register

_DOC = "docs/static_analysis.md"

#: cap on rank paths enumerated per function (DL114); beyond it the
#: remaining splits merge, an over-approximation that only costs recall
MAX_PATHS = 32

_SENDS = {"send", "send_obj", "eager_send"}
_RECVS = {"recv", "recv_obj", "eager_recv"}


@dataclass(frozen=True)
class Op:
    kind: str                  # "sym" | "send" | "recv"
    name: str                  # callee terminal name
    path: str
    line: int
    tag: object = None         # literal tag when statically known
    peer: object = None        # literal dest/src when statically known
    via: Tuple[str, ...] = ()  # call chain (function names) to reach it


@dataclass
class Branch:
    rank_dep: bool
    line: int
    body: List[object] = field(default_factory=list)
    orelse: List[object] = field(default_factory=list)


@dataclass
class CallSite:
    callee: str                # qualname in project.functions
    line: int
    path: str


def _p2p_facts(call: ast.Call, name: str):
    """(tag, peer) literals for an eager P2P call, mirroring DL102's
    argument conventions — or (None, None) when not statically known.
    Returns ``None`` (not a tuple) when the call doesn't look like one
    of ours at all (a socket/generator ``.send`` with neither tag nor
    endpoint keyword)."""
    if name in ("send", "recv"):
        # ``tag`` only as a KEYWORD: the traced functions.send/recv
        # share these names with the eager comm API but take the peer
        # rank positionally where eager takes the tag — a positional
        # guess mistakes one for the other
        ep_name = "dest" if name == "send" else "src"
        ep = _arg_or_kw(call, 1 if name == "send" else 0, ep_name)
        tag_node = _kw(call, "tag")
        if tag_node is None and not any(
                kw.arg in ("dest", "src", "as_rank")
                for kw in call.keywords):
            return None
        return (_literal(tag_node) if tag_node is not None else 0,
                _literal(ep))
    if name in ("send_obj", "recv_obj"):
        ep = _arg_or_kw(call, 1 if name == "send_obj" else 0,
                        "dest" if name == "send_obj" else "src")
        tag_node = _arg_or_kw(call, 2 if name == "send_obj" else 1, "tag")
        return (_literal(tag_node) if tag_node is not None else 0,
                _literal(ep))
    if name in ("eager_send", "eager_recv"):
        ep = _arg_or_kw(call, 2 if name == "eager_send" else 1, "rank")
        tag_node = _kw(call, "tag")
        return (_literal(tag_node) if tag_node is not None else 0,
                _literal(ep))
    return None


class SequenceAnalysis:
    """Builds and caches per-function event summaries for one project."""

    def __init__(self, project: Project,
                 depth: int = DEFAULT_CALL_DEPTH):
        self.project = project
        self.depth = depth
        self._summaries: Dict[str, List[object]] = {}
        self._flat: Dict[Tuple[str, int], List[Op]] = {}
        self._expanded: Dict[Tuple[str, int], List[object]] = {}
        self._op_reach_map: Optional[Dict[str, bool]] = None

    # -- summarization ----------------------------------------------------

    def summary(self, func: FunctionInfo) -> List[object]:
        if func.qualname in self._summaries:
            return self._summaries[func.qualname]
        self._summaries[func.qualname] = []     # cycle guard
        tainted = _tainted_names(func.node.body)
        local_types = self.project.local_types(func)
        events = self._events(func, func.node.body, tainted, local_types)
        self._summaries[func.qualname] = events
        return events

    def _events(self, func: FunctionInfo, stmts: Sequence[ast.stmt],
                tainted: Set[str], local_types) -> List[object]:
        out: List[object] = []
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                rank_dep = _contains_rank_source(stmt.test, tainted)
                body = self._events(func, stmt.body, tainted, local_types)
                orelse = self._events(func, stmt.orelse, tainted,
                                      local_types)
                if rank_dep and _terminates(stmt.body):
                    rest = self._events(func, stmts[i + 1:], tainted,
                                        local_types)
                    out.append(Branch(True, stmt.lineno, body,
                                      orelse + rest))
                    return out
                if rank_dep and _terminates(stmt.orelse):
                    rest = self._events(func, stmts[i + 1:], tainted,
                                        local_types)
                    out.append(Branch(True, stmt.lineno, body + rest,
                                      orelse))
                    return out
                out.append(Branch(rank_dep, stmt.lineno, body, orelse))
                continue
            # loops / with / try: inline nested blocks in source order
            # (one abstract iteration — enough for agreement and
            # waits-before ordering)
            nested = []
            for name in ("body", "orelse", "finalbody"):
                blk = getattr(stmt, name, None)
                if isinstance(blk, list):
                    nested.extend(blk)
            for h in getattr(stmt, "handlers", []) or []:
                nested.extend(h.body)
            if nested:
                # the statement's own expressions (loop iterables, with
                # items) may carry calls too
                out.extend(self._expr_events(func, stmt, local_types,
                                             skip_blocks=True))
                out.extend(self._events(func, nested, tainted,
                                        local_types))
                continue
            out.extend(self._expr_events(func, stmt, local_types))
        return out

    def _expr_events(self, func: FunctionInfo, stmt: ast.stmt,
                     local_types, skip_blocks: bool = False
                     ) -> List[object]:
        out: List[object] = []
        for n in ast.walk(stmt) if not skip_blocks else \
                self._walk_header(stmt):
            if not isinstance(n, ast.Call):
                continue
            name = _callee_name(n)
            if name in SYMMETRIC_COLLECTIVES:
                out.append(Op("sym", name, func.path, n.lineno))
            elif name in P2P_CALLS:
                facts = _p2p_facts(n, name)
                if facts is None:
                    continue
                tag, peer = facts
                kind = "send" if name in _SENDS else "recv"
                out.append(Op(kind, name, func.path, n.lineno,
                              tag=tag, peer=peer))
            else:
                resolved = self.project.resolve_call(n, func, local_types)
                if resolved is not None:
                    out.append(CallSite(resolved.qualname, n.lineno,
                                        func.path))
        out.sort(key=lambda e: e.line)
        return out

    @staticmethod
    def _walk_header(stmt: ast.stmt):
        """Walk only the non-block expressions of a compound statement
        (the loop iterable, the with items, the try has none)."""
        for fieldname, value in ast.iter_fields(stmt):
            if fieldname in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.AST):
                yield from ast.walk(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.AST):
                        yield from ast.walk(v)

    # -- flattening -------------------------------------------------------

    def flatten_callee(self, qualname: str, depth: int) -> List[Op]:
        """Every Op reachable from ``qualname``'s body (both sides of
        every nested branch), call chains expanded to ``depth``, each
        Op's ``via`` rooted at this callee's name. Memoized per
        (qualname, depth) — callers prepend their own prefix."""
        key = (qualname, depth)
        cached = self._flat.get(key)
        if cached is not None:
            return cached
        self._flat[key] = []               # cycle guard
        func = self.project.functions.get(qualname)
        if func is None:
            return []
        name = qualname.split(":", 1)[-1]
        ops = self._flatten_events(self.summary(func), depth, (name,))
        self._flat[key] = ops
        return ops

    def _flatten_events(self, events: List[object], depth: int,
                        via: Tuple[str, ...]) -> List[Op]:
        out: List[Op] = []
        for ev in events:
            if isinstance(ev, Op):
                if via != ev.via:
                    ev = Op(ev.kind, ev.name, ev.path, ev.line,
                            tag=ev.tag, peer=ev.peer, via=via)
                out.append(ev)
            elif isinstance(ev, Branch):
                out.extend(self._flatten_events(ev.body, depth, via))
                out.extend(self._flatten_events(ev.orelse, depth, via))
            elif isinstance(ev, CallSite):
                if depth <= 0:
                    continue
                if not self._op_reach().get(ev.callee, False):
                    continue               # op-free subtree: nothing there
                callee = ev.callee.split(":", 1)[-1]
                if callee in via:
                    continue               # recursion: treat as opaque
                for op in self.flatten_callee(ev.callee, depth - 1):
                    if any(v in via for v in op.via):
                        continue           # cycle through the prefix
                    out.append(Op(op.kind, op.name, op.path, op.line,
                                  tag=op.tag, peer=op.peer,
                                  via=via + op.via))
        return out

    def _summaries_for(self, qualname: str) -> List[object]:
        func = self.project.functions.get(qualname)
        return self.summary(func) if func is not None else []

    # -- op reachability (expansion pruning) ------------------------------

    def _op_reach(self) -> Dict[str, bool]:
        """qualname → does any Op exist transitively in its call tree.
        One whole-project fixpoint; expansion then skips op-free
        callees entirely. Without this, a cluster of mutually recursive
        helpers is re-inlined at every distinct remaining depth — an
        exponential tree copy that buys nothing, since an op-free
        subtree can never contribute an event."""
        if self._op_reach_map is not None:
            return self._op_reach_map
        direct: Dict[str, bool] = {}
        calls: Dict[str, Set[str]] = {}

        def scan(events, q):
            for ev in events:
                if isinstance(ev, Op):
                    direct[q] = True
                elif isinstance(ev, Branch):
                    scan(ev.body, q)
                    scan(ev.orelse, q)
                elif isinstance(ev, CallSite):
                    calls[q].add(ev.callee)

        for q, func in self.project.functions.items():
            direct.setdefault(q, False)
            calls.setdefault(q, set())
            scan(self.summary(func), q)
        changed = True
        while changed:
            changed = False
            for q, callees in calls.items():
                if not direct[q] and any(direct.get(c, False)
                                         for c in callees):
                    direct[q] = True
                    changed = True
        self._op_reach_map = direct
        return direct

    # -- rank paths (DL114) -----------------------------------------------

    def rank_paths(self, qualname: str) -> List[List[Op]]:
        func = self.project.functions.get(qualname)
        if func is None:
            return []
        return self._paths(self._expanded_tree(qualname, self.depth))

    def _expanded_tree(self, qualname: str,
                       depth: int) -> List[object]:
        """The function's event tree with resolved calls inlined
        (Branch structure kept, unlike :meth:`flatten_callee`).
        Memoized per (qualname, depth); an in-progress entry (direct or
        mutual recursion) reads as empty, i.e. the recursive call is
        opaque."""
        key = (qualname, depth)
        cached = self._expanded.get(key)
        if cached is not None:
            return cached
        self._expanded[key] = []           # cycle guard
        func = self.project.functions.get(qualname)
        if func is None:
            return []
        out = self._expand(self.summary(func), depth)
        self._expanded[key] = out
        return out

    def _expand(self, events: List[object], depth: int) -> List[object]:
        out: List[object] = []
        for ev in events:
            if isinstance(ev, Op):
                out.append(ev)
            elif isinstance(ev, Branch):
                out.append(Branch(
                    ev.rank_dep, ev.line,
                    self._expand(ev.body, depth),
                    self._expand(ev.orelse, depth)))
            elif isinstance(ev, CallSite):
                if depth <= 0:
                    continue
                if not self._op_reach().get(ev.callee, False):
                    continue               # op-free subtree: nothing there
                out.extend(self._expanded_tree(ev.callee, depth - 1))
        return out

    def _paths(self, events: List[object]) -> List[List[Op]]:
        paths: List[List[Op]] = [[]]
        for ev in events:
            if isinstance(ev, Op):
                for p in paths:
                    p.append(ev)
            elif isinstance(ev, Branch):
                if ev.rank_dep and len(paths) * 2 <= MAX_PATHS:
                    body_paths = self._paths(ev.body)
                    orelse_paths = self._paths(ev.orelse)
                    paths = [p + b for p in paths for b in body_paths] \
                        + [p + o for p in paths for o in orelse_paths]
                else:
                    # merged (data-dependent, or path budget exhausted):
                    # both sides contribute, in source order
                    seq = self._flatten_events(ev.body, 0, ()) \
                        + self._flatten_events(ev.orelse, 0, ())
                    for p in paths:
                        p.extend(seq)
        return paths[:MAX_PATHS]


# ---------------------------------------------------------------------------
# DL113 — interprocedural divergent collective
# ---------------------------------------------------------------------------


def _chain_str(op: Op) -> str:
    return " -> ".join(op.via) if op.via else op.name


def _walk_branches(events, out):
    for ev in events:
        if isinstance(ev, Branch):
            out.append(ev)
            _walk_branches(ev.body, out)
            _walk_branches(ev.orelse, out)


def check_interprocedural_divergent_collective(
        project: Project) -> List[Finding]:
    analysis = SequenceAnalysis(project)
    findings: List[Finding] = []
    for qualname, func in sorted(project.functions.items()):
        branches: List[Branch] = []
        _walk_branches(analysis.summary(func), branches)
        for br in branches:
            if not br.rank_dep:
                continue
            body = analysis._flatten_events(br.body, analysis.depth, ())
            orelse = analysis._flatten_events(br.orelse, analysis.depth,
                                              ())
            for a, b in ((body, orelse), (orelse, body)):
                other_names = {o.name for o in b if o.kind == "sym"}
                other_p2p = any(o.kind in ("send", "recv") for o in b)
                for op in a:
                    if not op.via:
                        continue      # zero call hops: DL101's finding
                    if op.kind == "sym" and op.name not in other_names:
                        findings.append(Finding(
                            "DL113", func.path, br.line,
                            f"rank-dependent branch reaches collective "
                            f"'{op.name}' through the call chain "
                            f"{_chain_str(op)} ({op.path}:{op.line}) "
                            "but the sibling path never reaches it — "
                            "ranks that take the other side skip the "
                            "rendezvous and the rest deadlock. Hoist "
                            "the call out of the rank guard or make "
                            "every path reach the same collective "
                            f"sequence ({_DOC}#dl113)."))
                        break
                    if (op.kind in ("send", "recv") and not other_p2p):
                        findings.append(Finding(
                            "DL113", func.path, br.line,
                            f"rank-dependent branch reaches "
                            f"point-to-point '{op.name}' through "
                            f"{_chain_str(op)} ({op.path}:{op.line}) "
                            "with no communication on the sibling "
                            "path — the peer rank never enters the "
                            "transport and both sides hang. Pair the "
                            "send/recv across the branch or hoist it "
                            f"({_DOC}#dl113)."))
                        break
    return findings


register(Rule("DL113", "interprocedural-divergent-collective",
              f"{_DOC}#dl113",
              check_interprocedural_divergent_collective,
              kind="project"))


# ---------------------------------------------------------------------------
# DL114 — send/recv channel cycles and unmatched endpoints
# ---------------------------------------------------------------------------


def _is_worker_entry(qualname: str, project: Project) -> bool:
    """Analyze every function as a potential per-rank entry; the
    summaries are shared, so this is cheap."""
    return qualname in project.functions


def check_send_recv_cycle(project: Project) -> List[Finding]:
    analysis = SequenceAnalysis(project)
    findings: List[Finding] = []

    # ---- collect ops globally (for endpoint matching) and per path
    send_sites: Dict[object, List[Op]] = {}
    recv_sites: Dict[object, List[Op]] = {}
    all_paths: List[List[Op]] = []
    # Only summarize TOP-LEVEL behavior once per function; paths reached
    # purely as callees of another analyzed function are re-walked there,
    # which is fine for a waits-before relation (duplicates add no edge).
    for qualname in sorted(project.functions):
        for path_ops in analysis.rank_paths(qualname):
            p2p = [op for op in path_ops
                   if op.kind in ("send", "recv") and op.tag is not None]
            if p2p:
                all_paths.append(p2p)

    seen_sites: Set[Tuple[str, int, str]] = set()
    for p2p in all_paths:
        for op in p2p:
            key = (op.path, op.line, op.kind)
            if key in seen_sites:
                continue
            seen_sites.add(key)
            (send_sites if op.kind == "send"
             else recv_sites).setdefault(op.tag, []).append(op)

    # ---- unmatched endpoints
    for tag in sorted(set(send_sites) - set(recv_sites), key=repr):
        op = min(send_sites[tag], key=lambda o: (o.path, o.line))
        findings.append(Finding(
            "DL114", op.path, op.line,
            f"channel tag {tag!r} is sent here but never received "
            "anywhere in the analyzed sources — the destination rank "
            "has no matching recv, so the transport strands the "
            "message (and a rendezvous send blocks forever). Add the "
            "matching recv, or if the receiver lives outside the "
            "analyzed tree (an embedded worker script, a subprocess), "
            f"suppress with a rationale ({_DOC}#dl114)."))
    for tag in sorted(set(recv_sites) - set(send_sites), key=repr):
        op = min(recv_sites[tag], key=lambda o: (o.path, o.line))
        findings.append(Finding(
            "DL114", op.path, op.line,
            f"channel tag {tag!r} is received here but never sent "
            "anywhere in the analyzed sources — this recv blocks "
            "forever (peer death aside, nothing will ever arrive). "
            "Add the matching send, or suppress with a rationale if "
            "the sender is outside the analyzed tree "
            f"({_DOC}#dl114)."))

    # ---- waits-before cycles
    # edge a -> b: some rank path receives tag a before sending tag b
    edges: Dict[object, Set[object]] = {}
    edge_sites: Dict[Tuple[object, object], Tuple[Op, Op]] = {}
    # per send occurrence: tags received earlier on its path
    send_prevs: Dict[Tuple[str, int], Set[object]] = {}
    for p2p in all_paths:
        seen_recvs: List[Op] = []
        for op in p2p:
            if op.kind == "recv":
                seen_recvs.append(op)
            else:
                key = (op.path, op.line)
                prev = {r.tag for r in seen_recvs}
                if key in send_prevs:
                    # same send reached along several paths: it can
                    # proceed if ANY path frees it
                    send_prevs[key] &= prev
                else:
                    send_prevs[key] = set(prev)
                for r in seen_recvs:
                    edges.setdefault(r.tag, set()).add(op.tag)
                    edge_sites.setdefault((r.tag, op.tag), (r, op))

    # SCCs over the waits-before graph (iterative Tarjan is overkill at
    # this scale; simple Kosaraju-style via reachability)
    tags = sorted(edges, key=repr)
    sccs: List[Set[object]] = []
    assigned: Set[object] = set()

    def _reach(start: object) -> Set[object]:
        out, stack = set(), [start]
        while stack:
            t = stack.pop()
            for nxt in edges.get(t, ()):
                if nxt not in out:
                    out.add(nxt)
                    stack.append(nxt)
        return out

    reach = {t: _reach(t) for t in tags}
    for t in tags:
        if t in assigned:
            continue
        scc = {t} | {u for u in reach[t] if t in reach.get(u, set())}
        if len(scc) > 1 or t in edges.get(t, set()):
            sccs.append(scc)
        assigned |= scc

    for scc in sccs:
        # deadlocked iff NO send of any member tag can go first: every
        # send occurrence of every member sits behind a member recv
        free = False
        for tag in scc:
            for op in send_sites.get(tag, []):
                prevs = send_prevs.get((op.path, op.line), set())
                if not (prevs & scc):
                    free = True
                    break
            if free:
                break
        if free:
            continue
        members = sorted(scc, key=repr)
        first_tag = members[0]
        anchor = min(recv_sites.get(first_tag, [])
                     or send_sites.get(first_tag, []),
                     key=lambda o: (o.path, o.line))
        chain = ", ".join(
            f"recv({a!r}) before send({b!r}) at "
            f"{edge_sites[(a, b)][0].path}:{edge_sites[(a, b)][0].line}"
            for a in members for b in edges.get(a, ())
            if b in scc and (a, b) in edge_sites)
        findings.append(Finding(
            "DL114", anchor.path, anchor.line,
            f"send/recv cycle over channel tags {members!r}: "
            f"{chain} — every rank waits to receive before any rank "
            "sends, so no message ever enters the transport (circular "
            "wait, runtime deadlock). Break the cycle by making one "
            "endpoint send first, or split the exchange onto distinct "
            f"tags with a send-first initiator ({_DOC}#dl114)."))
    return findings


register(Rule("DL114", "send-recv-cycle", f"{_DOC}#dl114",
              check_send_recv_cycle, kind="project"))

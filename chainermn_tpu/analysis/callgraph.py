"""Whole-program symbol table and call graph for the dlint project passes.

The per-file AST passes (:mod:`.ast_passes`) are deliberately
intra-function: a branch calling ``sync_helper()`` is not credited with
the ``comm.barrier()`` inside it. This module gives the interprocedural
rules (DL113–DL116, :mod:`.sequence` / :mod:`.locks`) the missing piece:
a :class:`Project` built once per lint run over every parsed file, with

* a **module table** — file path → dotted module name (derived by
  walking ``__init__.py`` packages up from the file, so fixture
  directories without packages still resolve as flat modules);
* a **symbol table** — every module-level function and every method,
  keyed ``module:func`` / ``module:Class.method``, plus per-class
  method maps, base-class links, and ``self.attr`` types harvested from
  ``self.attr = ClassName(...)`` assignments;
* **call resolution** — :meth:`Project.resolve_call` maps a call site
  to a :class:`FunctionInfo` through plain names, ``import`` /
  ``from .. import`` bindings (absolute and relative), ``self.method``
  dispatch (bases included), attribute chains (``mod.sub.fn``), and
  locally-typed receivers (``eng = Engine(...); eng.step()`` or an
  annotated parameter).

Resolution is deliberately CONSERVATIVE: a receiver whose class is not
statically known resolves to nothing (the interprocedural passes then
treat the call as opaque) rather than guessing by method name across
every class in the repo. That keeps the project rules' findings
high-confidence at the cost of missing dynamically-dispatched chains —
the same precision/recall trade every pass in this package documents
(docs/static_analysis.md#whole-program-engine).

Call-DEPTH bounding lives in the consumers: each project pass expands
callee summaries through :meth:`Project.resolve_call` down to a fixed
depth (:data:`DEFAULT_CALL_DEPTH`) with a cycle guard, so recursion and
deep chains cannot blow up a lint run.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: how many call hops the interprocedural passes follow before treating
#: a callee as opaque (summaries are memoized, so this bounds reported
#: chain length, not runtime)
DEFAULT_CALL_DEPTH = 6

#: wrappers that forward calls to their first positional argument:
#: ``g = partial(f, x)`` / ``g = jax.jit(f)`` — calling ``g`` runs
#: ``f``. NOT ``wraps``: ``functools.wraps(f)`` returns a decorator
#: for some OTHER function, not a callable forwarding to ``f``.
_WRAPPER_NAMES = {"partial", "jit", "pjit", "pmap", "vmap",
                  "lru_cache", "cache", "checkpoint", "remat"}

#: alias-chain resolution depth cap (``h = partial(g)``;
#: ``g = jit(f)`` …) — bounds lazy re-resolution, not graph size
_ALIAS_DEPTH = 4


@dataclass
class FunctionInfo:
    """One function or method the project knows by name."""

    qualname: str                    # "module:func" | "module:Class.meth"
    module: str
    name: str                        # terminal name
    cls: Optional[str]               # owning class name, if a method
    node: ast.AST                    # the FunctionDef / AsyncFunctionDef
    path: str


@dataclass
class ClassInfo:
    name: str
    module: str
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    bases: List[str] = field(default_factory=list)      # base-class names
    attr_types: Dict[str, str] = field(default_factory=dict)
    # ``self.attr = ClassName(...)`` → attr → ClassName (project classes
    # only; harvested after every class is indexed)


@dataclass
class ModuleInfo:
    name: str
    path: str
    tree: ast.AST
    #: import bindings visible at module scope: local name → dotted
    #: module name, or (module, symbol) for ``from m import f``
    imports: Dict[str, object] = field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    #: ``g = wrapper(f, ...)`` module-level assignments: local name →
    #: the Call node, resolved LAZILY (the wrapped symbol may live in a
    #: module indexed later, and non-wrapper calls are filtered at
    #: resolution time, not here)
    alias_calls: Dict[str, ast.Call] = field(default_factory=dict)


def module_name_for(path: str) -> str:
    """Dotted module name, derived by walking enclosing packages."""
    path = os.path.abspath(path)
    base = os.path.splitext(os.path.basename(path))[0]
    parts = [] if base == "__init__" else [base]
    d = os.path.dirname(path)
    while d and os.path.exists(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return ".".join(parts) if parts else base


def _attr_chain(node: ast.expr) -> Optional[List[str]]:
    """``a.b.c`` → ``["a", "b", "c"]``; None when any link is not a
    plain name/attribute (e.g. a call or subscript in the chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.insert(0, node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.insert(0, node.id)
        return parts
    return None


def _ann_name(ann: Optional[ast.expr]) -> Optional[str]:
    """Terminal class name of an annotation (``Engine``,
    ``serving.Engine``, ``"Engine"``)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split(".")[-1].strip()
    chain = _attr_chain(ann)
    if chain:
        return chain[-1]
    return None


class Project:
    """Symbol table + call graph over one set of parsed files."""

    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}       # name → module
        self.by_path: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}   # qualname → info
        self.classes: Dict[str, List[ClassInfo]] = {}  # name → candidates
        self._local_types: Dict[str, Dict[str, str]] = {}  # memo
        self._local_aliases: Dict[int, Dict[str, ast.Call]] = {}  # memo

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, files: Dict[str, Tuple[ast.AST, str]]) -> "Project":
        """``files``: path → (parsed tree, source). Files that failed to
        parse must be filtered out by the caller (core.py reports DL000
        for them)."""
        proj = cls()
        for path in sorted(files):
            tree, _src = files[path]
            name = module_name_for(path)
            if name in proj.modules:      # collision: first (sorted) wins
                name = f"{name}@{len(proj.modules)}"
            mod = ModuleInfo(name=name, path=path, tree=tree)
            proj.modules[name] = mod
            proj.by_path[path] = mod
            proj._index_module(mod)
        proj._link_attr_types()
        return proj

    def _index_module(self, mod: ModuleInfo) -> None:
        pkg = mod.name.rsplit(".", 1)[0] if "." in mod.name else ""
        for node in mod.tree.body:
            self._index_stmt(mod, node, pkg)

    def _index_stmt(self, mod: ModuleInfo, node: ast.stmt,
                    pkg: str) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                mod.imports[local] = target
                if alias.asname is None and "." in alias.name:
                    # ``import a.b.c`` also makes the full dotted chain
                    # resolvable through attribute access on ``a``
                    mod.imports[alias.name] = alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                # relative import: climb from this module's package
                parts = mod.name.split(".")
                climb = len(parts) - node.level
                prefix = ".".join(parts[:climb]) if climb > 0 else ""
                base = f"{prefix}.{base}".strip(".") if base else prefix
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                mod.imports[local] = (base, alias.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = FunctionInfo(
                qualname=f"{mod.name}:{node.name}", module=mod.name,
                name=node.name, cls=None, node=node, path=mod.path)
            mod.functions[node.name] = info
            self.functions[info.qualname] = info
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(name=node.name, module=mod.name)
            for b in node.bases:
                chain = _attr_chain(b)
                if chain:
                    ci.bases.append(chain[-1])
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    info = FunctionInfo(
                        qualname=f"{mod.name}:{node.name}.{item.name}",
                        module=mod.name, name=item.name, cls=node.name,
                        node=item, path=mod.path)
                    ci.methods[item.name] = info
                    self.functions[info.qualname] = info
            mod.classes[node.name] = ci
            self.classes.setdefault(node.name, []).append(ci)
        elif isinstance(node, ast.Assign):
            # candidate wrapper alias: ``g = something(f, ...)`` with a
            # name/attr first argument. Whether ``something`` actually
            # forwards calls is decided lazily in _through_wrapper.
            if (isinstance(node.value, ast.Call) and node.value.args
                    and _attr_chain(node.value.args[0]) is not None):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        mod.alias_calls[t.id] = node.value
        elif isinstance(node, (ast.If, ast.Try)):
            # module-level try/if wrappers around imports/defs (the
            # optional-dependency idiom) still contribute symbols
            for blk in ([node.body] + [getattr(node, "orelse", [])]
                        + [h.body for h in getattr(node, "handlers", [])]
                        + [getattr(node, "finalbody", [])]):
                for sub in blk or []:
                    self._index_stmt(mod, sub, pkg)

    def _link_attr_types(self) -> None:
        """Second pass: harvest ``self.attr = ClassName(...)`` so a
        later ``self.attr.method()`` resolves when ClassName is a
        project class with an unambiguous name."""
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for meth in ci.methods.values():
                    for n in ast.walk(meth.node):
                        if not (isinstance(n, ast.Assign)
                                and isinstance(n.value, ast.Call)):
                            continue
                        callee = self._class_of_call(mod, n.value)
                        if callee is None:
                            continue
                        for t in n.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                ci.attr_types[t.attr] = callee.name

    # -- lookup helpers ---------------------------------------------------

    def class_named(self, name: str,
                    prefer_module: Optional[str] = None
                    ) -> Optional[ClassInfo]:
        cands = self.classes.get(name) or []
        if not cands:
            return None
        if prefer_module:
            for ci in cands:
                if ci.module == prefer_module:
                    return ci
        return cands[0] if len(cands) == 1 else None

    def _class_of_call(self, mod: ModuleInfo,
                       call: ast.Call) -> Optional[ClassInfo]:
        """The project class a constructor call instantiates, if any."""
        chain = _attr_chain(call.func)
        if not chain:
            return None
        name = chain[-1]
        if len(chain) == 1:
            if name in mod.classes:
                return mod.classes[name]
            bound = mod.imports.get(name)
            if isinstance(bound, tuple):
                target = self.modules.get(bound[0])
                if target and bound[1] in target.classes:
                    return target.classes[bound[1]]
            return None
        # mod_alias.Class(...) — resolve the module prefix
        target = self._module_for_chain(mod, chain[:-1])
        if target and name in target.classes:
            return target.classes[name]
        return None

    def _module_for_chain(self, mod: ModuleInfo,
                          chain: List[str]) -> Optional[ModuleInfo]:
        """Resolve ``["pkg", "sub"]`` (an attribute chain minus the
        terminal symbol) to a known module via the import table."""
        dotted = ".".join(chain)
        bound = mod.imports.get(dotted)
        if isinstance(bound, str):
            return self.modules.get(bound)
        bound = mod.imports.get(chain[0])
        if isinstance(bound, str):
            full = ".".join([bound] + chain[1:])
            return self.modules.get(full)
        if isinstance(bound, tuple):   # from pkg import sub
            full = ".".join([f"{bound[0]}.{bound[1]}".strip(".")]
                            + chain[1:])
            return self.modules.get(full)
        return None

    def _method_on(self, ci: ClassInfo, name: str,
                   depth: int = 0) -> Optional[FunctionInfo]:
        if name in ci.methods:
            return ci.methods[name]
        if depth >= 4:
            return None
        for base in ci.bases:
            bi = self.class_named(base, prefer_module=ci.module)
            if bi is not None:
                hit = self._method_on(bi, name, depth + 1)
                if hit is not None:
                    return hit
        return None

    def local_types(self, func: FunctionInfo) -> Dict[str, str]:
        """name → class-name for locals whose type is statically known:
        annotated parameters and ``v = ClassName(...)`` assignments.
        Memoized — the interprocedural passes revisit functions once
        per caller."""
        cached = self._local_types.get(func.qualname)
        if cached is not None:
            return cached
        mod = self.modules[func.module]
        out: Dict[str, str] = {}
        args = func.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            ann = _ann_name(a.annotation)
            if ann and self.classes.get(ann):
                out[a.arg] = ann
        for n in ast.walk(func.node):
            if (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)):
                ci = self._class_of_call(mod, n.value)
                if ci is None:
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = ci.name
            elif (isinstance(n, ast.AnnAssign)
                    and isinstance(n.target, ast.Name)):
                ann = _ann_name(n.annotation)
                if ann and self.classes.get(ann):
                    out[n.target.id] = ann
        self._local_types[func.qualname] = out
        return out

    def local_aliases(self, func: FunctionInfo) -> Dict[str, ast.Call]:
        """``g = wrapper(f, ...)`` assignments inside ``func``: name →
        the Call node (same lazy contract as
        :attr:`ModuleInfo.alias_calls`). Memoized by node identity so
        synthetic contexts (module bodies wrapped as functions by the
        dataflow rules) are safe."""
        cached = self._local_aliases.get(id(func.node))
        if cached is not None:
            return cached
        out: Dict[str, ast.Call] = {}
        for n in ast.walk(func.node):
            if (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call) and n.value.args
                    and _attr_chain(n.value.args[0]) is not None):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = n.value
        self._local_aliases[id(func.node)] = out
        return out

    # -- the resolver -----------------------------------------------------

    def resolve_call(self, call: ast.Call, ctx: FunctionInfo,
                     local_types: Optional[Dict[str, str]] = None
                     ) -> Optional[FunctionInfo]:
        """Map one call site inside ``ctx`` to a known function, or
        None when the callee is not statically known. Sees through
        forwarding wrappers: ``g = partial(f, x)`` / ``g = jax.jit(f)``
        aliases (module-level and local), inline ``jit(f)(args)``
        application, and single-level project decorators whose body
        provably forwards (returns its function parameter or a nested
        def)."""
        mod = self.modules.get(ctx.module)
        if mod is None:
            return None
        if local_types is None:
            local_types = self.local_types(ctx)
        return self._resolve_func_expr(mod, call.func, ctx,
                                       local_types, 0)

    def _decorator_forwards(self, deco: FunctionInfo) -> bool:
        """True when ``deco`` is a single-level decorator shape: it
        takes exactly ONE positional parameter (the function) and
        either returns it (identity decorator) or returns a nested def
        while CALLING the parameter somewhere in its body (the standard
        closure decorator). A factory that returns a closure over
        config it never calls (``make_step(cfg)``) is NOT a decorator —
        treating it as one would invent edges from the closure to the
        config's constructor."""
        node = deco.node
        args = node.args
        pos = list(args.posonlyargs) + list(args.args)
        if len(pos) != 1 or args.kwonlyargs:
            return False
        fn_param = pos[0].arg
        nested = {n.name for n in ast.iter_child_nodes(node)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        returns_nested = False
        param_called = False
        for n in ast.walk(node):
            if isinstance(n, ast.Return) and isinstance(n.value, ast.Name):
                if n.value.id == fn_param:
                    return True
                if n.value.id in nested:
                    returns_nested = True
            elif (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == fn_param):
                param_called = True
        return returns_nested and param_called

    def _through_wrapper(self, mod: ModuleInfo, call: ast.Call,
                         ctx: FunctionInfo, local_types: Dict[str, str],
                         depth: int) -> Optional[FunctionInfo]:
        """Resolve the function a wrapper application forwards to:
        ``partial(f, x)`` / ``jit(f)`` → ``f``. Unknown callees only
        count when they resolve to a project function that provably
        forwards (see :meth:`_decorator_forwards`) — a plain data call
        ``x = compute(y)`` is NOT an alias."""
        if depth > _ALIAS_DEPTH or not call.args:
            return None
        chain = _attr_chain(call.func)
        if chain is None:
            # decorator-factory application: ``lru_cache(None)(f)``
            inner = call.func
            if (isinstance(inner, ast.Call)
                    and (_attr_chain(inner.func) or [""])[-1]
                    in _WRAPPER_NAMES):
                return self._resolve_func_expr(mod, call.args[0], ctx,
                                               local_types, depth + 1)
            return None
        if chain[-1] not in _WRAPPER_NAMES:
            deco = self._resolve_func_expr(mod, call.func, ctx,
                                           local_types, depth + 1)
            if deco is None or not self._decorator_forwards(deco):
                return None
        return self._resolve_func_expr(mod, call.args[0], ctx,
                                       local_types, depth + 1)

    def _resolve_func_expr(self, mod: ModuleInfo, expr: ast.expr,
                           ctx: FunctionInfo,
                           local_types: Dict[str, str],
                           depth: int) -> Optional[FunctionInfo]:
        if depth > _ALIAS_DEPTH:
            return None
        if isinstance(expr, ast.Call):
            # inline application: ``jit(f)(args)`` / ``partial(f, 1)()``
            return self._through_wrapper(mod, expr, ctx,
                                         local_types, depth)
        chain = _attr_chain(expr)
        if not chain:
            return None

        if len(chain) == 1:
            name = chain[0]
            if name in mod.functions:
                return mod.functions[name]
            bound = mod.imports.get(name)
            if isinstance(bound, tuple):
                target = self.modules.get(bound[0])
                if target is not None:
                    if bound[1] in target.functions:
                        return target.functions[bound[1]]
                    # ``from m import Class`` then ``Class()`` — the
                    # constructor body runs: resolve to __init__
                    if bound[1] in target.classes:
                        return self._method_on(
                            target.classes[bound[1]], "__init__")
                    if bound[1] in target.alias_calls:
                        return self._through_wrapper(
                            target, target.alias_calls[bound[1]],
                            ctx, local_types, depth + 1)
            if name in mod.classes:
                return self._method_on(mod.classes[name], "__init__")
            local = self.local_aliases(ctx)
            if name in local:
                return self._through_wrapper(mod, local[name], ctx,
                                             local_types, depth + 1)
            if name in mod.alias_calls:
                return self._through_wrapper(mod, mod.alias_calls[name],
                                             ctx, local_types, depth + 1)
            return None

        head, meth = chain[0], chain[-1]
        if head == "self" and ctx.cls is not None:
            ci = self.class_named(ctx.cls, prefer_module=ctx.module)
            if ci is None:
                return None
            if len(chain) == 2:
                return self._method_on(ci, meth)
            if len(chain) == 3 and chain[1] in ci.attr_types:
                owner = self.class_named(ci.attr_types[chain[1]],
                                         prefer_module=ctx.module)
                if owner is not None:
                    return self._method_on(owner, meth)
            return None
        if len(chain) == 2 and head in local_types:
            ci = self.class_named(local_types[head],
                                  prefer_module=ctx.module)
            if ci is not None:
                return self._method_on(ci, meth)
        target = self._module_for_chain(mod, chain[:-1])
        if target is not None:
            if meth in target.functions:
                return target.functions[meth]
            if meth in target.classes:
                return self._method_on(target.classes[meth], "__init__")
            if meth in target.alias_calls:
                return self._through_wrapper(target,
                                             target.alias_calls[meth],
                                             ctx, local_types, depth + 1)
        return None

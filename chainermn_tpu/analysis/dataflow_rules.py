"""dlint dataflow rules DL118–DL122, DL125: value-level contracts.

These project passes stand on :mod:`.dataflow` (reaching definitions +
def-use chains + interprocedural parameter summaries) and encode the
value contracts the rest of the stack only states in prose:

* **DL118 prng-key-reuse** — a ``jax.random`` key fed to two consumers
  (or a ``split``/``fold_in`` result discarded) breaks the
  one-split-per-sampled-token replay contract (serving/sampling.py):
  reuse correlates samples silently and replay/migration stop being
  bitwise. ``fold_in(key, i)`` does NOT consume its key — folding
  varying data into one base key is the sanctioned loop idiom
  (training/step.py) — but dropping its RESULT is still flagged.
* **DL119 use-after-donation** — a value passed at a
  ``donate_argnums`` position of a jit-compiled callable and read
  afterwards: XLA reuses the donated buffer, so the read sees garbage.
  Tracked through jit aliases (``step = jax.jit(f, donate_argnums=...)``
  and ``self._fn = jax.jit(...)``) and through callees whose summary
  says a parameter is donated. ``IfExp`` donation switches
  (``donate_argnums=(0,) if donate else ()``) are deliberately opaque —
  maybe-donated must not flag.
* **DL120 nondeterministic-iteration** — iterating a ``set`` to build
  collectives, assign channel tags, or form signature/cache-key tuples:
  set order varies across processes, so ranks disagree on collective
  order or tag assignment. Dict iteration is NOT flagged (insertion
  order is a language guarantee since 3.7 — the repo relies on it).
* **DL121 host-sync-in-decode** — ``.item()``/``float()``/
  ``np.asarray``/``jax.device_get`` on a value derived from the data
  parameters of anything reachable from ``decode_k*`` functions or
  ``ServingStep`` methods: each pull serializes the decode conveyor.
  ``self`` state is not tracked (the sanctioned debug pulls like
  ``ServingStep.cursors`` read ``self.cache`` outside the token path).
* **DL122 trace-count-instability** — a Python ``if``/``while`` on a
  value derived from a traced parameter of a jit/pjit/pmap-compiled
  function: each outcome traces a separate executable (the static twin
  of DL108's runtime trace budget) or raises under tracing. Parameters
  bound by a default (the ``_k=kk`` capture idiom), listed in
  ``static_argnums``/``static_argnames``, named ``self``/``cls``, and
  bare ``is None`` tests are static and exempt.

* **DL125 draft-target-key-confusion** — a token sampled with a
  ``draft_shadow_keys`` SHADOW key row (serving/speculative.py's draft
  proposal stream) committed through an emit/commit-style call with no
  verify/accept call receiving it on the dataflow path: draft samples
  are PROPOSALS — only the target's verify pass may put tokens into a
  stream, or accepted streams stop being bitwise-identical to
  non-speculative decode and the draft's shadow splits leak into the
  real one-split-per-sampled-token key stream.

All six fire only when EVERY definition reaching the flagged use has
the hazardous property — an uncertain merge silences the finding (the
package-wide precision stance, docs/static_analysis.md#dl118).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from chainermn_tpu.analysis.ast_passes import (
    P2P_CALLS,
    SYMMETRIC_COLLECTIVES,
    _callee_name,
    _walk_excluding_defs,
)
from chainermn_tpu.analysis.callgraph import (
    DEFAULT_CALL_DEPTH,
    FunctionInfo,
    ModuleInfo,
    Project,
    _attr_chain,
)
from chainermn_tpu.analysis.core import Finding, Rule, register
from chainermn_tpu.analysis.dataflow import (
    Analysis,
    DefUse,
    FlowWalker,
    STATIC_ATTRS,
    map_args_to_params,
    positional_param_indices,
    scopes_in,
    walk_skipping_attrs,
)

_DOC = "docs/static_analysis.md"


# ---------------------------------------------------------------------------
# shared: resolving jax.random / numpy / jit name chains per module
# ---------------------------------------------------------------------------


def _chain_module(mod: Optional[ModuleInfo],
                  chain: List[str]) -> Optional[str]:
    """Dotted module a receiver chain refers to (``["jax","random"]``
    -> ``"jax.random"``, an alias ``jr`` -> its import target)."""
    if not chain:
        return None
    dotted = ".".join(chain)
    if dotted in ("jax.random", "numpy", "jax"):
        return dotted
    if mod is None:
        return None
    bound = mod.imports.get(chain[0])
    if isinstance(bound, str):
        return ".".join([bound] + chain[1:])
    if isinstance(bound, tuple):
        return ".".join([f"{bound[0]}.{bound[1]}".strip(".")] + chain[1:])
    return None


#: jax.random ops that CONSUME the key they are given (first arg or
#: ``key=``): samplers plus split. fold_in is excluded — see module doc.
_PRNG_CONSUMERS = {
    "split", "normal", "uniform", "categorical", "bernoulli", "gumbel",
    "randint", "truncated_normal", "permutation", "choice",
    "exponential", "laplace", "cauchy", "logistic", "beta", "gamma",
    "dirichlet", "poisson", "rademacher", "bits", "ball", "maxwell",
    "multivariate_normal", "orthogonal", "t", "loggamma", "weibull_min",
}

#: ops whose RESULT being discarded is the bug (the advanced key is lost)
_PRNG_PRODUCERS = {"split", "fold_in"}


def _prng_op(mod: Optional[ModuleInfo], call: ast.Call) -> Optional[str]:
    """The ``jax.random`` op name this call invokes, else None."""
    chain = _attr_chain(call.func)
    if chain is None:
        return None
    op = chain[-1]
    if op not in _PRNG_CONSUMERS | _PRNG_PRODUCERS:
        return None
    if len(chain) == 1:
        bound = mod.imports.get(op) if mod is not None else None
        if isinstance(bound, tuple) and bound[0] == "jax.random" \
                and bound[1] == op:
            return op
        return None
    return op if _chain_module(mod, chain[:-1]) == "jax.random" else None


def _prng_key_arg(call: ast.Call) -> Optional[ast.expr]:
    if call.args and not isinstance(call.args[0], ast.Starred):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _prng_consumed_args(mod: Optional[ModuleInfo], call: ast.Call
                        ) -> List[Tuple[ast.expr, str]]:
    op = _prng_op(mod, call)
    if op is None or op not in _PRNG_CONSUMERS:
        return []
    arg = _prng_key_arg(call)
    return [(arg, op)] if arg is not None else []


def _display(call: ast.Call) -> str:
    chain = _attr_chain(call.func)
    return ".".join(chain) if chain else (_callee_name(call) or "<call>")


def _functions_by_node(project: Project) -> Dict[int, FunctionInfo]:
    cached = getattr(project, "_dataflow_by_node", None)
    if cached is None:
        cached = {id(f.node): f for f in project.functions.values()}
        project._dataflow_by_node = cached   # type: ignore[attr-defined]
    return cached


def _ctx_for(project: Project, mod: ModuleInfo, scope: ast.AST
             ) -> Tuple[FunctionInfo, Optional[Dict[str, str]]]:
    """A resolve_call context for any scope: the real FunctionInfo for
    indexed functions (memoized local types), a synthetic one with
    empty local types for module bodies and nested defs."""
    info = _functions_by_node(project).get(id(scope))
    if info is not None:
        return info, None
    name = getattr(scope, "name", "<module>")
    info = FunctionInfo(
        qualname=f"{mod.name}:<{name}@{getattr(scope, 'lineno', 0)}>",
        module=mod.name, name=name, cls=None, node=scope, path=mod.path)
    return info, {}


# ---------------------------------------------------------------------------
# DL118 — prng-key-reuse
# ---------------------------------------------------------------------------


class _KeyReuseWalker(FlowWalker):
    """Path-sensitive consumption tracking: state is the set of
    ``(definition uid, literal subscript index)`` keys already fed to a
    consumer on EVERY path reaching the current point (merges
    intersect). ``ks = split(key, 3)`` used as ``ks[0]``/``ks[1]`` keeps
    distinct indices; a bare ``ks`` use conflicts with all of them."""

    def __init__(self, scope, project: Project, mod: ModuleInfo,
                 ctx: FunctionInfo, local_types, analysis: Analysis,
                 detector, findings: List[Finding]):
        super().__init__(scope)
        self.project, self.mod, self.ctx = project, mod, ctx
        self.local_types = local_types
        self.analysis, self.detector = analysis, detector
        self.findings = findings

    def initial_state(self):
        return set()

    def copy_state(self, state):
        return set(state)

    def merge_states(self, a, b):
        return a & b

    def _key_refs(self, arg: ast.expr
                  ) -> List[Tuple[int, Optional[int], str]]:
        """(uid, subscript-index, display name) per definition the key
        argument may refer to; [] when untrackable (calls, variable
        subscripts — those never flag and never mark)."""
        if isinstance(arg, ast.Name):
            return [(d.uid, None, arg.id)
                    for d in self.env.get(arg.id, frozenset())]
        if (isinstance(arg, ast.Subscript)
                and isinstance(arg.value, ast.Name)
                and isinstance(arg.slice, ast.Constant)
                and isinstance(arg.slice.value, int)):
            idx = arg.slice.value
            return [(d.uid, idx, f"{arg.value.id}[{idx}]")
                    for d in self.env.get(arg.value.id, frozenset())]
        return []

    def _conflicts(self, ref) -> bool:
        uid, idx, _name = ref
        if (uid, None) in self.state or (uid, idx) in self.state:
            return True
        return idx is None and any(u == uid for u, _i in self.state)

    def on_call(self, call: ast.Call) -> None:
        consumed = _prng_consumed_args(self.mod, call)
        ops = {op for _, op in consumed}
        if not consumed:
            callee = self.project.resolve_call(call, self.ctx,
                                               self.local_types)
            if callee is not None:
                sub = self.analysis.summary(callee, self.detector, "prng")
                if sub.consumed:
                    arg_map = map_args_to_params(call, callee)
                    consumed = [(arg_map[i], reason)
                                for i, reason in sub.consumed.items()
                                if i in arg_map]
                    ops = {f"{callee.name}()"}
        for arg, op in consumed:
            refs = self._key_refs(arg)
            if refs and all(self._conflicts(r) for r in refs):
                self.findings.append(Finding(
                    "DL118", self.mod.path, call.lineno,
                    f"PRNG key '{refs[0][2]}' is used again by "
                    f"'{op}' after already being consumed on every "
                    "path reaching this call — reusing a key "
                    "correlates samples and breaks the one-split-per-"
                    "sampled-token replay contract (serving/"
                    "sampling.py). Split and rebind first: "
                    "`key, sub = jax.random.split(key)` "
                    f"({_DOC}#dl118)."))
            self.state.update((u, i) for u, i, _n in refs)

    def on_expr_statement(self, value: ast.expr) -> None:
        if not isinstance(value, ast.Call):
            return
        op = _prng_op(self.mod, value)
        if op in _PRNG_PRODUCERS:
            self.findings.append(Finding(
                "DL118", self.mod.path, value.lineno,
                f"the result of 'jax.random.{op}' is discarded — "
                "split/fold_in RETURN the advanced key(s); dropping "
                "them leaves the caller sampling from the stale key, "
                "so every consumer downstream reuses old randomness "
                f"({_DOC}#dl118)."))


def _prng_detector(project: Project):
    def det(du: DefUse, call: ast.Call, func: FunctionInfo):
        return _prng_consumed_args(project.modules.get(func.module), call)
    return det


def check_prng_key_reuse(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    analysis = Analysis.of(project)
    det = _prng_detector(project)
    for mod in project.modules.values():
        for scope in scopes_in(mod.tree):
            ctx, local_types = _ctx_for(project, mod, scope)
            _KeyReuseWalker(scope, project, mod, ctx, local_types,
                            analysis, det, findings).run()
    return findings


register(Rule("DL118", "prng-key-reuse", f"{_DOC}#dl118",
              check_prng_key_reuse, kind="project"))


# ---------------------------------------------------------------------------
# DL119 — use-after-donation
# ---------------------------------------------------------------------------


_JIT_WRAPPERS = {"jit", "pjit", "pmap"}


def _literal_int_set(node: ast.expr) -> Optional[FrozenSet[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return frozenset((node.value,))
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            one = _literal_int_set(elt)
            if one is None:
                return None
            out |= one
        return frozenset(out)
    return None


def _donating_jit(call: ast.expr) -> Optional[FrozenSet[int]]:
    """Donated positions of a ``jax.jit(f, donate_argnums=<literal>)``
    call; None when not a jit call or the positions are not literal
    (the ``(0,) if donate else ()`` switch stays opaque on purpose)."""
    if not isinstance(call, ast.Call) \
            or _callee_name(call) not in _JIT_WRAPPERS:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            pos = _literal_int_set(kw.value)
            return pos if pos else None
    return None


def _donate_tables(mod: ModuleInfo
                   ) -> Tuple[Dict[str, FrozenSet[int]],
                              Dict[str, FrozenSet[int]]]:
    """(plain-name, self-attribute) tables of jit aliases with literal
    donated positions, harvested module-wide."""
    names: Dict[str, FrozenSet[int]] = {}
    attrs: Dict[str, FrozenSet[int]] = {}
    for n in ast.walk(mod.tree):
        if not (isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)):
            continue
        pos = _donating_jit(n.value)
        if pos is None:
            continue
        for t in n.targets:
            if isinstance(t, ast.Name):
                names[t.id] = pos
            elif (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                attrs[t.attr] = pos
    return names, attrs


def _tables_for(project: Project, mod: ModuleInfo):
    cache = getattr(project, "_dataflow_donate_tables", None)
    if cache is None:
        cache = {}
        project._dataflow_donate_tables = cache  # type: ignore[attr-defined]
    if mod.name not in cache:
        cache[mod.name] = _donate_tables(mod)
    return cache[mod.name]


def _call_donated_args(project: Project, mod: ModuleInfo, call: ast.Call
                       ) -> List[Tuple[int, ast.expr]]:
    """(position, argument expression) pairs donated at this call site
    through a jit alias or an inline jit(...)(...) application."""
    names, attrs = _tables_for(project, mod)
    fn = call.func
    pos: Optional[FrozenSet[int]] = None
    if isinstance(fn, ast.Name):
        pos = names.get(fn.id)
    elif (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
            and fn.value.id == "self"):
        pos = attrs.get(fn.attr)
    elif isinstance(fn, ast.Call):
        pos = _donating_jit(fn)
    if not pos:
        return []
    return [(i, call.args[i]) for i in sorted(pos)
            if i < len(call.args)
            and not isinstance(call.args[i], ast.Starred)]


class _DonationWalker(FlowWalker):
    """State: definition uids donated on every path so far (merges
    intersect — maybe-donated stays silent). A load whose reaching
    definitions are ALL donated is the finding; rebinding the result
    over the input (``x = step(x)``) mints a fresh definition and
    reads clean."""

    def __init__(self, scope, project: Project, mod: ModuleInfo,
                 ctx: FunctionInfo, local_types, analysis: Analysis,
                 detector, findings: List[Finding]):
        super().__init__(scope)
        self.project, self.mod, self.ctx = project, mod, ctx
        self.local_types = local_types
        self.analysis, self.detector = analysis, detector
        self.findings = findings
        self.donated_at: Dict[int, Tuple[str, int]] = {}

    def initial_state(self):
        return set()

    def copy_state(self, state):
        return set(state)

    def merge_states(self, a, b):
        return a & b

    def _mark(self, arg: ast.expr, display: str, line: int) -> None:
        if not isinstance(arg, ast.Name):
            return
        for d in self.env.get(arg.id, frozenset()):
            self.state.add(d.uid)
            self.donated_at.setdefault(d.uid, (display, line))

    def on_call(self, call: ast.Call) -> None:
        donated = _call_donated_args(self.project, self.mod, call)
        if donated:
            for _i, arg in donated:
                self._mark(arg, _display(call), call.lineno)
            return
        callee = self.project.resolve_call(call, self.ctx,
                                           self.local_types)
        if callee is None:
            return
        sub = self.analysis.summary(callee, self.detector, "donate")
        if not sub.consumed:
            return
        arg_map = map_args_to_params(call, callee)
        for cidx in sub.consumed:
            if cidx in arg_map:
                self._mark(arg_map[cidx], callee.name, call.lineno)

    def on_load(self, node: ast.Name, defs) -> None:
        if not defs or not all(d.uid in self.state for d in defs):
            return
        display, line = self.donated_at.get(
            next(iter(defs)).uid, ("a donating jit call", node.lineno))
        self.findings.append(Finding(
            "DL119", self.mod.path, node.lineno,
            f"'{node.id}' is read after being donated to "
            f"'{display}' (line {line}) — XLA reuses a donated "
            "buffer's memory, so this read sees garbage or crashes. "
            "Rebind the step result over the input "
            f"(`{node.id} = {display}(...)`) or drop donation for "
            f"this argument ({_DOC}#dl119)."))


def _donate_detector(project: Project):
    def det(du: DefUse, call: ast.Call, func: FunctionInfo):
        mod = project.modules.get(func.module)
        if mod is None:
            return []
        return [(arg, f"donated at position {i}")
                for i, arg in _call_donated_args(project, mod, call)]
    return det


def check_use_after_donation(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    analysis = Analysis.of(project)
    det = _donate_detector(project)
    for mod in project.modules.values():
        for scope in scopes_in(mod.tree):
            ctx, local_types = _ctx_for(project, mod, scope)
            _DonationWalker(scope, project, mod, ctx, local_types,
                            analysis, det, findings).run()
    return findings


register(Rule("DL119", "use-after-donation", f"{_DOC}#dl119",
              check_use_after_donation, kind="project"))


# ---------------------------------------------------------------------------
# DL120 — nondeterministic-iteration
# ---------------------------------------------------------------------------


_SET_MAKERS = {"set", "frozenset"}
_SET_METHODS = {"union", "intersection", "difference",
                "symmetric_difference", "copy"}
#: names whose assignment from ``tuple(<set>)`` marks a signature/key
_SIG_NAME_HINTS = ("sig", "signature", "key", "fingerprint")
#: iterator wrappers that preserve the argument's (non)order
_ORDER_PRESERVING = {"enumerate", "list", "tuple", "iter"}


def _set_typed_defs(du: DefUse) -> Set[int]:
    """uids of definitions that are statically set-typed (literals,
    ``set()``/``frozenset()`` calls, set methods returning sets, plain
    copies, and set-algebra BinOps over set-typed names)."""
    sets: Set[int] = set()

    def names_all_set(expr: ast.expr) -> bool:
        if not isinstance(expr, ast.Name):
            return False
        defs = du.defs_of(expr)
        return bool(defs) and all(d.uid in sets for d in defs)

    def is_set_expr(v: ast.expr) -> bool:
        if isinstance(v, (ast.Set, ast.SetComp)):
            return True
        if isinstance(v, ast.Call):
            name = _callee_name(v)
            if name in _SET_MAKERS:
                return True
            if (name in _SET_METHODS
                    and isinstance(v.func, ast.Attribute)
                    and names_all_set(v.func.value)):
                return True
            return False
        if isinstance(v, ast.Name):
            return names_all_set(v)
        if isinstance(v, ast.BinOp) and isinstance(
                v.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            return names_all_set(v.left) or names_all_set(v.right)
        return False

    changed = True
    while changed:
        changed = False
        for d in du.defs:
            if d.uid in sets or d.index is not None:
                continue
            v = du.def_value.get(d.uid)
            if v is not None and is_set_expr(v):
                sets.add(d.uid)
                changed = True
    return sets


def _iterated_set(du: DefUse, sets: Set[int],
                  it: ast.expr) -> Optional[str]:
    """Display name when a ``for`` iterates a set (directly, through a
    literal, or through an order-preserving wrapper); None otherwise
    (``sorted(s)`` reads clean here)."""
    while (isinstance(it, ast.Call)
            and _callee_name(it) in _ORDER_PRESERVING and it.args):
        it = it.args[0]
    if isinstance(it, (ast.Set, ast.SetComp)):
        return "a set literal"
    if isinstance(it, ast.Call) and _callee_name(it) in _SET_MAKERS:
        return f"'{_callee_name(it)}(...)'"
    if isinstance(it, ast.Name):
        defs = du.defs_of(it)
        if defs and all(d.uid in sets for d in defs):
            return f"'{it.id}'"
    return None


def check_nondeterministic_iteration(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    analysis = Analysis.of(project)
    for mod in project.modules.values():
        for scope in scopes_in(mod.tree):
            du = analysis.defuse(scope)
            sets = _set_typed_defs(du)
            body = getattr(scope, "body", [])
            if not isinstance(body, list):
                continue
            for n in _walk_excluding_defs(body):
                if isinstance(n, (ast.For, ast.AsyncFor)):
                    what = _iterated_set(du, sets, n.iter)
                    if what is None:
                        continue
                    hazard = _loop_body_comm_hazard(n.body)
                    if hazard is None:
                        continue
                    findings.append(Finding(
                        "DL120", mod.path, n.lineno,
                        f"iterating {what} — a set — drives {hazard}: "
                        "set iteration order differs across processes "
                        "and runs, so ranks disagree on collective "
                        "order / channel-tag assignment and deadlock "
                        "or cross wires. Iterate "
                        f"sorted({what.strip(chr(39))}) instead "
                        f"({_DOC}#dl120)."))
                elif isinstance(n, ast.Assign):
                    hit = _sig_tuple_from_set(du, sets, n)
                    if hit is not None:
                        findings.append(Finding(
                            "DL120", mod.path, n.lineno,
                            f"'{hit}' is a signature/key tuple built "
                            "from a set — its element order varies "
                            "per process, so trace signatures and "
                            "cache keys stop matching across ranks. "
                            "Build it from sorted(...) "
                            f"({_DOC}#dl120)."))
    return findings


def _loop_body_comm_hazard(body: List[ast.stmt]) -> Optional[str]:
    for n in _walk_excluding_defs(body):
        if not isinstance(n, ast.Call):
            continue
        name = _callee_name(n)
        if name in SYMMETRIC_COLLECTIVES:
            return f"the collective '{name}'"
        if name in P2P_CALLS:
            return f"the P2P call '{name}'"
        if any(kw.arg == "tag" for kw in n.keywords):
            return f"'{name}(tag=...)' channel-tag assignment"
    return None


def _sig_tuple_from_set(du: DefUse, sets: Set[int],
                        assign: ast.Assign) -> Optional[str]:
    v = assign.value
    if not (isinstance(v, ast.Call) and _callee_name(v) in
            ("tuple", "list") and v.args
            and isinstance(v.args[0], ast.Name)):
        return None
    defs = du.defs_of(v.args[0])
    if not defs or not all(d.uid in sets for d in defs):
        return None
    for t in assign.targets:
        if isinstance(t, ast.Name) and any(
                h in t.id.lower() for h in _SIG_NAME_HINTS):
            return t.id
    return None


register(Rule("DL120", "nondeterministic-iteration", f"{_DOC}#dl120",
              check_nondeterministic_iteration, kind="project"))


# ---------------------------------------------------------------------------
# DL121 — host-sync-in-decode
# ---------------------------------------------------------------------------


_HOST_PULL_ATTRS = {"item", "tolist"}


def _host_sync_target(mod: ModuleInfo, call: ast.Call
                      ) -> Optional[Tuple[ast.expr, str]]:
    """(pulled expression, display) when the call synchronously moves a
    device value to host: .item()/.tolist(), float(), numpy
    asarray/array, jax.device_get."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _HOST_PULL_ATTRS:
        return fn.value, f".{fn.attr}()"
    chain = _attr_chain(fn)
    if chain is None:
        return None
    arg = call.args[0] if call.args else None
    if arg is None:
        return None
    if chain == ["float"]:
        return arg, "float()"
    if len(chain) >= 2 and chain[-1] in ("asarray", "array") \
            and _chain_module(mod, chain[:-1]) == "numpy":
        return arg, f"np.{chain[-1]}"
    if chain[-1] == "device_get" \
            and (len(chain) == 1
                 or _chain_module(mod, chain[:-1]) == "jax"):
        return arg, "jax.device_get"
    return None


def _decode_roots(project: Project) -> List[FunctionInfo]:
    # test functions whose NAME mentions decode_k are assertions about
    # the hot path, not the hot path — they pull to host by design
    return [f for f in project.functions.values()
            if not f.name.startswith("test")
            and ("decode_k" in f.name
                 or (f.cls is not None and "ServingStep" in f.cls))]


def check_host_sync_in_decode(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    analysis = Analysis.of(project)
    roots = _decode_roots(project)
    # reachable set: qualname -> (FunctionInfo, root it was reached from)
    reached: Dict[str, Tuple[FunctionInfo, str]] = {}
    frontier = [(f, f.name, 0) for f in roots]
    while frontier:
        func, root, depth = frontier.pop()
        if func.qualname in reached or depth > DEFAULT_CALL_DEPTH:
            continue
        reached[func.qualname] = (func, root)
        for n in ast.walk(func.node):
            if isinstance(n, ast.Call):
                callee = project.resolve_call(n, func)
                if callee is not None:
                    frontier.append((callee, root, depth + 1))
    for func, root in reached.values():
        mod = project.modules.get(func.module)
        if mod is None:
            continue
        du = analysis.defuse(func.node)
        indices = {n: i for n, i
                   in positional_param_indices(func.node).items()
                   if n not in ("self", "cls")}
        origins = du.param_origins(indices, skip_attrs=STATIC_ATTRS)
        data_uids = {uid for uid, srcs in origins.items() if srcs}
        for call in du.calls:
            hit = _host_sync_target(mod, call)
            if hit is None:
                continue
            pulled, display = hit
            if any(d.uid in data_uids
                   for d in du.loads_in(pulled, STATIC_ATTRS)):
                where = func.name if func.name == root \
                    else f"{func.name} (reached from {root})"
                findings.append(Finding(
                    "DL121", func.path, call.lineno,
                    f"host-device sync '{display}' on a value derived "
                    f"from the data arguments of '{where}' — the "
                    "decode hot path must stay device-resident; every "
                    "per-token pull stalls the conveyor behind a "
                    "device round-trip. Keep the value on device "
                    "(jnp ops) or hoist the pull out of the decode/"
                    f"step loop ({_DOC}#dl121)."))
    return findings


register(Rule("DL121", "host-sync-in-decode", f"{_DOC}#dl121",
              check_host_sync_in_decode, kind="project"))


# ---------------------------------------------------------------------------
# DL122 — trace-count-instability
# ---------------------------------------------------------------------------


def _static_marks(keywords: List[ast.keyword]
                  ) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in keywords:
        if kw.arg in ("static_argnums", "static_broadcasted_argnums"):
            lit = _literal_int_set(kw.value)
            if lit:
                nums |= lit
        elif kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value,
                                                              str):
                    names.add(v.value)
    return nums, names


def _jit_compiled_targets(mod: ModuleInfo
                          ) -> List[Tuple[ast.AST, Set[int], Set[str]]]:
    """(function node, static positions, static names) for every
    function this module compiles with jit/pjit/pmap — by decorator or
    by ``jit(f, ...)`` application anywhere (nested defs included)."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for n in ast.walk(mod.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(n.name, []).append(n)
    out: List[Tuple[ast.AST, Set[int], Set[str]]] = []
    seen: Set[int] = set()

    def add(node: ast.AST, nums: Set[int], names: Set[str]) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            out.append((node, nums, names))

    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and _callee_name(n) in _JIT_WRAPPERS \
                and n.args and isinstance(n.args[0], ast.Name):
            cands = defs_by_name.get(n.args[0].id, [])
            if len(cands) == 1:
                nums, names = _static_marks(n.keywords)
                add(cands[0], nums, names)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                chain = _attr_chain(dec)
                if chain and chain[-1] in _JIT_WRAPPERS:
                    add(n, set(), set())
                elif isinstance(dec, ast.Call):
                    dn = _callee_name(dec)
                    if dn in _JIT_WRAPPERS:
                        nums, names = _static_marks(dec.keywords)
                        add(n, nums, names)
                    elif dn == "partial" and dec.args:
                        inner = _attr_chain(dec.args[0])
                        if inner and inner[-1] in _JIT_WRAPPERS:
                            nums, names = _static_marks(dec.keywords)
                            add(n, nums, names)
    return out


def _is_none_compare(n: ast.AST) -> bool:
    return (isinstance(n, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops)
            and all(isinstance(c, ast.Constant) and c.value is None
                    for c in n.comparators))


def _test_loads(du: DefUse, test: ast.expr):
    """Name loads in a branch test, skipping ``is None`` comparisons
    (optional-argument dispatch is trace-stable) and static attribute
    reads (``x.shape[0]`` is a trace-time constant)."""
    stack = [test]
    while stack:
        n = stack.pop()
        if _is_none_compare(n):
            continue
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            continue
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Name):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def check_trace_count_instability(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    analysis = Analysis.of(project)
    for mod in project.modules.values():
        for node, static_nums, static_names in _jit_compiled_targets(mod):
            du = analysis.defuse(node)
            indices = positional_param_indices(node)
            static = set(static_names) | {"self", "cls"} \
                | du.defaulted_params \
                | {n for n, i in indices.items() if i in static_nums}
            traced = {n: i for n, i in indices.items() if n not in static}
            if not traced:
                continue
            origins = du.param_origins(traced, skip_attrs=STATIC_ATTRS)
            data_uids = {uid for uid, srcs in origins.items() if srcs}
            for n in _walk_excluding_defs(node.body):
                if not isinstance(n, (ast.If, ast.While)):
                    continue
                culprit = None
                for name_node in _test_loads(du, n.test):
                    if any(d.uid in data_uids
                           for d in du.defs_of(name_node)):
                        culprit = name_node.id
                        break
                if culprit is None:
                    continue
                kind = "if" if isinstance(n, ast.If) else "while"
                findings.append(Finding(
                    "DL122", mod.path, n.lineno,
                    f"Python '{kind}' on '{culprit}' — derived from a "
                    f"traced argument of jit-compiled '{node.name}' — "
                    "either raises under tracing or traces one "
                    "executable per outcome, destabilizing the trace "
                    "count DL108 budgets at runtime. Use "
                    "jax.lax.cond/jnp.where for data branching, or "
                    "declare the driving argument in static_argnums "
                    f"({_DOC}#dl122)."))
    return findings


register(Rule("DL122", "trace-count-instability", f"{_DOC}#dl122",
              check_trace_count_instability, kind="project"))


# ---------------------------------------------------------------------------
# DL125 — draft-target-key-confusion
# ---------------------------------------------------------------------------


#: the taint source: serving/sampling.py's shadow-copy of the target's
#: key rows for a draft proposal pass
_DRAFT_KEY_MAKER = "draft_shadow_keys"
#: samplers whose (logits, keys) call shape the rule understands
_DRAFT_SAMPLERS = {"sample_tokens"}
#: a call whose name carries one of these receives the token for
#: target-side verification — the blessing that makes a commit legal
_VERIFY_HINTS = ("verify", "accept")
#: commit-style sinks a raw draft sample must never reach
_COMMIT_SINKS = {"emit", "_emit", "commit", "commit_token",
                 "record_token", "append", "push", "send", "publish"}


def _call_name(call: ast.Call) -> str:
    chain = _attr_chain(call.func)
    return (chain[-1] if chain else _callee_name(call)) or ""


class _DraftKeyWalker(FlowWalker):
    """Taint tracking for the speculative-decoding PRNG contract.

    ``draft_shadow_keys(...)`` results are SHADOW keys; a
    ``sample_tokens`` call keyed by one yields a DRAFT token (result 0)
    and a new shadow key (result 1). Path state is the set of draft-
    token defs a verify/accept call has received on every path (merges
    intersect — maybe-verified stays silent); a commit-style call whose
    argument's reaching definitions are all unverified draft tokens is
    the finding."""

    def __init__(self, scope, mod: ModuleInfo, findings: List[Finding]):
        super().__init__(scope)
        self.mod = mod
        self.findings = findings
        self.shadow_keys: Set[int] = set()
        self.draft_toks: Set[int] = set()
        # per-sampler-call "keyed by shadow rows" verdict: the walker
        # binds each tuple-unpack target (and its env entry) before
        # on_def fires, so by the time the REBOUND key target of
        # ``tok, shadow = sample_tokens(.., shadow, ..)`` is processed
        # the key argument resolves to the def being created; the
        # verdict cached while processing the token target is the one
        # that saw the pre-bind environment
        self._keyed_calls: Dict[int, bool] = {}

    def initial_state(self):
        return set()

    def copy_state(self, state):
        return set(state)

    def merge_states(self, a, b):
        return a & b

    def _name_defs(self, expr) -> FrozenSet:
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id, frozenset())
        return frozenset()

    def on_def(self, d) -> None:
        v = self.def_value.get(d.uid)
        if not isinstance(v, ast.Call):
            return
        name = _call_name(v)
        if name == _DRAFT_KEY_MAKER:
            if d.index in (None, 0):
                self.shadow_keys.add(d.uid)
            return
        if name in _DRAFT_SAMPLERS:
            key_arg = (v.args[1] if len(v.args) > 1
                       and not isinstance(v.args[1], ast.Starred)
                       else None)
            for kw in v.keywords:
                if kw.arg in ("keys", "key"):
                    key_arg = kw.value
            refs = self._name_defs(key_arg)
            keyed = bool(refs) and all(
                r.uid in self.shadow_keys for r in refs)
            keyed = keyed or self._keyed_calls.get(id(v), False)
            self._keyed_calls[id(v)] = keyed
            if keyed:
                if d.index in (None, 0):
                    self.draft_toks.add(d.uid)
                elif d.index == 1:
                    # the advanced shadow key stays a shadow key
                    self.shadow_keys.add(d.uid)

    def on_call(self, call: ast.Call) -> None:
        name = _call_name(call)
        low = name.lower()
        if any(h in low for h in _VERIFY_HINTS):
            args = list(call.args) + [kw.value for kw in call.keywords]
            for arg in args:
                for d in self._name_defs(arg):
                    if d.uid in self.draft_toks:
                        self.state.add(d.uid)
            return
        if name not in _COMMIT_SINKS:
            return
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                continue
            refs = self._name_defs(arg)
            if refs and all(r.uid in self.draft_toks for r in refs) \
                    and any(r.uid not in self.state for r in refs):
                tok = arg.id if isinstance(arg, ast.Name) else "<token>"
                self.findings.append(Finding(
                    "DL125", self.mod.path, call.lineno,
                    f"'{tok}' was sampled with a draft_shadow_keys "
                    f"SHADOW key row and is committed by '{name}' "
                    "with no verify/accept call receiving it on this "
                    "path — draft samples are proposals; only the "
                    "target's verify pass may put tokens into a "
                    "stream, or accepted streams stop being bitwise "
                    "and the shadow key splits leak into the real "
                    "one-split-per-sampled-token stream (serving/"
                    f"speculative.py; {_DOC}#dl125)."))


def check_draft_target_key_confusion(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules.values():
        for scope in scopes_in(mod.tree):
            _DraftKeyWalker(scope, mod, findings).run()
    return findings


register(Rule("DL125", "draft-target-key-confusion", f"{_DOC}#dl125",
              check_draft_target_key_confusion, kind="project"))

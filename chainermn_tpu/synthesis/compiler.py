"""Compile a validated sketch program to a shard_map GradReducer.

The lowering is deliberately small: a :class:`~.sketch.Program` is a
linear sequence of per-tier collectives, so the compiled form is a
walk over the steps applying the matching ``lax`` collective to a flat
bucket vector. The machinery that makes it correct:

* :class:`_TierMap` resolves the program's ``tier_sizes`` onto the
  communicator's mesh — one named axis per tier (a ``('dcn', 'ici')``
  style mesh, innermost tier = LAST axis, same rule as
  ``collectives.hierarchical.HierTopology``) or a single axis factored
  into mixed-radix coordinates addressed with ``axis_index_groups``
  (rank ``r = Σ cᵢ·strideᵢ``, ``stride₀ = 1`` — tier 0 is the
  fastest-varying coordinate, generalizing HierTopology's
  ``r = g·intra + j`` to any number of tiers);
* scatter stages divide evenly because each bucket is padded to the
  product of every scattered tier size (``sketch._scatter_quantum``,
  the same quantum the wire accounting uses);
* quantized wire regions lower to the blockwise codec of
  ``collectives.quantized`` with the scale ``pmax`` and the integer
  accumulation both restricted to the region's tier group — the
  collective in the compiled HLO carries the narrow dtype (DL205), and
  ICI-local stages outside the region stay exact f32;
* error feedback follows the ``QuantizedReducer`` discipline, but the
  residual lives in the frame the region QUANTIZES in (the scattered
  chunk for slow-tier-only placement) — per-rank state threaded
  through ``_ReducerWrappedState`` so checkpoints and resume keep
  working unchanged.

Registered as strategy ``'synth'``; ``make_grad_reducer('synth', comm,
program=...)`` accepts a :class:`~.sketch.Program` or its ``to_dict``
form (what a tuned :class:`~chainermn_tpu.tuning.profile_db.
SchedulePlan` carries), validates it with :func:`~.sketch.
check_program`, and refuses a communicator whose size doesn't factor
as the program's ``tier_sizes``.

Numerics: programs without wire steps are bitwise-equal to ``flat`` on
integer-valued floats (the PR 4/8 parity contract —
tests/synthesis_tests/test_synth_reducer.py pins it over every
enumerated program on two topologies including a 3-tier one).
"""

from __future__ import annotations

import itertools
import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.collectives.base import (
    GradReducer,
    register_reducer,
    varying_axes,
)
from chainermn_tpu.collectives.quantized import _QMAX, QUANT_BLOCK
from chainermn_tpu.comm.xla import plan_buckets
from chainermn_tpu.synthesis.sketch import (
    Program,
    _scatter_quantum,
    check_program,
    program_wire_bytes,
)
from chainermn_tpu.utils import match_vma


class _TierMap:
    """The program's tiers resolved onto the communicator's mesh."""

    def __init__(self, comm, tier_sizes: Tuple[int, ...]):
        axes = comm.axis_names
        self.sizes = tuple(int(s) for s in tier_sizes)
        n = math.prod(self.sizes)
        if n != comm.size:
            raise ValueError(
                f"program tier sizes {self.sizes} multiply to {n} but "
                f"the communicator has {comm.size} ranks — a plan "
                "synthesized for one decomposition must not silently "
                "run another")
        if len(axes) == 1:
            self.mode = "groups"
            self.ax = axes[0]
            self.groups = [self._tier_groups(i)
                           for i in range(len(self.sizes))]
            return
        if len(axes) == len(self.sizes):
            self.mode = "axes"
            mesh_sizes = dict(zip(comm.mesh.axis_names,
                                  comm.mesh.devices.shape))
            # innermost/fastest tier is the LAST mesh axis (the
            # ('dcn', 'ici') factory layout — HierTopology's rule)
            self.axis_of = tuple(reversed(axes))
            for i, ax in enumerate(self.axis_of):
                if mesh_sizes[ax] != self.sizes[i]:
                    raise ValueError(
                        f"tier {i} has size {self.sizes[i]} but mesh "
                        f"axis {ax!r} has {mesh_sizes[ax]}")
            return
        raise ValueError(
            f"cannot map {len(self.sizes)} tiers onto mesh axes "
            f"{axes}: need a single axis (factored via "
            "axis_index_groups) or exactly one axis per tier")

    def _tier_groups(self, i: int) -> List[List[int]]:
        """Rank groups that vary tier ``i``'s coordinate and fix every
        other — mixed-radix, tier 0 fastest-varying."""
        strides, st = [], 1
        for s in self.sizes:
            strides.append(st)
            st *= s
        others = [t for t in range(len(self.sizes)) if t != i]
        groups = []
        for combo in itertools.product(
                *[range(self.sizes[t]) for t in others]):
            base = sum(c * strides[t] for c, t in zip(combo, others))
            groups.append([base + k * strides[i]
                          for k in range(self.sizes[i])])
        return groups

    # -- per-tier collectives (flat vectors, inside shard_map) ---------
    def psum(self, v, i: int):
        if self.mode == "axes":
            return lax.psum(v, self.axis_of[i])
        return lax.psum(v, self.ax, axis_index_groups=self.groups[i])

    def psum_scatter(self, v, i: int):
        if self.mode == "axes":
            return lax.psum_scatter(v, self.axis_of[i], tiled=True)
        return lax.psum_scatter(v, self.ax,
                                axis_index_groups=self.groups[i],
                                tiled=True)

    def all_gather(self, v, i: int):
        if self.mode == "axes":
            return lax.all_gather(v, self.axis_of[i], tiled=True)
        return lax.all_gather(v, self.ax,
                              axis_index_groups=self.groups[i],
                              tiled=True)

    def pmax(self, x, i: int):
        if self.mode == "axes":
            return lax.pmax(x, self.axis_of[i])
        return lax.pmax(x, self.ax, axis_index_groups=self.groups[i])


def _q_allreduce_tier(tm: _TierMap, v, i: int, mode: str):
    """Quantized psum restricted to tier ``i``'s group: the scale pmax
    and the integer accumulation both stay inside the group, so every
    group member quantizes onto the same grid (the precondition for
    integer accumulation — same contract as
    ``collectives.quantized.quantize_allreduce``, which only spans
    whole named axes and can't address a factored tier). Returns
    ``(reduced_sum, local_dequant)``; the dequantize is fused onto the
    collective output (narrow wire in the compiled HLO — DL205)."""
    dt = v.dtype
    if mode == "bf16":
        q = v.astype(jnp.bfloat16)
        return tm.psum(q, i).astype(dt), q.astype(dt)
    qmax = _QMAX[mode]
    pad = (-v.size) % QUANT_BLOCK
    vp = jnp.concatenate([v, jnp.zeros((pad,), dt)]) if pad else v
    b = vp.reshape(-1, QUANT_BLOCK)
    amax = tm.pmax(jnp.max(jnp.abs(b), axis=1), i)
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(dt)
    q = jnp.clip(jnp.round(b / scale[:, None]),
                 -qmax, qmax).astype(jnp.int32)
    red = tm.psum(q, i)  # s32 on the wire (narrow — DL205)
    deq = (red.astype(dt) * scale[:, None]).reshape(-1)
    loc = (q.astype(dt) * scale[:, None]).reshape(-1)
    return deq[:v.size], loc[:v.size]


class SynthesizedReducer(GradReducer):
    """A sketch program lowered to the GradReducer contract.

    Args (beyond the base): ``program`` — a :class:`~.sketch.Program`
    or its ``to_dict`` form (required; validated with
    :func:`~.sketch.check_program`); ``ef`` — carry error-feedback
    residuals for quantized programs (default True; lossless programs
    are stateless regardless); ``wire_format`` — accepted for registry
    parity and checked against the program's own wire (a plan's
    recorded format must match the program it rode in with).
    """

    name = "synth"
    wire_formats = ("f32", "bf16", "int8-block", "int4-block")

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 bucket_order: str = "emission",
                 program=None, ef: bool = True,
                 wire_format: Optional[str] = None):
        super().__init__(comm, op, bucket_bytes, bucket_order)
        if program is None:
            raise ValueError(
                "SynthesizedReducer needs program= (a synthesis.Program "
                "or its to_dict form — enumerate with "
                "synthesis.enumerate_programs or tools/synth.py)")
        if isinstance(program, dict):
            program = Program.from_dict(program)
        errs = check_program(program)
        if errs:
            raise ValueError(
                f"invalid program {program.name!r}: " + "; ".join(errs))
        if wire_format is not None and wire_format != program.wire_format:
            raise ValueError(
                f"wire_format={wire_format!r} but program "
                f"{program.name!r} carries {program.wire_format!r} — "
                "the format is part of the program, not a separate knob")
        self.program = program
        self.tiers = _TierMap(comm, program.tier_sizes)
        self.ef = bool(ef)
        self._n_regions = sum(1 for s in program.steps
                              if s.op == "quantize")
        self.stateful = bool(self.ef and self._n_regions)

    # -- the static bucket plan (QuantizedReducer's discipline: a pure
    # function of leaf shapes/dtypes so the EF state layout is stable
    # across traces and checkpoint round-trips) -------------------------
    def _plan(self, leaves):
        """``[(dtype, run_program?, [leaf indices])]`` — float buckets
        run the program; integer gradients take one exact psum (a
        quantized or decomposed integer gradient buys nothing)."""
        from collections import defaultdict

        by_dt = defaultdict(list)
        for i, l in enumerate(leaves):
            by_dt[jnp.dtype(l.dtype)].append(i)
        plan = []
        for dt, idxs in by_dt.items():
            run = bool(jnp.issubdtype(dt, jnp.floating))
            for bucket in plan_buckets(
                    [(i, leaves[i].size * dt.itemsize) for i in idxs],
                    self.bucket_bytes):
                plan.append((dt, run, bucket))
        return plan

    def _residual_lens(self, bucket_elems: int) -> List[int]:
        """Vector length at each quantize step's entry — the frame the
        region's residual lives in (the scattered chunk for slow-tier
        placements, not the full bucket)."""
        quantum = _scatter_quantum(self.program) // 4
        ln = bucket_elems + ((-bucket_elems) % quantum)
        out = []
        for s in self.program.steps:
            if s.op == "quantize":
                out.append(ln)
            elif s.op == "reduce_scatter":
                ln //= self.program.tier_sizes[s.tier]
            elif s.op == "all_gather":
                ln *= self.program.tier_sizes[s.tier]
        return out

    def _state_lens(self, params):
        leaves = jax.tree_util.tree_leaves(params)
        out = []
        for dt, run, bucket in self._plan(leaves):
            if not run:
                continue
            elems = sum(leaves[i].size for i in bucket)
            out.extend((dt, ln) for ln in self._residual_lens(elems))
        return out

    def init(self, params):
        if not self.stateful:
            return ()
        return tuple(jnp.zeros((ln,), dt)
                     for dt, ln in self._state_lens(params))

    def init_global(self, params):
        if not self.stateful:
            return ()
        n = self.comm.size
        return tuple(jnp.zeros((n, ln), dt)
                     for dt, ln in self._state_lens(params))

    # -- program execution ----------------------------------------------
    def _run_program(self, flat, residuals):
        """Walk the steps over one flat bucket vector; returns
        ``(reduced_sum, new_residuals)``. ``residuals`` is the list of
        this bucket's per-region residuals (empty when stateless)."""
        prog, tm = self.program, self.tiers
        size = flat.size
        quantum = _scatter_quantum(prog) // 4
        pad = (-size) % quantum
        v = (jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
             if pad else flat)
        new_res: List = []
        ri, qmode, err = 0, None, None
        for s in prog.steps:
            if s.op == "quantize":
                qmode = s.wire
                if residuals:
                    v = v + residuals[ri]
                err = jnp.zeros_like(v)
            elif s.op == "dequantize":
                if residuals:
                    new_res.append(err)
                    ri += 1
                qmode, err = None, None
            elif s.op == "reduce_scatter":
                v = tm.psum_scatter(v, s.tier)
            elif s.op == "all_gather":
                v = tm.all_gather(v, s.tier)
            else:  # all_reduce
                if qmode is None:
                    v = tm.psum(v, s.tier)
                else:
                    deq, loc = _q_allreduce_tier(tm, v, s.tier, qmode)
                    err = err + (v - loc)
                    v = deq
        return (v[:size] if pad else v), new_res

    # -- the hot path ----------------------------------------------------
    def reduce(self, grads, state=()):
        comm = self.comm
        axes = comm.axis_names
        n = comm.size
        mesh_sizes = dict(zip(comm.mesh.axis_names,
                              comm.mesh.devices.shape))
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        plan = self._plan(leaves)
        if self.stateful:
            n_res = (sum(1 for _, run, _ in plan if run)
                     * self._n_regions)
            if len(state) != n_res:
                raise ValueError(
                    f"synthesized reducer state has {len(state)} "
                    f"residuals but the gradient tree plans {n_res}; "
                    "was the state initialized against a different "
                    "model?")
        # full-variance template: invariant leaves are pre-scaled and
        # pcast onto it so the whole bucket reduces over every tier
        # (the program's stages jointly cover all comm axes)
        tmpl = sum(lax.axis_index(a) for a in axes)
        out = [None] * len(leaves)
        new_state, si = [], 0
        for dt, run, bucket in plan:
            parts = []
            for i in bucket:
                l = leaves[i]
                va = varying_axes(l, axes)
                m = n // math.prod([mesh_sizes[a] for a in va] or [1])
                v = l.ravel().astype(dt)
                if m > 1:
                    v = v / m
                parts.append(match_vma(v, tmpl))
            flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            if run:
                res = (list(state[si:si + self._n_regions])
                       if self.stateful else [])
                red, nres = self._run_program(flat, res)
                if self.stateful:
                    new_state.extend(nres)
                    si += self._n_regions
            else:
                red = lax.psum(flat, axes)
            off = 0
            for i in bucket:
                l = leaves[i]
                piece = red[off:off + l.size].reshape(l.shape).astype(
                    l.dtype)
                off += l.size
                out[i] = piece / n if self.op == "mean" else piece
        return (jax.tree_util.tree_unflatten(treedef, out),
                tuple(new_state) if self.stateful else state)

    # -- introspection ----------------------------------------------------
    def tier_wire_bytes(self, payload_bytes: int):
        """Exact per-rank wire bytes by TIER NAME for one reduction —
        the accounting tests/synthesis_tests pins (values + blockwise
        scale sidecars on quantized tiers)."""
        per = program_wire_bytes(self.program, payload_bytes)
        names = [f"tier{i}" for i in range(len(self.program.tier_sizes))]
        return {names[i]: int(math.ceil(b)) for i, b in per.items()}

    def wire_bytes(self, payload_bytes: int) -> int:
        """Total per-rank RING bytes across every tier (unlike the flat
        strategies' payload-equivalent convention, a synthesized
        program's whole point is how the bytes split across tiers —
        the sum is the honest scalar)."""
        per = program_wire_bytes(self.program, payload_bytes)
        return int(math.ceil(sum(per.values())))

    def plan(self, tree):
        rows = super().plan(tree)
        for b in rows:
            b["algorithm"] = f"synth:{self.program.name}"
            b["tier_wire_bytes"] = self.tier_wire_bytes(b["bytes"])
        return rows


register_reducer("synth", SynthesizedReducer)

"""synthesis — collective-algorithm synthesis from communication
sketches over the multi-tier Topology.

schedtune (chainermn_tpu/tuning/) tunes KNOBS over three fixed
reducers; this package widens the search space to PROGRAMS (the
ROADMAP's TACCL/GC3 item): a sketch IR of per-tier primitive steps
(:mod:`.sketch`), a validity checker, a deterministic enumerator, an
alpha-beta cost walker with exact per-tier wire accounting, and a
compiler (:mod:`.compiler`) lowering validated programs to the
shard_map :class:`SynthesizedReducer` — registered as strategy
``'synth'``, scored by the tuner alongside the fixed reducers, and
persisted/consumed through the same profile DB →
``create_multi_node_optimizer(tune=...)`` path. One CLI:
``tools/synth.py``. See docs/tuning.md#from-knobs-to-programs and
docs/collectives.md#synthesized-programs.
"""

from chainermn_tpu.synthesis.compiler import SynthesizedReducer  # noqa: F401
from chainermn_tpu.synthesis.sketch import (  # noqa: F401
    QUANT_WIRES,
    STEP_OPS,
    Program,
    Step,
    check_program,
    enumerate_programs,
    program_cost_us,
    program_wire_bytes,
)

__all__ = [
    "Step",
    "Program",
    "STEP_OPS",
    "QUANT_WIRES",
    "check_program",
    "enumerate_programs",
    "program_cost_us",
    "program_wire_bytes",
    "SynthesizedReducer",
]

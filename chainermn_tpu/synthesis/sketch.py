"""The communication-sketch IR: per-tier collective programs.

TACCL (arxiv 2111.04867) synthesizes collective algorithms from
"communication sketches" — a human-scale description of how chunks move
through the topology hierarchy — and GC3 (arxiv 2201.11840) compiles
chunk-routing programs to executable collectives. This module is the
small, deterministic middle of that pipeline for the multi-tier
:class:`~chainermn_tpu.tuning.topology.Topology`:

* :class:`Step` / :class:`Program` — a linear IR of per-tier primitive
  steps (``reduce_scatter`` / ``all_reduce`` / ``all_gather``) plus
  paired ``quantize`` / ``dequantize`` wire steps that put a compressed
  format on the tiers they bracket;
* :func:`check_program` — the validity rules (every tier reduced
  exactly once, scatter/gather properly nested, wire regions paired);
* :func:`enumerate_programs` — the deterministic enumerator: every
  HiCCL-style partial cascade over the topology's tiers, plus (with
  ``lossy=True``) tier-aware quantized placements — the slow-tier-only
  placement the EQuARX analysis motivates and the quantize-everywhere
  variant;
* :func:`program_cost_us` / :func:`program_wire_bytes` — the alpha-beta
  cost walker and the exact per-tier wire-byte accounting the tests pin.

Deliberately stdlib-only (like :mod:`chainermn_tpu.tuning.topology`, the
only intra-repo import): the enumerator and cost model run in CLIs and
tuners without jax. Lowering a validated program to a shard_map reducer
is :mod:`chainermn_tpu.synthesis.compiler`'s job.

Numerics contract (pinned by tests/synthesis_tests/): every program the
default (lossless) enumeration emits is bitwise-equal to one flat psum
on integer-valued floats — the per-tier decomposition only re-orders
exactly-representable additions.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from chainermn_tpu.tuning.topology import WIRE_RATIO, Topology, _xfer_us

#: ops a step may carry. The three collectives are tier-local
#: (``tier`` indexes Topology.tiers, innermost first); the two wire
#: steps open/close a compressed-wire region and carry ``tier = -1``.
STEP_OPS = ("reduce_scatter", "all_reduce", "all_gather",
            "quantize", "dequantize")

#: wire formats a quantize step may name (the compressing subset of
#: topology.WIRE_RATIO — 'f32' is the absence of a quantize step, and
#: plain 'int8' is dominated by 'int8-block', same width better scales)
QUANT_WIRES = ("bf16", "int8-block", "int4-block")

#: elements per scale block for the blockwise formats — MUST equal
#: collectives.quantized.QUANT_BLOCK (stdlib module, can't import the
#: jax-side constant; pinned by tests/synthesis_tests/test_sketch.py)
_BLOCK = 256


@dataclasses.dataclass(frozen=True)
class Step:
    """One primitive: a collective on one tier, or a wire bracket.

    ``wire`` is only meaningful on ``quantize`` steps (the format the
    bracketed collectives carry); collective and ``dequantize`` steps
    leave it ``'f32'``.
    """

    op: str
    tier: int = -1
    wire: str = "f32"

    def describe(self) -> str:
        if self.op == "quantize":
            return f"q[{self.wire}]"
        if self.op == "dequantize":
            return "dq"
        short = {"reduce_scatter": "rs", "all_reduce": "ar",
                 "all_gather": "ag"}.get(self.op, self.op)
        return f"{short}({self.tier})"


@dataclasses.dataclass(frozen=True)
class Program:
    """A validated-or-not sequence of steps bound to tier sizes.

    ``tier_sizes`` (innermost first, same order as ``Topology.tiers``)
    travels with the program so a plan persisted in the profile DB can
    rebuild the exact rank decomposition on another process — the
    compiler refuses a communicator whose size doesn't factor this way.
    """

    steps: Tuple[Step, ...]
    tier_sizes: Tuple[int, ...]
    name: str = ""

    def describe(self) -> str:
        sizes = "x".join(str(s) for s in self.tier_sizes)
        body = " ".join(s.describe() for s in self.steps)
        return f"{self.name or 'program'}[{sizes}]: {body}"

    @property
    def wire_format(self) -> str:
        """The (single) quantized wire the program carries, or 'f32'."""
        for s in self.steps:
            if s.op == "quantize":
                return s.wire
        return "f32"

    @property
    def has_scatter(self) -> bool:
        return any(s.op == "reduce_scatter" for s in self.steps)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "tier_sizes": list(self.tier_sizes),
            "steps": [[s.op, s.tier, s.wire] for s in self.steps],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Program":
        return cls(
            steps=tuple(Step(str(op), int(tier), str(wire))
                        for op, tier, wire in d["steps"]),
            tier_sizes=tuple(int(s) for s in d["tier_sizes"]),
            name=str(d.get("name", "")),
        )


# ---------------------------------------------------------------------------
# validity
# ---------------------------------------------------------------------------


def check_program(program: Program) -> List[str]:
    """The validity rules; returns a list of violations (empty = valid).

    1. every step op is known and every collective's tier index is in
       range — tier-local steps stay on their (existing) tier;
    2. every tier is REDUCED exactly once: it appears in exactly one
       ``reduce_scatter`` or ``all_reduce`` step (the "every chunk
       reduced exactly once per tier" rule — zero means the program
       computes a partial sum, twice means it double-counts);
    3. scatter/gather nesting is LIFO: each ``all_gather`` closes the
       most recent still-open ``reduce_scatter`` (any other order
       permutes the chunk layout), and every scatter is closed by the
       end (otherwise the output isn't grads-shaped);
    4. wire regions are paired and flat: ``quantize`` opens (never
       nested), ``dequantize`` closes, every region closes by the end,
       names a known format, and brackets at least one ``all_reduce``
       — and ONLY ``all_reduce`` steps: the quantized group
       reduce-scatter belongs to the flat ZeRO path
       (``reduce_scatter_flat_ef``), not the sketch IR.
    """
    errs: List[str] = []
    m = len(program.tier_sizes)
    reduced: Dict[int, int] = {}
    scatter_stack: List[int] = []
    q_open: Optional[str] = None
    q_reduces = 0
    for idx, s in enumerate(program.steps):
        where = f"step {idx} ({s.describe()})"
        if s.op not in STEP_OPS:
            errs.append(f"{where}: unknown op {s.op!r}")
            continue
        if s.op == "quantize":
            if s.wire not in QUANT_WIRES:
                errs.append(f"{where}: unknown wire {s.wire!r}; "
                            f"expected one of {QUANT_WIRES}")
            if q_open is not None:
                errs.append(f"{where}: nested quantize region")
            q_open, q_reduces = s.wire, 0
            continue
        if s.op == "dequantize":
            if q_open is None:
                errs.append(f"{where}: dequantize without open quantize")
            elif q_reduces == 0:
                errs.append(f"{where}: empty quantize region (no "
                            "all_reduce inside)")
            q_open = None
            continue
        if not (0 <= s.tier < m):
            errs.append(f"{where}: tier {s.tier} out of range for "
                        f"{m} tiers")
            continue
        if s.op in ("reduce_scatter", "all_reduce"):
            reduced[s.tier] = reduced.get(s.tier, 0) + 1
        if q_open is not None:
            if s.op != "all_reduce":
                errs.append(f"{where}: only all_reduce may sit inside "
                            "a quantize region")
            else:
                q_reduces += 1
        if s.op == "reduce_scatter":
            scatter_stack.append(s.tier)
        elif s.op == "all_gather":
            if not scatter_stack:
                errs.append(f"{where}: all_gather with no open "
                            "reduce_scatter")
            elif scatter_stack[-1] != s.tier:
                errs.append(f"{where}: all_gather(tier {s.tier}) but "
                            f"the innermost open scatter is tier "
                            f"{scatter_stack[-1]} (gathers must close "
                            "LIFO or the chunk layout permutes)")
            else:
                scatter_stack.pop()
    if q_open is not None:
        errs.append("quantize region never closed")
    if scatter_stack:
        errs.append(f"reduce_scatter on tiers {scatter_stack} never "
                    "gathered — output would not be grads-shaped")
    for t in range(m):
        c = reduced.get(t, 0)
        if c != 1:
            errs.append(f"tier {t} reduced {c} times (must be exactly "
                        "once)")
    return errs


# ---------------------------------------------------------------------------
# the deterministic enumerator
# ---------------------------------------------------------------------------


def _cascade(m: int, depth: int) -> Tuple[Step, ...]:
    """Partial cascade: scatter the ``depth`` innermost tiers, allreduce
    the rest innermost-out, gather back LIFO."""
    steps = [Step("reduce_scatter", t) for t in range(depth)]
    steps += [Step("all_reduce", t) for t in range(depth, m)]
    steps += [Step("all_gather", t) for t in reversed(range(depth))]
    return tuple(steps)


def _scatter_through(m: int) -> Tuple[Step, ...]:
    """Scatter every tier, gather every tier — the two_dimensional
    communicator's rs/ag ladder generalized to m tiers."""
    steps = [Step("reduce_scatter", t) for t in range(m)]
    steps += [Step("all_gather", t) for t in reversed(range(m))]
    return tuple(steps)


def enumerate_programs(topology: Topology, lossy: bool = False,
                       wires: Sequence[str] = ("int8-block",
                                               "int4-block"),
                       ) -> List[Program]:
    """Every candidate program for ``topology``, in declaration order —
    no RNG, ties broken by position, same topology → same list.

    Lossless families (always emitted, all bitwise-equal to ``flat`` on
    integer-valued floats):

    * ``cascade-k`` for k = 0..m-1 — scatter the k innermost tiers,
      allreduce the rest (k = 0 is the per-tier allreduce ladder; k =
      m-1 is the canonical HiCCL cascade, the ``hierarchical`` reducer
      generalized);
    * ``scatter-through`` — rs/ag on every tier (m ≥ 2 only; for m = 1
      it duplicates ``cascade-0``'s byte/launch profile).

    ``lossy=True`` adds, per wire format in ``wires``, the two
    tier-aware placements the tentpole names:

    * ``@inter`` (m ≥ 2): the canonical cascade with ONLY the slowest
      tier's allreduce quantized — ICI-local stages stay exact, the
      narrow wire goes where bandwidth is scarce;
    * ``@all``: the allreduce ladder with every tier's wire quantized.
    """
    m = len(topology.tiers)
    sizes = tuple(t.size for t in topology.tiers)
    out: List[Program] = []
    for depth in range(m):
        out.append(Program(_cascade(m, depth), sizes,
                           name=f"cascade-{depth}"))
    if m >= 2:
        out.append(Program(_scatter_through(m), sizes,
                           name="scatter-through"))
    if lossy:
        for wire in wires:
            if m >= 2:
                steps = ([Step("reduce_scatter", t) for t in range(m - 1)]
                         + [Step("quantize", wire=wire),
                            Step("all_reduce", m - 1),
                            Step("dequantize")]
                         + [Step("all_gather", t)
                            for t in reversed(range(m - 1))])
                out.append(Program(tuple(steps), sizes,
                                   name=f"cascade-q@inter-{wire}"))
            steps = ([Step("quantize", wire=wire)]
                     + [Step("all_reduce", t) for t in range(m)]
                     + [Step("dequantize")])
            out.append(Program(tuple(steps), sizes,
                               name=f"ladder-q@all-{wire}"))
    return out


# ---------------------------------------------------------------------------
# cost + wire accounting
# ---------------------------------------------------------------------------


def _pad_to(nbytes: float, quantum: int) -> float:
    if quantum <= 1:
        return nbytes
    return math.ceil(nbytes / quantum) * quantum


def _scatter_quantum(program: Program) -> int:
    """Bytes-granularity the compiler pads a bucket to: the product of
    every scattered tier size × 4 (f32) so each rs stage divides
    evenly (compiler.py applies the same padding)."""
    q = 1
    for s in program.steps:
        if s.op == "reduce_scatter":
            q *= program.tier_sizes[s.tier]
    return q * 4


def program_wire_bytes(program: Program, nbytes: int,
                       exact: bool = True) -> Dict[int, float]:
    """Per-rank wire bytes each TIER carries for one reduction of
    ``nbytes`` of f32 payload: ``{tier index: bytes}``.

    Ring byte counts (the same convention the Topology cost model
    prices): a k-ring reduce-scatter or all-gather of a chunk ``c``
    moves ``c·(k-1)/k`` per rank; an allreduce moves both. Quantized
    regions multiply the bracketed tiers' bytes by the format's wire
    ratio; with ``exact=True`` the blockwise formats count the true
    integer bytes (1 B/elem int8 codes or 2-per-byte int4 nibbles, plus
    one 4 B scale per 256-element block) — the accounting
    tests/synthesis_tests pin against the compiled reducer.
    """
    sizes = program.tier_sizes
    chunk = float(_pad_to(nbytes, _scatter_quantum(program)))
    wire: Optional[str] = None
    out: Dict[int, float] = {t: 0.0 for t in range(len(sizes))}

    def _on_wire(c: float) -> float:
        if wire is None:
            return c
        if not exact:
            return c * WIRE_RATIO[wire]
        elems = c / 4.0
        if wire == "bf16":
            return elems * 2.0
        nblocks = math.ceil(elems / _BLOCK)
        if wire == "int8-block":
            return math.ceil(elems) + 4.0 * nblocks
        return math.ceil(elems / 2.0) + 4.0 * nblocks  # int4-block

    for s in program.steps:
        if s.op == "quantize":
            wire = s.wire
            continue
        if s.op == "dequantize":
            wire = None
            continue
        k = sizes[s.tier]
        if s.op == "reduce_scatter":
            out[s.tier] += _on_wire(chunk) * (k - 1) / k
            chunk /= k
        elif s.op == "all_reduce":
            out[s.tier] += 2.0 * _on_wire(chunk) * (k - 1) / k
        elif s.op == "all_gather":
            out[s.tier] += _on_wire(chunk) * (k - 1)  # chunk·k output
            chunk *= k
    return out


def program_cost_us(program: Program, topology: Topology,
                    nbytes: int) -> float:
    """Alpha-beta price of one reduction: each step pays its tier's
    launch latency plus its wire bytes over its tier's bandwidth; each
    quantize step pays the topology's (de)quantize kernel overhead
    once. For the canonical cascade (``cascade-(m-1)``) this reproduces
    ``Topology.estimate_us('hierarchical', nbytes)`` exactly (pinned by
    tests/synthesis_tests/test_sketch.py)."""
    if tuple(t.size for t in topology.tiers) != program.tier_sizes:
        raise ValueError(
            f"program {program.name!r} is bound to tier sizes "
            f"{program.tier_sizes} but the topology has "
            f"{tuple(t.size for t in topology.tiers)}")
    per_tier = program_wire_bytes(program, nbytes, exact=False)
    t = 0.0
    for s in program.steps:
        if s.op == "quantize":
            t += topology.quant_overhead_us
        elif s.op in ("reduce_scatter", "all_reduce", "all_gather"):
            t += topology.tiers[s.tier].latency_us
    for tier_idx, nb in per_tier.items():
        tier = topology.tiers[tier_idx]
        t += _xfer_us(nb, tier.bw_gbps)
    return t

"""Version shims for the supported jax range.

The codebase targets the modern top-level ``jax.shard_map`` spelling;
older jaxlibs (< 0.5) only ship it as
``jax.experimental.shard_map.shard_map``. Publishing the attribute on
the ``jax`` module keeps every ``from jax import shard_map`` site —
package, examples, tools, and embedded multi-process worker scripts —
working on both sides of the move with a single shim, imported first
thing by :mod:`chainermn_tpu`.
"""

import jax
from jax import lax

if not hasattr(jax, "shard_map"):
    import functools
    import inspect

    from jax.experimental.shard_map import shard_map as _experimental_sm

    if "check_vma" in inspect.signature(_experimental_sm).parameters:
        shard_map = _experimental_sm
    else:
        # the replication-check kwarg was renamed check_rep -> check_vma
        # along with the move to the top level
        @functools.wraps(_experimental_sm)
        def shard_map(*args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            # the old static replication checker predates the vma system
            # this codebase is written against: it has no pallas_call
            # rule and refuses out_specs whose replication it cannot
            # infer, both of which the vma checker handles. Default it
            # off; callers that ask for checking still get it.
            kwargs.setdefault("check_rep", False)
            return _experimental_sm(*args, **kwargs)

    jax.shard_map = shard_map

if not hasattr(jax, "typeof"):
    from jax._src import core as _src_core

    class _AvalView:
        """Aval plus an (empty) ``vma`` set.

        Old jax has no varying-manual-axes tracking; every caller in this
        codebase probes ``typeof(x).vma`` and falls back to its
        tracking-off path when the set is empty, so an empty frozenset is
        the correct answer everywhere.
        """

        vma = frozenset()

        def __init__(self, aval):
            self._aval = aval

        def __getattr__(self, name):
            return getattr(self._aval, name)

        def __repr__(self):
            return repr(self._aval)

    def _typeof(x):
        return _AvalView(_src_core.get_aval(x))

    jax.typeof = _typeof

if not hasattr(lax, "pcast"):
    # pcast only adjusts vma metadata; with tracking off it is identity
    def _pcast(x, axis_name, *, to=None):
        return x

    lax.pcast = _pcast

if not hasattr(lax, "axis_size"):
    from jax._src import core as _src_core

    def _axis_size(axis_name):
        # pre-0.5 jax: core.axis_frame(name) IS the static size
        if isinstance(axis_name, (tuple, list)):
            size = 1
            for a in axis_name:
                size *= _src_core.axis_frame(a)
            return size
        return _src_core.axis_frame(axis_name)

    lax.axis_size = _axis_size

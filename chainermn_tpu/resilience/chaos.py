"""Deterministic, seed-driven fault injection for distributed training.

The restart-based fault-tolerance story (per-rank snapshots + consensus
election, PAPER.md §2.5/§3.5) is only as good as its worst failure mode —
and the dominant ones on real pods are preemption, one wedged host, and
torn snapshot files. This harness *injects* exactly those faults, on a
deterministic schedule, so the suite can prove the stack survives them:

* ``kill`` — deliver a signal (SIGKILL/SIGTERM/...) to the *own* process
  when the training loop reaches a given step, on a given rank;
* ``delay_rpc`` — sleep before coordinator KV RPCs in the object plane
  (a slow/loaded coordinator);
* ``blackhole_rpc`` — stall matching RPCs for a long, configurable time
  (a wedged coordinator link; the guard probes bound the damage);
* ``corrupt`` / ``truncate`` — damage a named checkpoint file right
  after it is published (a torn write / bad disk);
* ``enospc`` — fail a matching snapshot publish with ``OSError(ENOSPC)``
  before any byte is written (a full disk — the save raises, nothing is
  published, the election must fall back);
* ``slow_disk`` — sleep before a matching snapshot publish (a
  overloaded/slow disk stretching the write window);
* ``kill_replica`` — abruptly kill ONE fleet replica's scheduler loop at
  a given working iteration (the in-process SIGKILL analogue the router
  drill uses: futures stay unresolved, survivors must absorb the work);
* ``corrupt_handoff`` — damage a prefill→decode KV handoff blob on the
  wire (flip or truncate), which the decode pool's manifest verification
  must catch and answer with a clean re-prefill;
* ``reset_conn`` / ``partial_write`` / ``stall_accept`` — socket-level
  connection faults for the TCP object plane (:func:`on_socket`,
  ``comm/socket_plane.py``): a connection dies with a frame in flight,
  a frame is torn mid-write, the listener wedges. The plane's framing
  (length + SHA), bounded reconnect, and re-handshake must contain
  every one to a re-sent frame — never a torn delivery;
* ``drop_handoff`` / ``delay_handoff`` / ``dup_handoff`` — wire-level
  delivery faults for the fleet transport (:func:`on_wire`): a frame
  vanishes, arrives late, or arrives twice. The transport's sequence
  numbers + SHA-verified frames + bounded NACK/re-send protocol must
  end every case in exact adoption or a clean re-prefill — never a
  poisoned decode slot or a duplicated token. All wire faults accept
  ``times=N`` (fire at most N times) so a drill can damage exactly one
  delivery attempt and let the re-send heal;
* ``corrupt_rollout_chunk`` / ``kill_mid_swap`` / ``canary_mismatch``
  — rolling-weight-update faults (:mod:`chainermn_tpu.fleet.rollout`):
  a relay chunk is damaged on the wire (per-chunk SHA must NACK and
  re-send; persistent damage must end in a rollback to v1), a replica
  dies inside its swap window (classified as a crash; the restart
  converges to the version its verified local manifest names), and the
  canary's bitwise prompt replay miscompares (the rollout must abort
  with zero traffic moved).

Faults can be pinned to one supervised incarnation with ``run=K``: the
supervisor (:mod:`chainermn_tpu.resilience.supervisor`) exports
``$CHAINERMN_TPU_RESTART_COUNT`` to each child, and a fault carrying
``run=K`` fires only when that counter equals ``K`` — so "kill at step 7,
first run only" heals on restart, while the same fault *without* ``run=``
reproduces a crash loop that must trip the restart budget.

Activation is by environment variable so `tests/mp_harness.py` worker
processes self-inject without any code path knowing about the test:

    CHAINERMN_TPU_CHAOS="kill@step=3,rank=1,signal=SIGKILL"
    CHAINERMN_TPU_CHAOS="corrupt@match=snapshot_iter_6.1;delay_rpc@op=kv_get,ms=200,prob=0.5,seed=7"

Specs are ``;``-separated faults, each ``kind@key=value,key=value,...``.
Probabilistic faults draw from a ``seed``-pinned RNG: the same spec
replays the same failure schedule (the point of *deterministic* chaos).

Hook points (all no-ops when the env var is unset):

* :func:`on_step` — called by the Trainer loop (and any manual step
  loop) with the global iteration number;
* :func:`on_rpc` — called by ``comm/object_plane.py`` before each
  coordinator RPC (ops: ``kv_get``, ``kv_put``, ``barrier``);
* :func:`on_checkpoint` — called by the checkpointer after publishing a
  snapshot file, with its path;
* :func:`on_publish` — called by the checkpointer right BEFORE writing a
  snapshot file (fires ``enospc``/``slow_disk``);
* :func:`on_offload` — called by the async snapshot plane
  (``checkpointing/async_plane.py``) at its two pipeline stages:
  ``stage="offload"`` on the step thread right before the device→host
  copy is kicked off (fires ``slow_offload``), and ``stage="writer"`` on
  the background writer thread right before serialization + publish
  (fires ``stall_writer`` — widening the offload→publish window a crash
  can land in, which is exactly what the SIGKILL drill needs).
"""

from __future__ import annotations

import errno
import os
import random
import signal as _signal
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

ENV_VAR = "CHAINERMN_TPU_CHAOS"

#: fault kind -> one-line description (the CLI's --dry-run catalogue)
FAULT_KINDS: Dict[str, str] = {
    "kill": ("deliver a signal to this process at a training step: "
             "step=N[,rank=R|*][,signal=SIGKILL|SIGTERM|...]"),
    "delay_rpc": ("sleep before matching coordinator RPCs: "
                  "ms=M[,op=kv_get|kv_put|barrier|*][,prob=P][,seed=S]"
                  "[,rank=R|*]"),
    "blackhole_rpc": ("stall matching coordinator RPCs: "
                      "[ms=M (default 3600000)][,op=...][,prob=P]"
                      "[,seed=S][,rank=R|*][,after=K (skip first K)]"),
    "corrupt": ("flip bytes in a checkpoint file right after publish: "
                "match=SUBSTRING[,rank=R|*][,offset=O]"),
    "truncate": ("truncate a checkpoint file right after publish: "
                 "match=SUBSTRING[,rank=R|*][,keep=BYTES (default half)]"),
    "enospc": ("fail a matching snapshot publish with OSError(ENOSPC): "
               "match=SUBSTRING[,rank=R|*][,after=K][,prob=P][,seed=S]"),
    "slow_disk": ("sleep before a matching snapshot publish: "
                  "ms=M,match=SUBSTRING[,rank=R|*][,prob=P][,seed=S]"),
    "slow_offload": ("sleep on the STEP thread before the async plane's "
                     "device-to-host offload (a congested PCIe/ICI "
                     "link): ms=M,match=SUBSTRING[,rank=R|*][,after=K]"
                     "[,prob=P][,seed=S]"),
    "stall_writer": ("sleep on the async plane's WRITER thread before "
                     "serialize+publish (stretches the offload→publish "
                     "window): ms=M,match=SUBSTRING[,rank=R|*][,after=K]"
                     "[,prob=P][,seed=S]"),
    "kill_replica": ("kill ONE serving replica's scheduler (the fleet "
                     "router's SIGKILL analogue: the loop dies abruptly, "
                     "futures unresolved, and the router must re-queue): "
                     "step=N[,replica=R|*][,rank=R|*]"),
    "corrupt_handoff": ("damage a prefill→decode KV handoff on the "
                        "wire (flip 64 bytes at offset, or truncate "
                        "when keep= is given — the transport must NACK "
                        "and the decode pool must fall back to a clean "
                        "re-prefill): [offset=O][,keep=BYTES][,after=K]"
                        "[,times=N][,prob=P][,seed=S][,rank=R|*]"),
    "drop_handoff": ("swallow a handoff frame on the wire (the sender's "
                     "RpcPolicy-bounded ack wait must notice and "
                     "re-send; an unbounded drop must end in a clean "
                     "re-prefill): [times=N][,after=K][,prob=P]"
                     "[,seed=S][,rank=R|*]"),
    "delay_handoff": ("hold a handoff frame in flight for ms= before "
                      "delivery (a congested DCN link — late frames "
                      "past the receiver's deadline must be fenced "
                      "out as duplicates): ms=M[,times=N][,after=K]"
                      "[,prob=P][,seed=S][,rank=R|*]"),
    "dup_handoff": ("deliver a handoff frame twice (the receiver must "
                    "dedup by stream — a double adoption would emit "
                    "duplicated tokens): [times=N][,after=K][,prob=P]"
                    "[,seed=S][,rank=R|*]"),
    "kill_dest": ("kill the MIGRATION DESTINATION replica right after "
                  "it adopts a migrated session, before the source "
                  "releases its slot (the adopt-before-ack crash "
                  "window — the router's sweep must replay the stream "
                  "from seed on a survivor): [times=N][,after=K]"
                  "[,prob=P][,seed=S][,rank=R|*]"),
    "reset_conn": ("abruptly close a SocketObjectPlane connection "
                   "before a frame is written (a peer RST / dead NAT "
                   "entry — the sender must reconnect with backoff and "
                   "the ack machinery must re-send the lost frame): "
                   "[times=N][,after=K][,prob=P][,seed=S][,rank=R|*]"),
    "partial_write": ("write only HALF a socket frame then close the "
                      "connection (a torn TCP stream — the receiver's "
                      "length/SHA framing must reject the fragment and "
                      "resync on the reconnect, never deliver torn "
                      "bytes): [times=N][,after=K][,prob=P][,seed=S]"
                      "[,rank=R|*]"),
    "stall_accept": ("sleep in the SocketObjectPlane acceptor before "
                     "accept() (a wedged listener — connect attempts "
                     "must time out under the RpcPolicy budget and "
                     "retry with jittered backoff): [ms=M (default "
                     "2000)][,times=N][,after=K][,prob=P][,seed=S]"
                     "[,rank=R|*]"),
    "corrupt_rollout_chunk": ("damage a weight-rollout relay chunk on "
                              "the wire (flip 64 bytes at offset, or "
                              "truncate when keep= is given — the "
                              "relay's per-chunk SHA must NACK and "
                              "re-send; when every attempt is damaged "
                              "the rollout must fail and roll back to "
                              "v1): [offset=O][,keep=BYTES][,after=K]"
                              "[,times=N][,prob=P][,seed=S][,rank=R|*]"),
    "kill_mid_swap": ("kill ONE replica inside its weight-swap window "
                      "(after drain, before readmit — the rollout "
                      "controller must classify the death as a crash, "
                      "skip the replica, and the restart must converge "
                      "to whichever version its local manifest "
                      "verifies): [replica=R|*][,times=N][,after=K]"
                      "[,prob=P][,seed=S][,rank=R|*]"),
    "canary_mismatch": ("force the rollout canary's bitwise prompt "
                        "replay to MISCOMPARE (a bad v2 snapshot — the "
                        "controller must abort with zero traffic "
                        "moved): [times=N][,after=K][,prob=P][,seed=S]"
                        "[,rank=R|*]"),
}

#: every fault kind also accepts ``run=K`` — fire only in supervised
#: incarnation K ($CHAINERMN_TPU_RESTART_COUNT, 0 when unsupervised)
_INT_KEYS = {"step", "ms", "offset", "keep", "after", "seed", "run",
             "replica", "times"}
_FLOAT_KEYS = {"prob"}


@dataclass
class Fault:
    kind: str
    step: Optional[int] = None
    rank: Optional[int] = None          # None = every rank ('*')
    signal: str = "SIGKILL"
    op: Optional[str] = None            # None = every rpc op ('*')
    ms: Optional[int] = None
    prob: float = 1.0
    seed: Optional[int] = None
    match: Optional[str] = None
    offset: int = 0
    keep: Optional[int] = None
    after: int = 0
    times: Optional[int] = None         # fire at most N times (wire faults)
    run: Optional[int] = None           # None = every incarnation
    replica: Optional[int] = None       # None = every replica ('*')
    fired: int = field(default=0, repr=False)
    _rng: Optional[random.Random] = field(default=None, repr=False)
    _skipped: int = field(default=0, repr=False)

    def rng(self) -> random.Random:
        if self._rng is None:
            self._rng = random.Random(self.seed)
        return self._rng

    def applies_to_rank(self, rank: Optional[int]) -> bool:
        return self.rank is None or rank is None or self.rank == rank

    def applies_to_run(self) -> bool:
        """Supervised-incarnation match: the supervisor exports the
        restart counter; unsupervised processes count as incarnation 0."""
        if self.run is None:
            return True
        return _own_run() == self.run

    def roll(self) -> bool:
        if self.prob >= 1.0:
            return True
        return self.rng().random() < self.prob

    def describe(self) -> str:
        """One-line human rendering of the set fields (the CLI's
        --dry-run listing)."""
        parts = []
        for name in ("step", "signal", "op", "ms", "prob", "seed",
                     "match", "offset", "keep", "after", "times", "run",
                     "replica"):
            val = getattr(self, name)
            if val is None:
                continue
            if name == "signal" and self.kind != "kill":
                continue
            if name == "prob" and val >= 1.0:
                continue
            if name in ("offset", "after") and not val:
                continue
            parts.append(f"{name}={val}")
        return " ".join(parts) or "(defaults)"


def parse_spec(spec: str) -> List[Fault]:
    """Parse a ``;``-separated chaos spec into faults (raises ValueError
    with the offending clause on malformed input)."""
    faults: List[Fault] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, _, rest = clause.partition("@")
        kind = kind.strip()
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown chaos fault {kind!r} in {clause!r} — known: "
                + ", ".join(sorted(FAULT_KINDS)))
        kv: Dict[str, object] = {}
        for item in filter(None, (s.strip() for s in rest.split(","))):
            key, eq, val = item.partition("=")
            if not eq:
                raise ValueError(
                    f"malformed {key!r} in chaos clause {clause!r} "
                    "(expected key=value)")
            key = key.strip()
            val = val.strip()
            if key in ("rank", "replica") and val == "*":
                kv[key] = None
            elif key in _INT_KEYS or key == "rank":
                kv[key] = int(val)
            elif key in _FLOAT_KEYS:
                kv[key] = float(val)
            else:
                kv[key] = val
        try:
            fault = Fault(kind=kind, **kv)
        except TypeError as e:
            raise ValueError(
                f"bad field in chaos clause {clause!r}: {e}") from e
        if fault.kind in ("kill", "kill_replica") and fault.step is None:
            raise ValueError(
                f"{fault.kind} fault needs step=N: {clause!r}")
        if (fault.kind in ("corrupt", "truncate", "enospc", "slow_disk",
                           "slow_offload", "stall_writer")
                and not fault.match):
            raise ValueError(
                f"{fault.kind} fault needs match=SUBSTRING: {clause!r}")
        if (fault.kind in ("delay_rpc", "slow_disk", "slow_offload",
                           "stall_writer", "delay_handoff")
                and fault.ms is None):
            raise ValueError(f"{fault.kind} fault needs ms=M: {clause!r}")
        if fault.times is not None and fault.times <= 0:
            raise ValueError(f"times must be positive: {clause!r}")
        if not (0.0 <= fault.prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1]: {clause!r}")
        faults.append(fault)
    return faults


def _own_run() -> int:
    """This process's supervised-incarnation number: 0 on the first
    launch (or unsupervised), incremented by the supervisor per restart
    (resilience/supervisor.py exports $CHAINERMN_TPU_RESTART_COUNT)."""
    raw = os.environ.get("CHAINERMN_TPU_RESTART_COUNT")
    if raw is not None:
        try:
            return int(raw)
        except ValueError:
            pass
    return 0


def _own_rank() -> Optional[int]:
    """This process's rank for fault matching: the mp-harness worker id
    when set, else jax.process_index() if jax is initialized, else None
    (matches every-rank faults only)."""
    for var in ("CHAINERMN_TPU_CHAOS_RANK", "JAX_PROCESS_ID"):
        raw = os.environ.get(var)
        if raw is not None:
            try:
                return int(raw)
            except ValueError:
                pass
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_index()
    except Exception:
        pass
    return None


class ChaosPlan:
    """The parsed fault schedule plus the injection hooks.

    ``kill_fn``/``sleep_fn`` are injectable for tests; real use keeps the
    defaults (``os.kill`` on the own pid, ``time.sleep``).
    """

    def __init__(self, faults: List[Fault],
                 kill_fn: Optional[Callable[[int], None]] = None,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.faults = faults
        self._kill = kill_fn or (
            lambda signum: os.kill(os.getpid(), signum))
        self._sleep = sleep_fn
        self.log: List[str] = []  # fired faults, for tests/debugging

    # -- hooks ----------------------------------------------------------

    def on_step(self, iteration: int, rank: Optional[int] = None) -> None:
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind != "kill" or f.step != iteration:
                continue
            if not f.applies_to_rank(rank) or not f.applies_to_run():
                continue
            signum = getattr(_signal, f.signal, None)
            if signum is None:
                raise ValueError(f"unknown signal {f.signal!r}")
            f.fired += 1
            self.log.append(f"kill step={iteration} signal={f.signal}")
            self._kill(int(signum))

    def on_rpc(self, op: str, rank: Optional[int] = None) -> None:
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind not in ("delay_rpc", "blackhole_rpc"):
                continue
            if f.op is not None and f.op != "*" and f.op != op:
                continue
            if not f.applies_to_rank(rank) or not f.applies_to_run():
                continue
            if f._skipped < f.after:
                f._skipped += 1
                continue
            if not f.roll():
                continue
            ms = f.ms if f.ms is not None else (
                3_600_000 if f.kind == "blackhole_rpc" else 0)
            f.fired += 1
            self.log.append(f"{f.kind} op={op} ms={ms}")
            self._sleep(ms / 1000.0)

    def on_checkpoint(self, path: str,
                      rank: Optional[int] = None) -> None:
        rank = _own_rank() if rank is None else rank
        base = os.path.basename(path)
        for f in self.faults:
            if f.kind not in ("corrupt", "truncate"):
                continue
            if not f.applies_to_rank(rank) or not f.applies_to_run():
                continue
            if f.match not in path and f.match not in base:
                continue
            if not f.roll():
                continue
            f.fired += 1
            self.log.append(f"{f.kind} path={base}")
            if f.kind == "truncate":
                size = os.path.getsize(path)
                keep = f.keep if f.keep is not None else size // 2
                with open(path, "rb+") as fh:
                    fh.truncate(max(0, keep))
            else:
                with open(path, "rb+") as fh:
                    fh.seek(f.offset)
                    chunk = fh.read(64) or b"\0"
                    fh.seek(f.offset)
                    fh.write(bytes(b ^ 0xFF for b in chunk))

    def on_publish(self, path: str,
                   rank: Optional[int] = None) -> None:
        """Pre-publish hook (the checkpointer calls it before any byte of
        a snapshot is written): ``slow_disk`` sleeps, ``enospc`` raises
        ``OSError(ENOSPC)`` so the save fails with nothing published —
        the election falls back, exactly like a full disk."""
        rank = _own_rank() if rank is None else rank
        base = os.path.basename(path)
        for f in self.faults:
            if f.kind not in ("enospc", "slow_disk"):
                continue
            if not f.applies_to_rank(rank) or not f.applies_to_run():
                continue
            if f.match not in path and f.match not in base:
                continue
            if f._skipped < f.after:
                f._skipped += 1
                continue
            if not f.roll():
                continue
            f.fired += 1
            self.log.append(f"{f.kind} path={base}")
            if f.kind == "slow_disk":
                self._sleep((f.ms or 0) / 1000.0)
            else:
                raise OSError(
                    errno.ENOSPC,
                    f"No space left on device (chaos enospc: {base})")

    def on_replica_step(self, replica: int, iteration: int,
                        rank: Optional[int] = None) -> bool:
        """Fleet-replica hook: the router's per-replica scheduler loop
        calls this before each WORKING iteration (idle spins don't
        count, so ``step=N`` means the same thing under any poll rate).
        Returns True when a matching ``kill_replica`` fault fires — the
        caller must die abruptly (no drain, no future resolution), the
        in-process analogue of SIGKILLing that replica's host."""
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind != "kill_replica" or f.step != iteration:
                continue
            if f.replica is not None and f.replica != replica:
                continue
            if not f.applies_to_rank(rank) or not f.applies_to_run():
                continue
            f.fired += 1
            self.log.append(
                f"kill_replica replica={replica} step={iteration}")
            return True
        return False

    def _damage_handoff(self, f: Fault, data: bytes) -> bytes:
        """Apply one fired corruption fault (``corrupt_handoff`` or
        ``corrupt_rollout_chunk``): truncate to ``keep`` bytes, or
        XOR-flip 64 bytes at ``offset``."""
        if f.keep is not None:
            self.log.append(f"{f.kind} keep={f.keep}")
            return data[:max(0, f.keep)]
        self.log.append(f"{f.kind} offset={f.offset}")
        buf = bytearray(data)
        end = min(len(buf), f.offset + 64)
        for i in range(f.offset, end):
            buf[i] ^= 0xFF
        return bytes(buf)

    def _wire_gate(self, f: Fault, rank: Optional[int]) -> bool:
        """Shared fire/skip decision for the wire faults: rank + run +
        ``after=`` skip window + ``times=`` fire cap + probability."""
        if not f.applies_to_rank(rank) or not f.applies_to_run():
            return False
        if f.times is not None and f.fired >= f.times:
            return False
        if f._skipped < f.after:
            f._skipped += 1
            return False
        return f.roll()

    def on_handoff(self, data: bytes,
                   rank: Optional[int] = None) -> bytes:
        """KV-handoff byte hook (legacy single-blob form of
        :meth:`on_wire`): ``corrupt_handoff`` returns a damaged copy —
        64 bytes XOR-flipped at ``offset``, or the blob truncated to
        ``keep`` bytes. The decode side's manifest verification must
        catch it and fall back to a clean re-prefill."""
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind != "corrupt_handoff":
                continue
            if not self._wire_gate(f, rank):
                continue
            f.fired += 1
            data = self._damage_handoff(f, data)
        return data

    #: on_wire traffic kind → corruption fault that targets it (the
    #: generic delivery faults drop/delay/dup fire for every kind)
    _WIRE_CORRUPT = {"handoff": "corrupt_handoff",
                     "rollout": "corrupt_rollout_chunk"}

    def on_wire(self, data: bytes,
                rank: Optional[int] = None,
                kind: str = "handoff") -> tuple:
        """Transport wire hook (fleet/transport.py, once per delivery
        ATTEMPT — a re-send rolls the faults again): returns
        ``(verdict, data)`` with verdict ``"deliver"``, ``"drop"`` (the
        frame vanishes; the sender's RpcPolicy-bounded ack wait must
        notice and re-send), or ``"dup"`` (the frame arrives twice; the
        receiver must dedup by stream). ``delay_handoff`` sleeps the
        frame in flight; the corruption fault matching ``kind`` damages
        the returned bytes (``corrupt_handoff`` for KV-handoff traffic,
        ``corrupt_rollout_chunk`` for weight-rollout relay chunks — a
        rollout drill must not damage ordinary handoffs, and vice
        versa). Wire faults honour ``times=N`` so a drill can drop
        exactly one attempt and let the re-send heal."""
        corrupt_kind = self._WIRE_CORRUPT.get(kind)
        if corrupt_kind is None:
            raise ValueError(f"unknown wire kind {kind!r} — known: "
                             + ", ".join(sorted(self._WIRE_CORRUPT)))
        rank = _own_rank() if rank is None else rank
        verdict = "deliver"
        for f in self.faults:
            if f.kind not in ("drop_handoff", "delay_handoff",
                              "dup_handoff", corrupt_kind):
                continue
            if not self._wire_gate(f, rank):
                continue
            f.fired += 1
            if f.kind == "drop_handoff":
                self.log.append("drop_handoff")
                return ("drop", data)
            if f.kind == "delay_handoff":
                self.log.append(f"delay_handoff ms={f.ms}")
                self._sleep((f.ms or 0) / 1000.0)
            elif f.kind == "dup_handoff":
                self.log.append("dup_handoff")
                verdict = "dup"
            else:
                data = self._damage_handoff(f, data)
        return (verdict, data)

    #: socket-plane op → fault kinds that can fire there
    _SOCKET_OPS = {"send": ("reset_conn", "partial_write"),
                   "accept": ("stall_accept",)}

    def on_socket(self, op: str,
                  rank: Optional[int] = None) -> Optional[str]:
        """Socket-level wire hook (comm/socket_plane.py) — the
        connection-layer extension of :meth:`on_wire`, for faults the
        verdict-over-bytes contract cannot express. ``op`` names the
        plane operation:

        * ``"send"`` — before a frame is written. Returns
          ``"reset_conn"`` (the plane must close the connection and
          lose the frame — a peer RST) or ``"partial_write"`` (the
          plane must write half the frame then close — a torn stream),
          else None.
        * ``"accept"`` — in the acceptor loop. ``stall_accept`` sleeps
          ``ms`` (default 2000) inline; always returns None.

        One fault per call (first match wins), gated like every wire
        fault: rank + run + ``after=`` + ``times=`` + probability."""
        kinds = self._SOCKET_OPS.get(op)
        if kinds is None:
            raise ValueError(f"unknown socket op {op!r} — known: "
                             + ", ".join(sorted(self._SOCKET_OPS)))
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind not in kinds:
                continue
            if not self._wire_gate(f, rank):
                continue
            f.fired += 1
            self.log.append(f.kind)
            if f.kind == "stall_accept":
                self._sleep((f.ms if f.ms is not None else 2000) / 1000.0)
                return None
            return f.kind
        return None

    def on_migration(self, stream_id: int,
                     rank: Optional[int] = None) -> bool:
        """Migration hook (fleet/router.py ``drain``): called right
        after the DESTINATION replica adopts a migrated session and
        before the source slot is released — the adopt-before-ack
        window. Returns True when a matching ``kill_dest`` fault fires;
        the caller must kill the destination replica, whose sweep then
        re-queues the adopted session for a replay from seed."""
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind != "kill_dest":
                continue
            if not self._wire_gate(f, rank):
                continue
            f.fired += 1
            self.log.append(f"kill_dest stream={stream_id}")
            return True
        return False

    def on_swap(self, replica: int,
                rank: Optional[int] = None) -> bool:
        """Rollout swap hook (fleet/rollout.py): called inside a
        replica's weight-swap window — after it drained, before it is
        readmitted. Returns True when a matching ``kill_mid_swap``
        fault fires; the caller must kill that replica abruptly (the
        SIGKILL-mid-swap analogue: the rollout controller classifies
        the death as a crash and skips the replica, and a supervised
        restart converges to whichever version its local manifest
        verifies)."""
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind != "kill_mid_swap":
                continue
            if f.replica is not None and f.replica != replica:
                continue
            if not self._wire_gate(f, rank):
                continue
            f.fired += 1
            self.log.append(f"kill_mid_swap replica={replica}")
            return True
        return False

    def on_canary(self, rank: Optional[int] = None) -> bool:
        """Rollout canary hook (fleet/rollout.py): called right before
        the canary's bitwise compare against the v2 oracle. Returns
        True when a ``canary_mismatch`` fault fires — the caller must
        treat the compare as FAILED (a bad v2 snapshot) and abort the
        rollout with the fleet untouched."""
        rank = _own_rank() if rank is None else rank
        for f in self.faults:
            if f.kind != "canary_mismatch":
                continue
            if not self._wire_gate(f, rank):
                continue
            f.fired += 1
            self.log.append("canary_mismatch")
            return True
        return False

    #: pipeline stage → fault kind for :meth:`on_offload`
    _OFFLOAD_STAGES = {"offload": "slow_offload", "writer": "stall_writer"}

    def on_offload(self, path: str, stage: str,
                   rank: Optional[int] = None) -> None:
        """Async-plane hook: ``stage`` names the pipeline point —
        ``"offload"`` (step thread, before the device→host copy) fires
        ``slow_offload``; ``"writer"`` (writer thread, before
        serialize+publish) fires ``stall_writer``."""
        kind = self._OFFLOAD_STAGES.get(stage)
        if kind is None:
            raise ValueError(f"unknown offload stage {stage!r} — known: "
                             + ", ".join(sorted(self._OFFLOAD_STAGES)))
        rank = _own_rank() if rank is None else rank
        base = os.path.basename(path)
        for f in self.faults:
            if f.kind != kind:
                continue
            if not f.applies_to_rank(rank) or not f.applies_to_run():
                continue
            if f.match not in path and f.match not in base:
                continue
            if f._skipped < f.after:
                f._skipped += 1
                continue
            if not f.roll():
                continue
            f.fired += 1
            self.log.append(f"{f.kind} path={base}")
            self._sleep((f.ms or 0) / 1000.0)


_plan: Optional[ChaosPlan] = None
_plan_spec: Optional[str] = None


def chaos_from_env() -> Optional[ChaosPlan]:
    """The process-wide plan from $CHAINERMN_TPU_CHAOS (cached; re-parsed
    when the env var's value changes, so tests can swap specs)."""
    global _plan, _plan_spec
    spec = os.environ.get(ENV_VAR)
    if not spec:
        _plan, _plan_spec = None, None
        return None
    if _plan is None or spec != _plan_spec:
        _plan = ChaosPlan(parse_spec(spec))
        _plan_spec = spec
    return _plan


# module-level hook wrappers: callers stay one `if` away from zero cost

def on_step(iteration: int) -> None:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            plan.on_step(iteration)


def on_rpc(op: str) -> None:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            plan.on_rpc(op)


def on_checkpoint(path: str) -> None:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            plan.on_checkpoint(path)


def on_publish(path: str) -> None:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            plan.on_publish(path)


def on_offload(path: str, stage: str) -> None:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            plan.on_offload(path, stage)


def on_replica_step(replica: int, iteration: int) -> bool:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            return plan.on_replica_step(replica, iteration)
    return False


def on_handoff(data: bytes) -> bytes:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            return plan.on_handoff(data)
    return data


def on_wire(data: bytes, kind: str = "handoff") -> tuple:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            return plan.on_wire(data, kind=kind)
    return ("deliver", data)


def on_socket(op: str) -> Optional[str]:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            return plan.on_socket(op)
    return None


def on_migration(stream_id: int) -> bool:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            return plan.on_migration(stream_id)
    return False


def on_swap(replica: int) -> bool:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            return plan.on_swap(replica)
    return False


def on_canary() -> bool:
    if os.environ.get(ENV_VAR):
        plan = chaos_from_env()
        if plan is not None:
            return plan.on_canary()
    return False

"""Preemption survival: turn SIGTERM into a checkpoint, not a loss.

TPU pods are preempted with a SIGTERM and a grace window; the default
disposition kills the process mid-step and throws away every iteration
since the last periodic snapshot. This module installs a handler that
only sets a flag; the Trainer loop polls :func:`preemption_requested`
once per step and, when set, runs an emergency all-rank checkpoint
(bounded by :func:`grace_deadline`) and exits the run loop cleanly —
the consensus election finds the emergency snapshot on restart.

The handler is deliberately minimal (async-signal-safe: set a flag,
remember the signal, chain nothing): all real work happens on the
training thread. Install/uninstall is idempotent and restores the
previous handlers, so library users and tests can scope it to a run.

``CHAINERMN_TPU_PREEMPTION_GRACE_S`` configures the grace window the
emergency checkpoint must fit into (default 30 s).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, Optional, Tuple

_ENV_GRACE = "CHAINERMN_TPU_PREEMPTION_GRACE_S"
_DEFAULT_GRACE_S = 30.0

#: conventional exit code for a run that stopped on preemption after
#: checkpointing (distinct from 0 so orchestrators can tell "finished"
#: from "preempted but resumable"; 128+SIGTERM is what an unhandled
#: SIGTERM would have produced)
PREEMPTED_EXIT_CODE = 143


class PreemptionGuard:
    """Flag-and-deadline state shared between the signal handler and the
    training loop. Thread-safe: the flag is a simple attribute write from
    the handler, reads are racy-but-monotonic (once True, stays True until
    :meth:`reset`)."""

    def __init__(self) -> None:
        self._requested = False
        self._signum: Optional[int] = None
        self._at: Optional[float] = None
        self._prev: Dict[int, object] = {}
        self._installed: Tuple[int, ...] = ()

    # -- handler side ----------------------------------------------------

    def _handle(self, signum, frame) -> None:  # noqa: ARG002 (signature)
        self._requested = True
        self._signum = signum
        if self._at is None:
            self._at = time.monotonic()

    # -- training-loop side ----------------------------------------------

    @property
    def requested(self) -> bool:
        return self._requested

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def grace_deadline(self) -> Optional[float]:
        """Monotonic deadline the emergency checkpoint must beat (None
        until a signal arrived)."""
        if self._at is None:
            return None
        return self._at + grace_seconds()

    def remaining(self) -> Optional[float]:
        dl = self.grace_deadline()
        return None if dl is None else max(0.0, dl - time.monotonic())

    def reset(self) -> None:
        self._requested = False
        self._signum = None
        self._at = None

    # -- install/uninstall -----------------------------------------------

    def install(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                  signal.SIGINT)) -> bool:
        """Install the flag-setting handler; returns False when not on the
        main thread (signal.signal would raise) — callers treat that as
        "preemption handling unavailable", not an error."""
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            return False
        prev = {}
        try:
            for s in signals:
                prev[s] = signal.signal(s, self._handle)
        except ValueError:
            for s, h in prev.items():
                signal.signal(s, h)
            return False
        self._prev = prev
        self._installed = tuple(signals)
        return True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s in self._installed:
            prev = self._prev.get(s)
            if prev is not None:
                try:
                    signal.signal(s, prev)
                except (ValueError, TypeError):
                    pass
        self._prev = {}
        self._installed = ()


def reserve_grace(deadline_s: Optional[float], fraction: float = 0.5,
                  floor_s: float = 0.0) -> Optional[float]:
    """Split one absolute (monotonic) emergency deadline between a drain
    phase and the final synchronous write.

    The async snapshot plane must drain its in-flight publish before the
    last-chance ``emergency_save`` runs — but both phases share ONE grace
    window (``CHAINERMN_TPU_PREEMPTION_GRACE_S``): the drain budget is
    SUBTRACTED from the window, never added on top. Returns the earlier
    deadline the drain phase must beat, reserving ``fraction`` of the
    remaining window (at least ``floor_s`` seconds) for the write; the
    caller keeps using the ORIGINAL ``deadline_s`` for the write itself.
    None passes through (no deadline → unbounded drain, the crash-path
    semantics)."""
    if deadline_s is None:
        return None
    now = time.monotonic()
    remaining = max(0.0, deadline_s - now)
    reserve = max(floor_s, remaining * fraction)
    return max(now, deadline_s - reserve)


def grace_seconds() -> float:
    raw = os.environ.get(_ENV_GRACE)
    if not raw:
        return _DEFAULT_GRACE_S
    try:
        v = float(raw)
    except ValueError:
        return _DEFAULT_GRACE_S
    return v if v > 0 else _DEFAULT_GRACE_S


_guard: Optional[PreemptionGuard] = None


def guard() -> PreemptionGuard:
    """The process-wide guard (created on first use, not installed)."""
    global _guard
    if _guard is None:
        _guard = PreemptionGuard()
    return _guard


def install_preemption_handler(
        signals: Tuple[int, ...] = (signal.SIGTERM,
                                    signal.SIGINT)) -> PreemptionGuard:
    """Install the process-wide guard's handler (idempotent) and return
    the guard. Safe to call off the main thread (it just won't install)."""
    g = guard()
    g.install(signals)
    return g


def preemption_requested() -> bool:
    """Has a preemption signal arrived? (False when no guard installed.)"""
    return _guard is not None and _guard.requested

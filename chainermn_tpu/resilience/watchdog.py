"""Heartbeat/watchdog: convert a dead peer into a bounded error.

A single wedged or killed host is the nastiest pod failure mode: every
symmetric collective blocks on the missing rank, the survivors sit in a
rendezvous with no deadline, and nobody notices until a human does. The
reference had MPI_Abort semantics for *crashes* (global except hook); a
SIGKILL leaves no hook to run.

This watchdog closes the gap at the host plane. Every process runs a
daemon thread that (1) bumps its own heartbeat key in the coordinator KV
store every ``interval_ms`` and (2) watches every peer's key; a peer
whose heartbeat stops advancing for ``timeout_ms`` is declared dead, the
abort poison key is posted (``object_plane.post_abort``), and every
process blocked in a guarded host-plane operation raises
:class:`~chainermn_tpu.comm.object_plane.JobAbortedError` within one
probe interval — an infinite hang becomes a bounded, catchable error
that restart orchestration can act on.

Configuration (env):

* ``CHAINERMN_TPU_HEARTBEAT_MS`` — beat/check cadence (default 5000);
* ``CHAINERMN_TPU_HEARTBEAT_TIMEOUT_MS`` — staleness threshold before a
  peer is declared dead (default 6 × the cadence);
* ``CHAINERMN_TPU_WATCHDOG=1`` — lets :func:`maybe_start_watchdog`
  (called by the Trainer) start it without code changes.

Device-plane collectives (XLA rendezvous) cannot be interrupted from
Python; the watchdog bounds every *host-plane* wait and makes the death
visible to the step loop between dispatches — the documented contract
(docs/fault_tolerance.md).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

_ENV_INTERVAL = "CHAINERMN_TPU_HEARTBEAT_MS"
_ENV_TIMEOUT = "CHAINERMN_TPU_HEARTBEAT_TIMEOUT_MS"
_ENV_ENABLE = "CHAINERMN_TPU_WATCHDOG"

_HB_PREFIX = "og/hb"


def _env_ms(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


class Watchdog:
    """One process's heartbeat publisher + peer monitor.

    ``client`` duck-types the jax.distributed coordinator client
    (``key_value_set``, ``key_value_try_get``/``blocking_key_value_get``)
    so tests can drive it with a fake; production passes None and the
    real client is resolved lazily.
    """

    def __init__(self, rank: int, world: int,
                 client=None,
                 interval_ms: Optional[int] = None,
                 timeout_ms: Optional[int] = None,
                 on_dead=None):
        self.rank = rank
        self.world = world
        self._client_override = client
        self.interval_ms = interval_ms if interval_ms is not None else (
            _env_ms(_ENV_INTERVAL, 5_000))
        self.timeout_ms = timeout_ms if timeout_ms is not None else (
            _env_ms(_ENV_TIMEOUT, 6 * self.interval_ms))
        self._on_dead = on_dead
        self._beat = 0
        self._overwrite_ok: Optional[bool] = None
        # peer -> (last seen value, monotonic time it last advanced)
        self._seen: Dict[int, tuple] = {}
        self.dead_peer: Optional[int] = None
        self.dead_reason: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- kv access -------------------------------------------------------

    def _client(self):
        if self._client_override is not None:
            return self._client_override
        from chainermn_tpu.comm import object_plane

        return object_plane._client()

    def _publish(self, client) -> None:
        self._beat += 1
        key = f"{_HB_PREFIX}/{self.rank}"
        if self._overwrite_ok is not False:
            try:
                client.key_value_set(key, str(self._beat),
                                     allow_overwrite=True)
                self._overwrite_ok = True
                return
            except TypeError:  # older client: no allow_overwrite kwarg
                self._overwrite_ok = False
            except Exception:
                return  # coordinator trouble: peers' probes handle it
        # no-overwrite fallback: versioned keys; readers scan forward
        try:
            client.key_value_set(f"{key}/{self._beat}", "1")
        except Exception:
            pass

    def _read_peer(self, client, peer: int) -> Optional[str]:
        key = f"{_HB_PREFIX}/{peer}"
        if self._overwrite_ok is not False:
            val = self._try_get(client, key)
            if val is not None:
                return val
        # versioned-key fallback: has the peer advanced past what we saw?
        last = self._seen.get(peer, (None, 0.0))[0]
        nxt = int(last) + 1 if str(last).isdigit() else 1
        if self._try_get(client, f"{key}/{nxt}") is not None:
            return str(nxt)
        # a peer we have never actually read stays None — the startup
        # grace in _check_peers owns that case
        return str(last) if last is not None else None

    @staticmethod
    def _try_get(client, key: str) -> Optional[str]:
        if hasattr(client, "key_value_try_get"):
            try:
                return client.key_value_try_get(key)
            except Exception:  # NotFound
                return None
        try:
            return client.blocking_key_value_get(key, 200)
        except Exception:
            return None

    # -- monitoring ------------------------------------------------------

    def _check_peers(self, client) -> None:
        now = time.monotonic()
        for peer in range(self.world):
            if peer == self.rank:
                continue
            val = self._read_peer(client, peer)
            if val is None:
                # never seen: startup grace — start the staleness clock
                self._seen.setdefault(peer, (None, now))
                val, since = self._seen[peer]
                if val is None and (now - since) * 1000 > 2 * self.timeout_ms:
                    self._declare_dead(peer, "never published a heartbeat")
                continue
            prev = self._seen.get(peer)
            if prev is None or prev[0] != val:
                self._seen[peer] = (val, now)
            elif (now - prev[1]) * 1000 > self.timeout_ms:
                self._declare_dead(
                    peer, f"heartbeat stalled at beat {val} for "
                          f"{int((now - prev[1]) * 1000)} ms")

    def _declare_dead(self, peer: int, why: str) -> None:
        if self.dead_peer is not None:
            return
        self.dead_peer = peer
        self.dead_reason = f"watchdog(rank {self.rank}): peer {peer} {why}"
        try:
            from chainermn_tpu.comm.object_plane import post_abort

            post_abort(self.dead_reason)
        except Exception:
            pass
        if self._on_dead is not None:
            try:
                self._on_dead(peer, self.dead_reason)
            except Exception:
                pass

    def check(self) -> None:
        """Raise JobAbortedError if this watchdog declared a peer dead —
        the step loop's cheap per-iteration poll."""
        if self.dead_peer is not None:
            from chainermn_tpu.comm.object_plane import JobAbortedError

            raise JobAbortedError(self.dead_reason)

    # -- thread lifecycle ------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            client = self._client()
            if client is not None:
                try:
                    self._publish(client)
                    self._check_peers(client)
                except Exception:
                    pass  # transient coordinator trouble: retry next beat
            if self.dead_peer is not None:
                return  # job is aborted; nothing further to monitor
            self._stop.wait(self.interval_ms / 1000.0)

    def start(self) -> "Watchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"chainermn-watchdog-{self.rank}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


_watchdog: Optional[Watchdog] = None


def start_watchdog(interval_ms: Optional[int] = None,
                   timeout_ms: Optional[int] = None) -> Optional[Watchdog]:
    """Start the process-wide watchdog (idempotent). Returns None in a
    single-process job — there is no peer to watch."""
    global _watchdog
    import jax

    if jax.process_count() <= 1:
        return None
    if _watchdog is None:
        _watchdog = Watchdog(jax.process_index(), jax.process_count(),
                             interval_ms=interval_ms,
                             timeout_ms=timeout_ms)
    return _watchdog.start()


def maybe_start_watchdog() -> Optional[Watchdog]:
    """Start the watchdog iff $CHAINERMN_TPU_WATCHDOG is truthy — the
    Trainer's opt-in hook."""
    if os.environ.get(_ENV_ENABLE, "").lower() in ("", "0", "false"):
        return None
    return start_watchdog()


def stop_watchdog() -> None:
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None


def current_watchdog() -> Optional[Watchdog]:
    return _watchdog

"""One RPC timeout/backoff policy for the whole host plane.

Before this module existed, `comm/object_plane.py` scattered its deadline
logic: hard-coded 600 s key-wait budgets, a 60 s allgather barrier, a
10 s probe slice, and a (2 s, 5 s) liveness retry ladder — four unrelated
knobs that all had to agree for fail-fast detection to work. They now
derive from one :class:`RpcPolicy`, configured by environment variables so
the chaos/mp tests (and real deployments with flakier coordinators) can
shrink or stretch every budget coherently:

``CHAINERMN_TPU_RPC_TIMEOUT_MS``
    The total budget for one blocking host-plane operation (a key wait, a
    barrier, a chunked put). Default 600 000 (the historical constant).
``CHAINERMN_TPU_RPC_PROBE_MS``
    Fail-fast granularity: long waits are sliced into probes of this
    length so a dead coordinator/aborted job is noticed in O(probe), not
    O(timeout). Default 10 000.

Retries between probe slices follow jittered exponential backoff
(deterministic when seeded — the chaos harness pins the seed so failure
schedules replay exactly).
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

_ENV_TIMEOUT = "CHAINERMN_TPU_RPC_TIMEOUT_MS"
_ENV_PROBE = "CHAINERMN_TPU_RPC_PROBE_MS"

_DEFAULT_TIMEOUT_MS = 600_000
_DEFAULT_PROBE_MS = 10_000


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer millisecond count")
    if v <= 0:
        raise ValueError(f"{name} must be positive, got {v}")
    return v


@dataclass(frozen=True)
class RpcPolicy:
    """Deadlines and retry shape for coordinator (host-plane) RPCs.

    ``timeout_ms``  — total budget for one blocking operation;
    ``probe_ms``    — liveness-probe slice length;
    ``backoff_base_ms``/``backoff_max_ms``/``backoff_factor``/``jitter``
    — the retry ladder: attempt ``k`` waits
    ``min(base * factor**k, max) * (1 ± jitter)``.
    """

    timeout_ms: int = _DEFAULT_TIMEOUT_MS
    probe_ms: int = _DEFAULT_PROBE_MS
    backoff_base_ms: int = 100
    backoff_max_ms: int = 5_000
    backoff_factor: float = 2.0
    jitter: float = 0.25
    seed: Optional[int] = None

    @classmethod
    def from_env(cls) -> "RpcPolicy":
        return cls(timeout_ms=_env_int(_ENV_TIMEOUT, _DEFAULT_TIMEOUT_MS),
                   probe_ms=_env_int(_ENV_PROBE, _DEFAULT_PROBE_MS))

    def _rng(self) -> random.Random:
        return random.Random(self.seed)

    def backoff_ms(self, attempt: int,
                   rng: Optional[random.Random] = None) -> int:
        """Jittered exponential delay before retry ``attempt`` (0-based)."""
        if rng is None:
            rng = self._rng() if self.seed is not None else random
        base = min(self.backoff_base_ms * self.backoff_factor ** attempt,
                   float(self.backoff_max_ms))
        lo, hi = base * (1 - self.jitter), base * (1 + self.jitter)
        return max(1, int(rng.uniform(lo, hi)))

    def backoffs_ms(self, n: int) -> Iterator[int]:
        """The first ``n`` delays of the ladder (one shared RNG so a
        seeded policy yields a reproducible schedule)."""
        rng = self._rng() if self.seed is not None else None
        for k in range(n):
            yield self.backoff_ms(k, rng=rng)

    def liveness_ladder_ms(self) -> Tuple[int, ...]:
        """Per-attempt deadlines for the coordinator liveness check: two
        short attempts scaled off the probe slice (historically 2 s and
        5 s under the 10 s probe) — a loaded coordinator may miss one
        short deadline, so the second attempt waits longer."""
        return (max(1, self.probe_ms // 5), max(1, self.probe_ms // 2))

    def barrier_ms(self) -> int:
        """Budget for one host-plane barrier: barriers gate short
        metadata exchanges (allgather inventories), so they get a tenth
        of the payload budget, floored at one probe slice."""
        return max(self.probe_ms, self.timeout_ms // 10)

    def handoff_ack_ms(self) -> int:
        """Per-attempt budget for one handoff-frame acknowledgement
        (fleet/transport.py). One probe slice: an unacked frame should
        re-send in O(probe), not ride out the full payload timeout —
        the re-send itself is bounded by the transport's attempt cap."""
        return max(1, self.probe_ms)

    def put_budget_ms(self, nchunks: int) -> int:
        """Budget for a chunked KV put — scales with payload so multi-GB
        scatters aren't cut off (one probe slice of headroom per chunk)."""
        return self.timeout_ms + self.probe_ms * max(1, nchunks)


_policy: Optional[RpcPolicy] = None


def policy() -> RpcPolicy:
    """The process-wide policy (env-derived, cached on first use)."""
    global _policy
    if _policy is None:
        _policy = RpcPolicy.from_env()
    return _policy


def set_policy(p: Optional[RpcPolicy]) -> Optional[RpcPolicy]:
    """Install ``p`` as the process-wide policy (``None`` re-derives from
    the environment on next use). Returns the previous policy — tests
    restore it."""
    global _policy
    prev, _policy = _policy, p
    return prev

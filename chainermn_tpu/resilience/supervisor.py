"""Per-host supervisor: crashes heal by restart, not by a human.

PR 2's resilience layer made failures *detectable* (chaos injection,
watchdog aborts, consensus resume) but recovery stayed manual: a
SIGKILLed rank sat dead until someone relaunched it. This module closes
the loop — a tiny per-host parent process that wraps the training
command, classifies each exit, and relaunches:

* **clean** (exit 0) — the run finished; the supervisor exits 0.
* **preempted** (exit :data:`PREEMPTED_EXIT_CODE`, 143) — the child
  checkpointed inside its grace window and left voluntarily; restart is
  free (it does NOT count against the crash budget — preemptions are
  the platform's fault, and looping on them is the desired behavior).
* **aborted** (exit :data:`ABORTED_EXIT_CODE`, 75 = EX_TEMPFAIL) — the
  child's watchdog detected a dead peer and bounded the hang
  (``JobAbortedError``); the job is resumable once the peer's
  supervisor brings IT back, so restart — but count it: if the peer
  never returns, every incarnation re-aborts and the budget must trip.
* **crash** (anything else: nonzero exit, death by signal) — restart
  and count it against the budget.

The budget is N restarts per rolling window (:class:`RestartBudget`);
when it trips, the supervisor exits :data:`BUDGET_EXHAUSTED_EXIT_CODE`
with a diagnostic listing the exit history — a crash-loop stops after N
attempts instead of burning the pod forever. Between counted restarts
the supervisor sleeps the jittered exponential ladder of the shared
:class:`~chainermn_tpu.resilience.policy.RpcPolicy`, so a whole pod's
supervisors don't relaunch in lockstep and re-stampede the coordinator.

Each incarnation gets ``$CHAINERMN_TPU_RESTART_COUNT`` in its
environment — the chaos harness's ``run=`` fault key reads it, so a
spec like ``kill@step=7,run=0`` kills only the first incarnation (the
kill-then-heal test shape), while an unconditional ``kill@step=7``
produces the crash-loop the budget exists for.

Exit-status contract (the child side) lives in
:func:`main_exit_code` / ``Trainer.exit_code()`` — see
docs/fault_tolerance.md for the decision table.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from chainermn_tpu.resilience.policy import RpcPolicy
from chainermn_tpu.resilience.preemption import PREEMPTED_EXIT_CODE

#: exit code a training process uses for "watchdog aborted the job —
#: a peer died; restart me once the peer is back" (EX_TEMPFAIL: the
#: sysexits.h code for "transient failure, retry later")
ABORTED_EXIT_CODE = 75

#: the SUPERVISOR's own exit code when the restart budget trips — the
#: wrapped job is crash-looping and needs a human (distinct from every
#: child code so orchestrators can tell "gave up" from "crashed")
BUDGET_EXHAUSTED_EXIT_CODE = 112

#: environment variable carrying the incarnation number (0 for the
#: first launch) into the child — read by the chaos harness's ``run=``
#: fault key and available to training code for logging
RESTART_COUNT_ENV = "CHAINERMN_TPU_RESTART_COUNT"


def restart_count() -> int:
    """This process's supervised-incarnation number: 0 on the first
    launch (or when unsupervised). Scripts key per-incarnation
    artifacts off this — e.g. ``tools/fleet_lm.py --hosts`` names each
    incarnation's JSONL part file with it, so a restart NEVER appends
    to a file a SIGKILL may have left with a torn trailing line."""
    raw = os.environ.get(RESTART_COUNT_ENV)
    if raw is not None:
        try:
            return int(raw)
        except ValueError:
            pass
    return 0


def classify_exit(returncode: int) -> str:
    """One of ``clean`` / ``preempted`` / ``aborted`` / ``crash``.

    Negative returncodes are deaths by signal (subprocess convention).
    A death by unhandled SIGTERM (-15) still counts as ``preempted``:
    the platform sent the signal but the child had no handler installed
    — restarting it is right, billing the crash budget for the
    platform's preemption is not. A death by unhandled SIGUSR1 (-10)
    counts as ``clean``: SIGUSR1 is the fleet's drain request
    (``tools/serve_lm.py`` / ``tools/fleet_lm.py`` catch it, finish or
    migrate their sessions, and exit 0) — a serving binary too old to
    carry the handler must not bill the crash budget for being asked
    to retire."""
    if returncode == 0 or returncode == -signal.SIGUSR1:
        return "clean"
    if returncode == PREEMPTED_EXIT_CODE or returncode == -signal.SIGTERM:
        return "preempted"
    if returncode == ABORTED_EXIT_CODE:
        return "aborted"
    return "crash"


class RestartBudget:
    """N counted restarts per rolling window of ``window_s`` seconds.

    ``try_spend`` prunes events older than the window, then either
    records the restart and returns True, or returns False — the
    supervisor must stop. A long-healthy job earns its budget back as
    old crashes age out of the window."""

    def __init__(self, max_restarts: int = 5, window_s: float = 3600.0):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0: {max_restarts}")
        if window_s <= 0:
            raise ValueError(f"window_s must be positive: {window_s}")
        self.max_restarts = max_restarts
        self.window_s = window_s
        self._events: List[float] = []

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        self._events = [t for t in self._events if t > cutoff]

    def remaining(self, now: Optional[float] = None) -> int:
        if now is None:
            now = time.monotonic()
        self._prune(now)
        return max(0, self.max_restarts - len(self._events))

    def try_spend(self, now: Optional[float] = None) -> bool:
        if now is None:
            now = time.monotonic()
        self._prune(now)
        if len(self._events) >= self.max_restarts:
            return False
        self._events.append(now)
        return True


@dataclass
class ExitRecord:
    """One child incarnation's outcome, for the give-up diagnostic."""

    incarnation: int
    returncode: int
    kind: str
    runtime_s: float


@dataclass
class Supervisor:
    """Wrap ``cmd`` in a restart loop with a bounded crash budget.

    ``run()`` returns the supervisor's own exit status: the child's
    code on a terminal outcome (clean finish, preemption with
    ``restart_on_preempt=False``), or
    :data:`BUDGET_EXHAUSTED_EXIT_CODE` when the budget trips.

    ``sleep`` / ``spawn`` are injection points for tests (the chaos
    crash-loop test runs a real child but fakes no time)."""

    cmd: Sequence[str]
    max_restarts: int = 5
    window_s: float = 3600.0
    restart_on_preempt: bool = True
    policy: Optional[RpcPolicy] = None
    env: Optional[Dict[str, str]] = None
    sleep: Callable[[float], None] = time.sleep
    spawn: Optional[Callable[..., subprocess.Popen]] = None
    history: List[ExitRecord] = field(default_factory=list)

    def __post_init__(self):
        self.cmd = list(self.cmd)
        if not self.cmd:
            raise ValueError("supervisor needs a non-empty command")
        if self.policy is None:
            self.policy = RpcPolicy.from_env()
        self.budget = RestartBudget(self.max_restarts, self.window_s)

    def _log(self, msg: str) -> None:
        print(f"[supervise] {msg}", file=sys.stderr, flush=True)

    def _launch(self, incarnation: int) -> subprocess.Popen:
        env = dict(os.environ if self.env is None else self.env)
        env[RESTART_COUNT_ENV] = str(incarnation)
        spawn = self.spawn or subprocess.Popen
        return spawn(self.cmd, env=env)

    def run(self) -> int:
        incarnation = 0
        attempt = 0  # consecutive counted failures, drives the backoff
        while True:
            t0 = time.monotonic()
            self._log(f"launch #{incarnation}: {' '.join(self.cmd)}")
            proc = self._launch(incarnation)
            try:
                rc = proc.wait()
            except KeyboardInterrupt:
                # the operator killed the SUPERVISOR: forward, reap, stop
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                raise
            runtime = time.monotonic() - t0
            kind = classify_exit(rc)
            self.history.append(ExitRecord(incarnation, rc, kind, runtime))
            self._log(f"#{incarnation} exited {rc} ({kind}) "
                      f"after {runtime:.1f}s")

            if kind == "clean":
                return 0
            if kind == "preempted":
                if not self.restart_on_preempt:
                    return PREEMPTED_EXIT_CODE
                # free restart: preemptions are the platform's doing —
                # reset the failure streak, brief fixed pause (the
                # resource usually needs a moment to come back)
                attempt = 0
                self.sleep(self.policy.backoff_ms(0) / 1000.0)
            else:  # aborted or crash: counted
                if not self.budget.try_spend():
                    self._log(self._give_up_diagnostic())
                    return BUDGET_EXHAUSTED_EXIT_CODE
                delay = self.policy.backoff_ms(attempt) / 1000.0
                self._log(f"restarting in {delay:.2f}s "
                          f"(budget: {self.budget.remaining()} of "
                          f"{self.max_restarts} left in "
                          f"{self.window_s:.0f}s window)")
                attempt += 1
                self.sleep(delay)
            incarnation += 1

    def _give_up_diagnostic(self) -> str:
        lines = [
            f"restart budget exhausted: {self.max_restarts} counted "
            f"restart(s) within {self.window_s:.0f}s — the job is "
            "crash-looping; NOT restarting again.",
            "exit history (newest last):",
        ]
        for r in self.history[-(self.max_restarts + 2):]:
            lines.append(f"  #{r.incarnation}: exit {r.returncode} "
                         f"({r.kind}) after {r.runtime_s:.1f}s")
        lines.append(
            "next steps: inspect the newest incarnation's logs; if a "
            "peer host is permanently gone, resume on a smaller mesh "
            "(shrink-to-fit, docs/fault_tolerance.md#elastic-recovery).")
        return "\n".join(lines)


def _is_job_aborted(exc: BaseException) -> bool:
    # lazy import: JobAbortedError lives in the comm package, which
    # pulls jax — main_exit_code must stay usable in host-only tools
    try:
        from chainermn_tpu.comm.object_plane import JobAbortedError
    except Exception:
        return False
    return isinstance(exc, JobAbortedError)


def main_exit_code(main: Callable[..., object], *args, **kwargs) -> int:
    """Run a train script's ``main()`` and translate its outcome into
    the supervisor's exit-status contract:

    * returns normally, no preemption → 0 (clean);
    * the returned object (a ``Trainer``, or anything with a truthy
      ``preempted`` attribute) was preempted →
      :data:`PREEMPTED_EXIT_CODE`;
    * raises ``JobAbortedError`` (watchdog: a peer died) →
      :data:`ABORTED_EXIT_CODE`;
    * any other exception propagates (the interpreter's exit 1 reads as
      a crash — which it is).

    Usage in an example script::

        if __name__ == '__main__':
            sys.exit(main_exit_code(main))
    """
    try:
        result = main(*args, **kwargs)
    except BaseException as e:
        if _is_job_aborted(e):
            import traceback

            traceback.print_exc()
            return ABORTED_EXIT_CODE
        raise
    if getattr(result, "preempted", False):
        return PREEMPTED_EXIT_CODE
    return 0

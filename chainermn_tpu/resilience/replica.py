"""Ring replication of snapshot shards: a dead host's NEWEST state
survives on its neighbor.

The consensus election (extensions/checkpoint.py) can only elect an
iteration every rank still holds; when a host dies AND its disk goes
with it, the election falls back to an older common iteration — or, if
the window slid, to nothing. This extension closes that gap: after
each checkpoint trigger, every rank pushes its newest *verified*
snapshot file (plus its SHA-256 manifest) to its ring neighbor
``(rank+1) % world`` over the host object plane, and persists the copy
it receives from ``(rank-1) % world`` under
``<ckpt>/replicas/snapshot_iter_<N>.<source-rank>``.

The checkpointer already knows to look there: its election inventory
counts valid replicas of its own shard (``_valid_iters_on_disk``), the
completeness check counts replicas of ANY rank
(``_complete_iters_on_disk``), restore falls back to the replica when
the primary is missing or corrupt (``_own_file``), and the peer-splice
path globs the replica directory (``_PeerSnapshots``). So after a host
is replaced: its supervisor restarts the process, the fresh rank finds
its neighbor's replica of its own shard (shared filesystem) — or, with
per-host disks, shrink-to-fit (resilience/elastic.py) splices the
surviving primaries + replicas onto the smaller mesh.

Costs (see docs/fault_tolerance.md#replication-costs): one extra copy
of each rank's shard crosses the host plane per replication trigger and
lands on the neighbor's disk — fire it sparser than the checkpoint
trigger when shards are large. The exchange is collective (every rank
sends one message and receives one, even when it has nothing new), so
attach it on ALL ranks with the SAME trigger.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional

from chainermn_tpu.resilience import chaos as _chaos

#: object-plane p2p tag reserved for the replication ring (keeps its
#: KV sequence counters separate from user send_obj/recv_obj traffic)
REPLICA_TAG = 7


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        try:
            os.fsync(fh.fileno())
        except OSError:
            pass  # fsync unsupported (some tmpfs) — rename still atomic
    os.replace(tmp, path)


class PeerReplicator:
    """Trainer extension: ring-replicate the newest verified snapshot.

    ``trainer.extend(PeerReplicator(ck), trigger=...)`` AFTER extending
    the checkpointer ``ck`` itself (extensions fire in attach order, so
    the snapshot of the current iteration is published before the
    exchange). With one process the extension is a no-op.

    ``keep`` bounds the replicas retained per source rank (default: the
    checkpointer's ``cp_interval``); pruning never touches an iteration
    the checkpointer protects (the consensus winner / explicit pins).
    """

    def __init__(self, checkpointer, keep: Optional[int] = None):
        self.ck = checkpointer
        self.comm = checkpointer.comm
        self.keep = keep if keep is not None else checkpointer.cp_interval
        self._last_sent: Optional[int] = None

    # -- payload assembly ------------------------------------------------

    def _newest_verified_own(self) -> Optional[int]:
        """Newest iteration whose PRIMARY own file verifies (replicas of
        our shard are already copies — resending them is pure waste)."""
        for it in reversed(self.ck._iters_on_disk()):
            fn = os.path.join(
                self.ck.path,
                f"snapshot_iter_{it}.{self.comm.inter_rank}")
            if not os.path.isdir(fn) and self.ck._verify_snapshot_file(fn):
                return it
        return None

    def _build_payload(self) -> Dict[str, Any]:
        it = self._newest_verified_own()
        if it is None or it == self._last_sent:
            # nothing new — the exchange still happens (peers' recv
            # counts must match sends), just with an empty payload
            return {"iteration": None}
        fn = os.path.join(
            self.ck.path, f"snapshot_iter_{it}.{self.comm.inter_rank}")
        try:
            with open(fn, "rb") as fh:
                data = fh.read()
            manifest = None
            if os.path.exists(fn + ".json"):
                with open(fn + ".json", "rb") as fh:
                    manifest = fh.read()
        except OSError as e:
            warnings.warn(f"replica: could not read {fn} ({e}); "
                          "skipping this round")
            return {"iteration": None}
        self._last_sent = it
        return {"iteration": it, "rank": self.comm.inter_rank,
                "data": data, "manifest": manifest}

    # -- receive side ----------------------------------------------------

    def _store(self, payload: Dict[str, Any]) -> Optional[str]:
        it = payload.get("iteration")
        if it is None:
            return None
        src = int(payload["rank"])
        dst = os.path.join(self.ck.replica_path,
                           f"snapshot_iter_{it}.{src}")
        try:
            os.makedirs(self.ck.replica_path, exist_ok=True)
            # same chaos injection point as the primary publish: a full
            # disk breaks the replica too (and the test can prove the
            # election still works off the primaries)
            _chaos.on_publish(dst)
            _atomic_write(dst, payload["data"])
            if payload.get("manifest") is not None:
                _atomic_write(dst + ".json", payload["manifest"])
        except OSError as e:
            # best-effort by design: losing a replica copy must never
            # kill the training step that triggered the exchange
            warnings.warn(f"replica: could not store {dst} ({e})")
            return None
        self._prune(src)
        return dst

    def _prune(self, src: int) -> None:
        """Bound the replicas held for ``src`` to the ``keep`` newest,
        never dropping an iteration the checkpointer protects."""
        import re

        pat = re.compile(rf"snapshot_iter_(\d+)\.{src}$")
        if not os.path.isdir(self.ck.replica_path):
            return
        its = sorted(
            int(m.group(1)) for f in os.listdir(self.ck.replica_path)
            if (m := pat.match(f)))
        protected = set(getattr(self.ck, "_protected", ()))
        elected = getattr(self.ck, "_elected", None)
        if elected is not None:
            protected.add(elected)
        for it in its[:-self.keep] if self.keep else its:
            if it in protected:
                continue
            fn = os.path.join(self.ck.replica_path,
                              f"snapshot_iter_{it}.{src}")
            for victim in (fn, fn + ".json"):
                try:
                    os.remove(victim)
                except OSError:
                    pass

    # -- trainer-extension protocol --------------------------------------

    def replicate(self, drain: bool = True) -> Optional[str]:
        """One ring exchange; returns the stored replica path (None when
        the neighbor had nothing new). Collective: every rank must call
        with the same cadence.

        ``drain=False`` skips the checkpointer-queue join — the async
        snapshot plane (checkpointing/async_plane.py) calls from its OWN
        writer thread right after publishing, where a drain would
        self-deadlock on the item being processed."""
        world = self.comm.inter_size
        if world < 2:
            return None
        # published files only — an in-flight async write is invisible
        # and a FAILED one must not block the exchange (peers are
        # already waiting in recv)
        if drain:
            self.ck._drain()
        right = (self.comm.inter_rank + 1) % world
        left = (self.comm.inter_rank - 1) % world
        # KV-store p2p: the put returns without waiting on the peer, so
        # send-then-recv around the ring cannot deadlock
        self.comm.send_obj(self._build_payload(), right, tag=REPLICA_TAG)
        payload = self.comm.recv_obj(left, tag=REPLICA_TAG)
        return self._store(payload)

    def __call__(self, trainer) -> None:  # noqa: ARG002 (protocol)
        self.replicate()

"""Fault injection + preemption survival for distributed training.

The stack's fault-tolerance story (PAPER.md §2.5/§3.5) was restart-based
and untested: per-rank snapshots plus a consensus election, assuming
clean process death and intact files. This package supplies both the
*machinery* to survive the real failure modes and the *chaos harness*
that injects them so tests can prove it:

* :mod:`.chaos` — deterministic, seed-driven fault injection (kill a
  rank at step N, delay/blackhole coordinator RPCs, corrupt/truncate a
  checkpoint file), activated via ``$CHAINERMN_TPU_CHAOS``;
* :mod:`.preemption` — SIGTERM/SIGINT → flag → emergency checkpoint →
  clean exit (the Trainer polls it every step);
* :mod:`.watchdog` — per-process heartbeat thread that converts a dead
  peer's infinite collective hang into a bounded ``JobAbortedError``;
* :mod:`.policy` — the one RPC timeout/backoff policy the host plane's
  retry logic derives from (``$CHAINERMN_TPU_RPC_TIMEOUT_MS``);
* :mod:`.supervisor` — per-host restart loop with a bounded crash
  budget (``tools/supervise.py`` is the CLI): crashes heal by
  relaunch, crash-loops stop with a diagnostic;
* :mod:`.replica` — ring replication of each rank's newest verified
  snapshot to its neighbor, so a dead host's shard survives;
* :mod:`.elastic` — shrink-to-fit resume: when a host is permanently
  gone, re-splice the surviving shards onto the smaller world.

See docs/fault_tolerance.md for the failure-mode table and cookbook.
"""

from chainermn_tpu.resilience.chaos import (
    ChaosPlan,
    Fault,
    FAULT_KINDS,
    chaos_from_env,
    parse_spec,
)
from chainermn_tpu.resilience.elastic import (
    ElasticPlan,
    ElasticResumeError,
    ElasticTopologyError,
    elastic_resume,
    plan_elastic_resume,
)
from chainermn_tpu.resilience.policy import RpcPolicy, policy, set_policy
from chainermn_tpu.resilience.replica import PeerReplicator
from chainermn_tpu.resilience.supervisor import (
    ABORTED_EXIT_CODE,
    BUDGET_EXHAUSTED_EXIT_CODE,
    RESTART_COUNT_ENV,
    RestartBudget,
    Supervisor,
    classify_exit,
    main_exit_code,
)
from chainermn_tpu.resilience.preemption import (
    PREEMPTED_EXIT_CODE,
    PreemptionGuard,
    install_preemption_handler,
    preemption_requested,
)
from chainermn_tpu.resilience.watchdog import (
    Watchdog,
    current_watchdog,
    maybe_start_watchdog,
    start_watchdog,
    stop_watchdog,
)

__all__ = [
    "ChaosPlan",
    "Fault",
    "FAULT_KINDS",
    "chaos_from_env",
    "parse_spec",
    "ElasticPlan",
    "ElasticResumeError",
    "ElasticTopologyError",
    "elastic_resume",
    "plan_elastic_resume",
    "RpcPolicy",
    "policy",
    "set_policy",
    "PeerReplicator",
    "ABORTED_EXIT_CODE",
    "BUDGET_EXHAUSTED_EXIT_CODE",
    "RESTART_COUNT_ENV",
    "RestartBudget",
    "Supervisor",
    "classify_exit",
    "main_exit_code",
    "PREEMPTED_EXIT_CODE",
    "PreemptionGuard",
    "install_preemption_handler",
    "preemption_requested",
    "Watchdog",
    "current_watchdog",
    "maybe_start_watchdog",
    "start_watchdog",
    "stop_watchdog",
]

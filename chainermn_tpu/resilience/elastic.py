"""Shrink-to-fit resume: continue on a smaller mesh when a host is
permanently gone.

The consensus election and restart loop assume the SAME world size
comes back; when a host (and its disk) is gone for good, that never
happens — yet the surviving ranks still hold (or ring-hold, see
resilience/replica.py) every byte of state needed to continue. This
module plans and executes that continuation:

1. :func:`plan_elastic_resume` — elect the newest iteration the
   CURRENT (smaller) world can recover, compare against the saved
   world size recorded in each snapshot (``__world__``), and decide:
   ``resume`` (same world — the normal path), ``shrink`` (fewer
   processes than saved — re-splice), or ``give_up`` (nothing
   recoverable) — the decision table in
   docs/fault_tolerance.md#elastic-recovery.
2. :func:`elastic_resume` — execute the plan: load the device pytree
   through the checkpointer's splice path (``allow_incomplete=True``
   bypasses the complete-file-set gate; the per-leaf coverage check in
   ``_SpliceTargets.require_complete`` still rejects a genuinely
   missing shard), then rebalance the HOST side — re-scatter the
   dataset over the surviving processes and reposition the iterator.

What shrinking preserves and what it does not:

* device state — exact (replicated leaves load from any file; sharded
  leaves are spliced from all surviving files, and a shard nobody
  holds fails loudly);
* overall progress (iteration count, epoch counters) — exact;
* the data order — approximate: per-rank shards are re-split for the
  new world, so the resumed run draws from a freshly balanced shard at
  the equivalent position instead of replaying the exact batch
  schedule of the dead configuration;
* loss/grad averaging — automatic for steps built on
  ``allreduce_grad(op="mean")`` against the CURRENT communicator
  (they divide by the live world size); steps that baked the OLD
  world size into a constant must multiply by
  :attr:`ElasticPlan.averaging_rescale`.

Multi-axis meshes: snapshots restore by GLOBAL INDEX (the checkpointer
splices saved shard ranges onto whatever the template's sharding asks
for), so a tensor/pipeline-parallel mesh change is index-correct by
construction. The one genuinely world-DEPENDENT leaf class — the
flat-bucket error-feedback residual stacks from ``optimizers/zero.py``,
saved as ``(n_ranks, padded)`` frames — is regrouped by the
manifest-driven reshard path (``checkpointing/reshard.py``); such plans
come back as ``action="reshard"``. :class:`ElasticTopologyError`
(historically raised for any multi-axis mesh) is retained for
compatibility with callers that catch it, but the planner no longer
raises it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from chainermn_tpu.datasets import scatter_dataset


class ElasticResumeError(RuntimeError):
    """Elastic resume cannot proceed (nothing recoverable)."""


class ElasticTopologyError(ElasticResumeError):
    """The mesh topology does not support elastic resharding.

    Retained for compatibility: since the manifest-driven reshard path
    (checkpointing/reshard.py) landed, multi-axis meshes plan as
    ``action="reshard"`` instead of raising this."""


@dataclass
class ElasticPlan:
    """The decision :func:`plan_elastic_resume` reached.

    ``action`` is ``resume`` / ``shrink`` / ``reshard`` / ``give_up``;
    ``averaging_rescale`` is ``saved_world / new_world`` — multiply
    into any loss/grad normalization that baked in the OLD world size
    (steps averaging through the live communicator need no fix).
    ``saved_axes``/``new_axes`` carry the mesh axis→size maps for
    ``reshard`` plans (from the coverage manifest and the live mesh;
    None when unknowable)."""

    action: str
    iteration: Optional[int]
    saved_world: Optional[int]
    new_world: int
    reason: str
    averaging_rescale: float = 1.0
    saved_axes: Optional[Dict[str, int]] = field(default=None)
    new_axes: Optional[Dict[str, int]] = field(default=None)

    def describe(self) -> str:
        return (f"elastic plan: {self.action} at iteration "
                f"{self.iteration} (saved world {self.saved_world}, "
                f"current {self.new_world}) — {self.reason}")


def _axes_total(axes: Optional[Dict[str, int]]) -> Optional[int]:
    """Total device count spanned by an axis→size map (None when
    unknown)."""
    if not axes:
        return None
    n = 1
    for v in axes.values():
        n *= int(v)
    return n


def _recoverable_iters(ck) -> List[int]:
    """Iterations THIS rank can contribute to a shrunken election: any
    iteration with at least one valid file visible on this filesystem
    (own primary, a peer's primary on shared storage, or a ring
    replica). Per-leaf completeness is checked at load time — this is
    the cheap inventory, not the guarantee."""
    import os
    import re

    seen = set(ck._valid_iters_on_disk())
    pat = re.compile(r"snapshot_iter_(\d+)\.(\d+)$")
    for d in (ck.path, ck.replica_path):
        if not os.path.isdir(d):
            continue
        for f in os.listdir(d):
            m = pat.match(f)
            if not m or int(m.group(1)) in seen:
                continue
            fn = os.path.join(d, f)
            if not os.path.isdir(fn) and ck._verify_snapshot_file(fn):
                seen.add(int(m.group(1)))
    return sorted(seen)


def plan_elastic_resume(ck) -> ElasticPlan:
    """Elect over the CURRENT world and classify the resume.

    Collective: every surviving process must call it (the inventory is
    allgathered). Never raises for "nothing found" — that returns a
    ``give_up`` plan so the caller can report and exit. A snapshot
    whose MESH differs from the current one (multi-axis reshape, tile
    re-layout) plans as ``action="reshard"`` — executed through the
    manifest-driven path in ``checkpointing/reshard.py``."""
    comm = ck.comm
    world = comm.inter_size
    ck._drain()
    ck._pre_election_barrier()
    mine = _recoverable_iters(ck)
    all_lists = comm.allgather_obj(mine)
    common = set(all_lists[0])
    for lst in all_lists[1:]:
        common &= set(lst)
    if not common:
        return ElasticPlan(
            action="give_up", iteration=None, saved_world=None,
            new_world=world,
            reason="no snapshot iteration is recoverable on every "
                   "surviving process — nothing to resume from "
                   f"(per-rank inventories: {all_lists})")
    it = max(common)
    ck._elected = it  # pin against GC, same as the strict election
    saved = ck._saved_world(it)
    from chainermn_tpu.checkpointing.reshard import mesh_axes, saved_axes

    cur_axes = mesh_axes(comm)
    sv_axes = saved_axes(ck, it)
    axes_changed = (sv_axes is not None and cur_axes is not None
                    and sv_axes != cur_axes)
    multi = len(tuple(getattr(comm, "axis_names", ()) or ())) > 1
    if axes_changed or (multi and saved is not None and saved != world):
        sv_n, cur_n = _axes_total(sv_axes), _axes_total(cur_axes)
        rescale = (sv_n / cur_n if sv_n and cur_n
                   else (saved / world if saved else 1.0))
        return ElasticPlan(
            action="reshard", iteration=it, saved_world=saved,
            new_world=world, averaging_rescale=rescale,
            saved_axes=sv_axes, new_axes=cur_axes,
            reason=f"snapshot mesh {sv_axes} differs from the current "
                   f"mesh {cur_axes} — re-splicing through the "
                   "manifest-driven reshard path "
                   "(checkpointing/reshard.py)")
    if saved is None or saved == world:
        return ElasticPlan(
            action="resume", iteration=it, saved_world=saved,
            new_world=world, saved_axes=sv_axes, new_axes=cur_axes,
            reason="saved world matches the current world"
                   if saved == world else
                   "saved world unknown (pre-marker snapshot) — "
                   "assuming shape-preserving resume")
    rescale = saved / world
    return ElasticPlan(
        action="shrink", iteration=it, saved_world=saved,
        new_world=world, averaging_rescale=rescale,
        saved_axes=sv_axes, new_axes=cur_axes,
        reason=f"snapshot was saved by {saved} process(es), "
               f"{world} survive — re-splicing shards onto the "
               "smaller mesh")


def elastic_resume(ck, updater, global_dataset: Any = None,
                   shuffle: bool = False,
                   seed: Optional[int] = None) -> ElasticPlan:
    """Plan + execute: restore ``updater`` at the newest recoverable
    iteration on the current world size, rebalancing the host side.

    ``global_dataset`` is the FULL dataset (the thing originally passed
    to ``scatter_dataset``); when given, it is re-scattered over the
    surviving processes and installed as the iterator's dataset —
    without it, the iterator keeps its existing (old-world) shard and
    only the position is rebalanced. Returns the executed
    :class:`ElasticPlan`; raises :class:`ElasticResumeError` on a
    ``give_up`` plan."""
    plan = plan_elastic_resume(ck)
    if plan.action == "give_up":
        raise ElasticResumeError(plan.describe())
    resharder = None
    if plan.action == "reshard":
        from chainermn_tpu.checkpointing.reshard import \
            default_leaf_resharder

        resharder = default_leaf_resharder
    allow_inc = (plan.action == "shrink"
                 or (plan.action == "reshard"
                     and plan.saved_world is not None
                     and plan.saved_world > plan.new_world))
    state, it = ck.maybe_load(updater.state, iteration=plan.iteration,
                              allow_incomplete=allow_inc,
                              leaf_resharder=resharder)
    updater.state = state
    updater.iteration = it
    same_world = plan.saved_world in (None, plan.new_world)
    if plan.action == "resume" or (plan.action == "reshard"
                                   and same_world):
        # shape-preserving host side (a mesh reshape within the same
        # process count leaves the iterator untouched): exact restore
        host = ck.load_host_state(it)
        restore = getattr(updater, "load_host_state", None)
        if host is not None and callable(restore):
            restore(host)
        return plan
    _rebalance_host(ck, updater, plan, global_dataset, shuffle, seed)
    return plan


def _rebalance_host(ck, updater, plan: ElasticPlan, global_dataset,
                    shuffle, seed) -> None:
    """Shrink path: new data shard + approximate iterator position.

    The np RNG from the host state is restored when available (augment
    pipelines keep their stream); the iterator position is recomputed —
    the saved one indexes a shard that no longer exists."""
    host = ck.load_host_state(plan.iteration)
    if host is not None and host.get("np_random") is not None:
        import numpy as np

        np.random.set_state(host["np_random"])
    iterator = getattr(updater, "iterator", None)
    if iterator is None:
        return
    if global_dataset is not None:
        iterator.dataset = scatter_dataset(
            global_dataset, ck.comm, shuffle=shuffle, seed=seed)
    n = len(getattr(iterator, "dataset", ()) or ())
    bs = getattr(iterator, "batch_size", None)
    if not n or not bs:
        return
    consumed = plan.iteration * bs  # per-rank samples drawn so far
    if hasattr(iterator, "set_position"):
        iterator.set_position(consumed % n, consumed // n)
    elif hasattr(iterator, "epoch"):
        iterator.epoch = consumed // n

"""Hierarchical two-level reduction: intra-tier reduce-scatter →
inter-tier allreduce → intra-tier all-gather.

Reference: hierarchical_communicator.py (intra-node NCCL reduce →
inter-node MPI allreduce → intra-node NCCL bcast) and
two_dimensional_communicator.py (reduce-scatter / allreduce /
all-gather) — the composition HiCCL (arxiv 2408.05962) generalizes:
shrink the payload on the fast tier (ICI) before it crosses the slow
tier (DCN), so each inter link carries ``1/intra`` of the gradient.

Two topology sources:

* the communicator spans ≥ 2 mesh axes (the ``('dcn', 'ici')`` mesh the
  ``hierarchical``/``two_dimensional`` factory aliases build): last axis
  is the intra/ICI tier, the rest the inter tier;
* a single-axis communicator: the axis is factored into
  ``inter × intra`` contiguous blocks addressed with
  ``axis_index_groups`` (``intra`` defaults to ``comm.intra_size`` when
  that properly divides the axis — override with ``intra=``).

Numerics: the three-phase sum visits addends in a different order than
one flat psum, so float results can differ in the last ulp (observed
4.8e-7 on the 8-device CPU mesh); on integer-valued floats ("sum-
reducible" payloads) it is bitwise identical to ``flat`` — that is the
exact-parity contract tests/collectives_tests/test_reducers.py pins.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.collectives.base import (
    GradReducer,
    group_leaves_for_buckets,
    register_reducer,
)


class HierTopology:
    """Resolved two-tier topology over a communicator's mesh axes."""

    def __init__(self, comm, intra: Optional[int] = None):
        axes = comm.axis_names
        self.n = comm.size
        if len(axes) >= 2 and intra is None:
            # ('dcn', 'ici')-style mesh: last axis is the fast tier
            self.mode = "axes"
            self.intra_ax = axes[-1]
            self.inter_axes = tuple(axes[:-1])
            sizes = dict(zip(comm.mesh.axis_names, comm.mesh.devices.shape))
            self.intra = sizes[self.intra_ax]
            self.inter = self.n // self.intra
            return
        if len(axes) != 1:
            raise ValueError(
                "explicit intra= factoring needs a single-axis "
                f"communicator, got axes {axes}")
        self.mode = "groups"
        self.ax = axes[0]
        n = self.n
        if intra is None:
            intra = comm.intra_size
            if not (1 <= intra <= n and n % intra == 0):
                intra = n  # degenerate: one tier (still rs → ag)
        if not (1 <= intra <= n and n % intra == 0):
            raise ValueError(
                f"intra {intra} must divide communicator size {n}")
        self.intra = intra
        self.inter = n // intra
        # rank d = g * intra + j: intra group g walks j, inter group j
        # walks g (validated bitwise vs flat psum on the CPU mesh)
        self.intra_groups = [
            [g * intra + j for j in range(intra)] for g in range(self.inter)]
        self.inter_groups = [
            [j + g * intra for g in range(self.inter)] for j in range(intra)]

    # -- kernels (flat f32/bf16 vectors, inside shard_map) --------------

    def allreduce(self, v):
        """reduce-scatter(intra) → allreduce(inter) → all-gather(intra)
        of a flat vector; pads to a multiple of ``intra`` internally."""
        size = v.size
        pad = (-size) % self.intra
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
        if self.mode == "axes":
            s = lax.psum_scatter(v, self.intra_ax, tiled=True)
            if self.inter > 1:
                s = lax.psum(s, self.inter_axes)
            out = lax.all_gather(s, self.intra_ax, tiled=True)
        else:
            s = lax.psum_scatter(v, self.ax,
                                 axis_index_groups=self.intra_groups,
                                 tiled=True)
            if self.inter > 1:
                s = lax.psum(s, self.ax,
                             axis_index_groups=self.inter_groups)
            out = lax.all_gather(s, self.ax,
                                 axis_index_groups=self.intra_groups,
                                 tiled=True)
        return out[:size] if pad else out

    def reduce_scatter(self, g, ax: str):
        """Two-stage reduce-scatter of a flat vector whose length
        divides ``n``; rank ``r`` ends with tile ``r`` — the EXACT
        layout of one flat ``psum_scatter`` (ZeRO state depends on it).

        Stage order is inter-first: scattering the inter tier first is
        the only order whose composed tile layout matches the flat one
        without a data permutation (the intra-first order lands tile
        ``j*inter + g`` on rank ``g*intra + j``). The inter stage
        therefore still carries the full vector across the slow tier —
        the hierarchy here buys schedule granularity, not DCN bytes;
        the byte win belongs to :meth:`allreduce` (the DP path).
        """
        if self.mode != "groups" or self.inter == 1:
            return lax.psum_scatter(g, ax, tiled=True)
        s = lax.psum_scatter(g, ax, axis_index_groups=self.inter_groups,
                             tiled=True)
        return lax.psum_scatter(s, ax, axis_index_groups=self.intra_groups,
                                tiled=True)


class HierarchicalReducer(GradReducer):
    """Bucket-fused two-level allreduce (see module docstring).

    Args (beyond the base): ``intra`` — explicit fast-tier width for
    single-axis communicators (e.g. ``intra=4`` factors the 8-device CPU
    mesh into 2 inter-groups of 4); defaults to ``comm.intra_size``.
    """

    name = "hierarchical"

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 intra: Optional[int] = None,
                 bucket_order: str = "emission"):
        super().__init__(comm, op, bucket_bytes, bucket_order)
        self.topology = HierTopology(comm, intra=intra)

    def reduce(self, grads, state=()):
        comm = self.comm
        axes = comm.axis_names
        cdt = comm._grad_dtype
        n = comm.size
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        out = [None] * len(leaves)
        passthrough, groups = group_leaves_for_buckets(
            leaves, axes, self.bucket_bytes,
            comm_dtype_of=(lambda l: cdt) if cdt is not None else None,
            order=self.bucket_order)
        for i in passthrough:  # already global sums under vma tracking
            out[i] = leaves[i] / n if self.op == "mean" else leaves[i]
        for (va, comm_dtype), buckets in groups.items():
            full_tier = tuple(va) == tuple(axes)
            for bucket in buckets:
                flat = jnp.concatenate(
                    [leaves[i].astype(comm_dtype).ravel() for i in bucket])
                if full_tier:
                    red = self.topology.allreduce(flat)
                else:
                    # leaf varies on a strict subset of the comm axes —
                    # no two-tier structure to exploit; flat psum over
                    # the varying subset (correct, and rare)
                    red = lax.psum(flat, va)
                off = 0
                for i in bucket:
                    l = leaves[i]
                    piece = red[off:off + l.size].reshape(l.shape).astype(
                        l.dtype)
                    off += l.size
                    out[i] = piece / n if self.op == "mean" else piece
        return jax.tree_util.tree_unflatten(treedef, out), state

    def reduce_scatter_flat(self, g, ax: str, n: int):
        return self.topology.reduce_scatter(g, ax) / n


register_reducer("hierarchical", HierarchicalReducer)

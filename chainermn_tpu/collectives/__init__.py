"""Pluggable gradient-reduction strategies (the reference's communicator
zoo, rebuilt as in-graph reduction algorithms — docs/collectives.md).

Public surface::

    reducer = make_grad_reducer("hierarchical", comm, intra=4)
    opt = create_multi_node_optimizer(optax.adam(1e-3), comm,
                                      grad_reducer=reducer)   # or the name

Strategies: ``flat`` (the numerical reference), ``hierarchical``,
``quantized`` (error feedback), ``auto`` (cost model).
"""

from chainermn_tpu.collectives.auto import (  # noqa: F401
    AutoReducer,
    CostModel,
    measure_strategies,
)
from chainermn_tpu.collectives.base import (  # noqa: F401
    REDUCERS,
    GradReducer,
    make_grad_reducer,
    register_reducer,
)
from chainermn_tpu.collectives.flat import FlatReducer  # noqa: F401
from chainermn_tpu.collectives.hierarchical import (  # noqa: F401
    HierarchicalReducer,
    HierTopology,
)
from chainermn_tpu.collectives.quantized import (  # noqa: F401
    QuantizedReducer,
    quantize_allreduce,
)

__all__ = [
    "GradReducer",
    "make_grad_reducer",
    "register_reducer",
    "REDUCERS",
    "FlatReducer",
    "HierarchicalReducer",
    "HierTopology",
    "QuantizedReducer",
    "quantize_allreduce",
    "AutoReducer",
    "CostModel",
    "measure_strategies",
]

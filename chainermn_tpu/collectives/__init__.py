"""Pluggable gradient-reduction strategies (the reference's communicator
zoo, rebuilt as in-graph reduction algorithms — docs/collectives.md).

Public surface::

    reducer = make_grad_reducer("hierarchical", comm, intra=4)
    opt = create_multi_node_optimizer(optax.adam(1e-3), comm,
                                      grad_reducer=reducer)   # or the name

Strategies: ``flat`` (the numerical reference), ``hierarchical``,
``quantized`` (error feedback), ``auto`` (cost model), ``synth``
(a synthesized per-tier program from :mod:`chainermn_tpu.synthesis` —
needs ``program=``). The ``wire_format=`` knob (``'f32' | 'bf16' |
'int8' | 'int8-block' | 'int4-block'``) selects what the compressing
strategies put on the wire — see
docs/collectives.md#quantized-wire-formats.
"""

from chainermn_tpu.collectives.auto import (  # noqa: F401
    AutoReducer,
    CostModel,
    measure_strategies,
)
from chainermn_tpu.collectives.base import (  # noqa: F401
    REDUCERS,
    WIRE_FORMATS,
    GradReducer,
    make_grad_reducer,
    register_reducer,
)
from chainermn_tpu.collectives.flat import FlatReducer  # noqa: F401
from chainermn_tpu.collectives.hierarchical import (  # noqa: F401
    HierarchicalReducer,
    HierTopology,
)
from chainermn_tpu.collectives.quantized import (  # noqa: F401
    QUANT_BLOCK,
    QuantizedReducer,
    block_dequantize,
    block_quantize,
    pack_int4,
    quantize_allreduce,
    quantized_wire_bytes,
    unpack_int4,
    wire_ratio,
)
# last: registers the 'synth' strategy (imports collectives.base, so it
# must come after the base import above)
from chainermn_tpu.synthesis.compiler import (  # noqa: F401  # isort: skip
    SynthesizedReducer,
)

__all__ = [
    "GradReducer",
    "make_grad_reducer",
    "register_reducer",
    "REDUCERS",
    "WIRE_FORMATS",
    "FlatReducer",
    "HierarchicalReducer",
    "HierTopology",
    "QuantizedReducer",
    "quantize_allreduce",
    "QUANT_BLOCK",
    "block_quantize",
    "block_dequantize",
    "pack_int4",
    "unpack_int4",
    "wire_ratio",
    "quantized_wire_bytes",
    "AutoReducer",
    "CostModel",
    "measure_strategies",
    "SynthesizedReducer",
]

"""GradReducer — the strategy registry for gradient reduction.

Reference: ChainerMN's communicator zoo (SURVEY.md §2.1) was a set of
*algorithms* for turning per-rank gradients into reduced gradients —
pure_nccl (one flat ring, optional fp16 comm), hierarchical (intra-node
reduce → inter-node allreduce → intra-node bcast), two_dimensional
(reduce-scatter / allreduce / all-gather). The TPU rebuild collapsed the
*communicator* taxonomy into one mesh (comm/xla.py), but the *reduction
algorithm* axis is real and hardware-visible: over DCN the message
schedule, compression, and hierarchy of the gradient reduction are the
tuning surface (HiCCL, arxiv 2408.05962; EQuARX, arxiv 2506.17615).

A :class:`GradReducer` owns how a gradient pytree becomes a reduced
gradient pytree *inside the compiled step*.  Strategies:

==============  =====================================================
``flat``        today's psum (``allreduce_grad``) — default, the
                numerical reference
``hierarchical``  bucket-fused reduce-scatter over the intra/ICI tier
                → cross-inter allreduce → all-gather
``quantized``   bf16/int8 per-bucket scaled allreduce with
                error-feedback residuals carried as reducer state
``auto``        bytes/hop-latency cost model picks one of the above
                per bucket
==============  =====================================================

See docs/collectives.md for the catalogue and the cost model.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.comm.xla import DEFAULT_DCN_BUCKET_BYTES, plan_buckets

#: every wire format the registry understands ("f32" is the
#: uncompressed reference wire); per-format bytes/element live in
#: collectives/quantized.py's WIRE_ITEMSIZE (same keys)
WIRE_FORMATS = ("f32", "bf16", "int8", "int8-block", "int4-block")


def varying_axes(leaf, axes: Sequence[str]) -> Tuple[str, ...]:
    """The subset of ``axes`` the leaf still varies on.

    Same probe as ``XlaCommunicator.allreduce_grad``: when shard_map's
    varying-axis tracking is off (``check_rep=False`` on pre-vma jax),
    every axis is reported — the conservative reduce-everything answer.
    Must be called under a shard_map trace with ``axes`` bound.
    """
    if not jax.typeof(lax.axis_index(axes[0])).vma:
        return tuple(axes)
    vma = jax.typeof(leaf).vma
    return tuple(a for a in axes if a in vma)


class GradReducer:
    """Base strategy: how a gradient pytree becomes a reduced one.

    Subclasses implement :meth:`reduce` (and, when ``stateful``,
    :meth:`init` / :meth:`init_global`).  ``op`` is ``'mean'`` (the
    reference ``allreduce_grad`` contract) or ``'sum'``.

    The contract mirrors an optax transformation, with the state
    threaded explicitly so error-feedback residuals survive the step::

        reduced, new_state = reducer.reduce(grads, state)

    ``reduce`` must run inside the compiled (shard_map) step; the
    collectives lower into the same program as the backward, and XLA's
    latency-hiding scheduler overlaps them with adjacent compute.
    """

    name = "base"
    #: True when :meth:`reduce` threads state (error-feedback residuals).
    stateful = False
    #: wire formats this strategy can put on the wire; non-compressing
    #: strategies carry the uncompressed payload dtype only
    wire_formats = ("f32",)

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 bucket_order: str = "emission"):
        if op not in ("mean", "sum"):
            raise ValueError(f"unsupported grad-reduction op: {op!r}")
        if bucket_order not in ("emission", "size"):
            raise ValueError(
                f"bucket_order must be 'emission' or 'size', got "
                f"{bucket_order!r}")
        self.comm = comm
        self.op = op
        self.bucket_bytes = (bucket_bytes if bucket_bytes is not None
                             else (comm._bucket_bytes
                                   or DEFAULT_DCN_BUCKET_BYTES))
        #: 'emission' packs buckets in pytree order (the reference
        #: behavior); 'size' packs largest-first — the first bucket
        #: fills (and its collective issues) earlier in the backward,
        #: which is one of the schedtune knobs (docs/tuning.md). Pure
        #: packing: membership changes, every leaf is still reduced
        #: exactly once, so numerics are unchanged.
        self.bucket_order = bucket_order

    # -- state ----------------------------------------------------------
    def init(self, params):
        """Per-rank reducer state for a grads-shaped pytree (the view a
        single shard carries inside the compiled step). Stateless
        strategies return ``()``."""
        return ()

    def init_global(self, params):
        """Driver-level (global-view) reducer state: per-rank states
        stacked on a leading ``comm.size`` axis, ready to be sharded
        ``P(axis)`` into the step. Stateless strategies return ``()``."""
        return ()

    # -- the hot path ---------------------------------------------------
    def reduce(self, grads, state=()):
        raise NotImplementedError

    def reduce_scatter_flat(self, g, ax: str, n: int):
        """ZeRO-1/2 hook: mean-reduce-scatter one flat gradient vector
        (length divisible by ``n``) so rank ``r`` holds tile ``r``.
        The base implementation is today's flat path — subclasses that
        decompose or compress override it, but must preserve the exact
        tile-``r``-to-rank-``r`` layout (the ZeRO state layout depends
        on it)."""
        return lax.psum_scatter(g, ax, tiled=True) / n

    # -- introspection --------------------------------------------------
    def plan(self, tree) -> List[Dict[str, Any]]:
        """Host-side bucket plan for a grads-shaped pytree (concrete or
        abstract leaves): one dict per bucket with ``keys``, ``bytes``
        (payload), ``wire_bytes`` (what actually crosses the wire),
        ``algorithm``. Pure bookkeeping — safe off-device."""
        leaves_kp, _ = jax.tree_util.tree_flatten_with_path(tree)
        sized = []
        for kp, leaf in leaves_kp:
            key = jax.tree_util.keystr(kp)
            dt = jnp.dtype(getattr(leaf, "dtype", jnp.float32))
            nb = int(jnp.size(leaf)) * dt.itemsize
            sized.append((key, nb))
        if self.bucket_order == "size":
            sized = sorted(sized, key=lambda kv: -kv[1])  # stable
        out = []
        for i, bucket in enumerate(plan_buckets(sized, self.bucket_bytes)):
            sizes = dict(sized)
            nb = sum(sizes[k] for k in bucket)
            out.append({
                "bucket": i,
                "keys": list(bucket),
                "bytes": nb,
                "wire_bytes": self.wire_bytes(nb),
                "algorithm": self.name,
            })
        return out

    def wire_bytes(self, payload_bytes: int) -> int:
        """Bytes this strategy actually moves for a payload (per rank,
        one reduction). Compressing strategies override."""
        return payload_bytes

    def describe_rows(self, rows) -> List[str]:
        """One human line per :meth:`plan` row (ReductionReport/bench)."""
        out = []
        for b in rows:
            line = (
                f"bucket {b['bucket']:>3}  {b['algorithm']:>12}  "
                f"{b['bytes']:>12,} B payload  "
                f"{b['wire_bytes']:>12,} B wire  {len(b['keys'])} leaves")
            if "est_us" in b:
                line += f"  ~{b['est_us']} us"
            out.append(line)
        return out

    def describe(self, tree) -> str:
        """One human line per bucket (used by ReductionReport/bench)."""
        return "\n".join(self.describe_rows(self.plan(tree)))


#: name -> GradReducer subclass (strategies self-register on import)
REDUCERS: Dict[str, Type[GradReducer]] = {}


def register_reducer(name: str, cls: Type[GradReducer]) -> None:
    REDUCERS[name] = cls


def make_grad_reducer(spec, comm, op: str = "mean", **kwargs) -> Optional[GradReducer]:
    """Resolve a ``grad_reducer=`` argument.

    ``spec`` may be ``None`` (no reducer — callers keep their legacy
    path), an already-constructed :class:`GradReducer` (returned as-is),
    or a registered strategy name (``'flat' | 'hierarchical' |
    'quantized' | 'auto'``) with ``kwargs`` forwarded to the
    constructor.

    ``wire_format`` (in ``kwargs``) is the first-class compression knob
    (:data:`WIRE_FORMATS`): ``'f32'``/``None`` keep the uncompressed
    wire on any strategy; the narrow formats are forwarded to
    strategies that can carry them (``quantized``, ``auto``) and
    REFUSED on strategies whose wire is structurally f32 — a silently
    dropped compression request would misreport every downstream byte
    count.
    """
    if spec is None:
        return None
    if isinstance(spec, GradReducer):
        return spec
    try:
        cls = REDUCERS[spec]
    except KeyError:
        raise ValueError(
            f"unknown grad_reducer {spec!r}; registered strategies: "
            f"{sorted(REDUCERS)}") from None
    wf = kwargs.pop("wire_format", None)
    if wf is not None:
        if wf not in WIRE_FORMATS:
            raise ValueError(
                f"unknown wire_format {wf!r}; expected one of "
                f"{WIRE_FORMATS}")
        if cls.wire_formats != ("f32",):
            kwargs["wire_format"] = wf  # strategy prices/encodes it
        elif wf != "f32":
            raise ValueError(
                f"strategy {spec!r} carries an uncompressed f32 wire; "
                f"wire_format={wf!r} needs 'quantized' (fixed format) "
                "or 'auto' (cost model may pick it)")
    return cls(comm, op=op, **kwargs)


def group_leaves_for_buckets(leaves, axes, bucket_bytes,
                             comm_dtype_of=None, order: str = "emission"):
    """Shared bucket grouping: leaves are grouped by (varying axes,
    communication dtype) — only same-typed leaves share a flat buffer —
    then packed greedily (:func:`plan_buckets`, same rule as
    ``XlaCommunicator._bucketed_allreduce_grad``) in pytree order
    (``order='emission'``, the reference behavior) or largest-leaf
    first (``order='size'``, the schedtune knob — the first bucket is
    ready earlier in the backward; see docs/tuning.md).

    Returns ``(passthrough, groups)`` where ``passthrough`` is the list
    of leaf indices with no varying axis (already global sums under vma
    tracking — they skip communication) and ``groups`` maps
    ``(varying_axes, dtype)`` to a list of buckets (lists of leaf
    indices).
    """
    from collections import defaultdict

    passthrough, by_type = [], defaultdict(list)
    for i, l in enumerate(leaves):
        va = varying_axes(l, axes)
        if not va:
            passthrough.append(i)
            continue
        cdt = jnp.dtype(comm_dtype_of(l) if comm_dtype_of else l.dtype)
        by_type[(va, cdt)].append(i)
    groups = {}
    for key, idxs in by_type.items():
        cdt = key[1]
        if order == "size":
            idxs = sorted(idxs, key=lambda i: -leaves[i].size)  # stable
        groups[key] = plan_buckets(
            [(i, leaves[i].size * cdt.itemsize) for i in idxs],
            bucket_bytes)
    return passthrough, groups

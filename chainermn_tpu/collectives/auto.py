"""The ``auto`` strategy: a bytes/hop-latency cost model picks flat vs
hierarchical vs quantized per bucket.

The model is the standard alpha-beta form per tier (latency ``alpha`` +
bytes/bandwidth ``beta``), with ring-allreduce byte counts
(``2·b·(k-1)/k`` per rank over a k-ring). Defaults are v5e-flavored
order-of-magnitude numbers (ICI ~100 GB/s per link / ~1 µs, DCN
~25 GB/s per host / ~100 µs — docs/scaling_model.md); the point is the
*crossover structure*, not the absolute numbers:

* tiny buckets are launch-latency bound → ``flat`` (one collective);
* large buckets on a multi-tier mesh → ``hierarchical`` (the inter tier
  carries ``1/intra`` of the bytes);
* with ``lossy=True``, very large buckets → ``quantized`` bf16 (half
  the wire bytes; OFF by default — a strategy named "auto" must not
  silently change numerics).

Override with measurement (:func:`measure_strategies`): on TPU it times
real compiled reductions per size and the picker interpolates the
table; off TPU it returns ``{}`` untimed — on a CPU host-platform mesh
every "collective" is a memcpy and the numbers would be fiction (the
``ops/autotune.py`` honest-null convention; BASELINE.md records the
null). Pass ``db=`` to persist a non-empty sweep into the per-topology
profile DB (:mod:`chainermn_tpu.tuning.profile_db`) so one on-TPU run
permanently improves off-TPU tuning for that machine shape;
``AutoReducer(profile=...)`` loads it back.

The intra/inter split itself is no longer hard-coded here: cost
estimation goes through the explicit multi-tier
:class:`chainermn_tpu.tuning.topology.Topology` (for two tiers the
numbers are identical to the original :class:`CostModel` formulas,
which remain as the parameter bag and the documented reference).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.collectives.base import (
    GradReducer,
    group_leaves_for_buckets,
    register_reducer,
)
from chainermn_tpu.collectives.hierarchical import HierTopology
from chainermn_tpu.collectives.quantized import (
    quantize_allreduce,
    quantized_wire_bytes,
    wire_ratio,
)


@dataclasses.dataclass
class CostModel:
    """Per-tier alpha-beta parameters, microseconds and GB/s.

    Kept as the two-tier parameter bag (and reference formulas);
    :meth:`as_topology` lifts it into the general multi-tier
    :class:`~chainermn_tpu.tuning.topology.Topology` the estimators
    now run on."""

    ici_latency_us: float = 1.0
    ici_bw_gbps: float = 100.0
    dcn_latency_us: float = 100.0
    dcn_bw_gbps: float = 25.0
    quant_overhead_us: float = 2.0  # quantize/dequantize kernels

    @staticmethod
    def _xfer_us(nbytes: float, bw_gbps: float) -> float:
        return nbytes / (bw_gbps * 1e3)  # 1 GB/s == 1e3 bytes/us

    def estimate_us(self, strategy: str, nbytes: int,
                    topo: HierTopology,
                    wire_format: str = "bf16") -> float:
        """Modeled time for ONE reduction of ``nbytes`` payload."""
        n, intra, inter = topo.n, topo.intra, topo.inter
        ring = lambda b, k: 2.0 * b * (k - 1) / max(k, 1)
        slow_lat = self.dcn_latency_us if inter > 1 else self.ici_latency_us
        slow_bw = self.dcn_bw_gbps if inter > 1 else self.ici_bw_gbps
        if strategy == "flat":
            # one allreduce whose ring crosses the slowest tier
            return slow_lat + self._xfer_us(ring(nbytes, n), slow_bw)
        if strategy == "hierarchical":
            t = 2 * self.ici_latency_us + self._xfer_us(
                ring(nbytes, intra), self.ici_bw_gbps)  # rs + ag, ICI
            if inter > 1:
                t += self.dcn_latency_us + self._xfer_us(
                    ring(nbytes / intra, inter), self.dcn_bw_gbps)
            return t
        if strategy == "quantized":
            # beta scales with the ACTUAL wire width (values + block
            # scales) — pricing every format at bf16 meant 'auto' could
            # never rationally pick the int8/int4 wires
            wire = nbytes * wire_ratio(wire_format)
            return (slow_lat + self.quant_overhead_us
                    + self._xfer_us(ring(wire, n), slow_bw))
        raise ValueError(f"unknown strategy {strategy!r}")

    def as_topology(self, comm, intra: Optional[int] = None):
        """This parameter set as an explicit multi-tier
        :class:`~chainermn_tpu.tuning.topology.Topology` over the
        communicator's mesh (bitwise-same estimates for two tiers)."""
        from chainermn_tpu.tuning.topology import Topology

        return Topology.from_comm(
            comm, intra=intra,
            ici_latency_us=self.ici_latency_us,
            ici_bw_gbps=self.ici_bw_gbps,
            dcn_latency_us=self.dcn_latency_us,
            dcn_bw_gbps=self.dcn_bw_gbps,
            quant_overhead_us=self.quant_overhead_us)


_CACHE: Dict[tuple, Dict[Tuple[str, int], float]] = {}


def _persist_measured(db, comm, intra, table) -> None:
    """Write a non-empty measured sweep into the profile DB under this
    mesh's topology fingerprint. ``db`` is a ProfileDB, a path, or
    ``True`` for the default DB location."""
    from chainermn_tpu.tuning.profile_db import ProfileDB
    from chainermn_tpu.tuning.topology import Topology

    pdb = db if isinstance(db, ProfileDB) else ProfileDB(
        db if isinstance(db, str) else None)
    pdb.put_measured(Topology.from_comm(comm, intra=intra), table)
    pdb.save()


def measure_strategies(
    comm,
    sizes: Sequence[int] = (1 << 16, 1 << 20, 1 << 22, 1 << 24),
    strategies: Sequence[str] = ("flat", "hierarchical", "quantized"),
    steps: int = 10,
    intra: Optional[int] = None,
    db=None,
) -> Dict[Tuple[str, int], float]:
    """Measured sweep: {(strategy, payload_bytes): microseconds}.

    Times real compiled reductions on the communicator's mesh. Memoized
    per (mesh shape, sizes, strategies). Off TPU this returns ``{}``
    UNTIMED — host-platform "collectives" are memcpys and any number
    would mislead the picker (honest-null convention, BASELINE.md).
    Feed the result to ``AutoReducer(measured=...)``.

    ``db`` (a :class:`~chainermn_tpu.tuning.profile_db.ProfileDB`, a
    path, or ``True`` for the default location) persists a NON-EMPTY
    sweep under this mesh's topology fingerprint — the results used to
    be computed and thrown away; now one on-TPU run feeds every later
    off-TPU ``AutoReducer(profile=...)`` / ``tools/schedtune.py`` run
    on that machine shape. The off-TPU ``{}`` null is never written.
    """
    key = (tuple(comm.mesh.devices.shape), tuple(comm.axis_names),
           tuple(sizes), tuple(strategies), intra)
    if key in _CACHE:
        if db is not None and _CACHE[key]:
            _persist_measured(db, comm, intra, _CACHE[key])
        return _CACHE[key]
    if jax.devices()[0].platform != "tpu":
        _CACHE[key] = {}
        return {}
    import time

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    topo = HierTopology(comm, intra=intra)
    axes = comm.axis_names
    ax = axes if len(axes) > 1 else axes[0]
    out: Dict[Tuple[str, int], float] = {}
    for nbytes in sizes:
        nelem = max(1, nbytes // 4)
        x = jnp.ones((comm.size, nelem), jnp.float32)
        kernels = {
            "flat": lambda v: lax.psum(v, axes),
            "hierarchical": lambda v: topo.allreduce(v),
            "quantized": lambda v: quantize_allreduce(v, axes, "bf16")[0],
        }
        for s in strategies:
            f = jax.jit(shard_map(
                lambda v: kernels[s](v[0])[None], mesh=comm.mesh,
                in_specs=P(ax), out_specs=P(ax)))
            f(x).block_until_ready()  # compile
            t0 = time.perf_counter()
            for _ in range(steps):
                r = f(x)
            r.block_until_ready()
            out[(s, nbytes)] = (time.perf_counter() - t0) / steps * 1e6
    _CACHE[key] = out
    if db is not None and out:
        _persist_measured(db, comm, intra, out)
    return out


class AutoReducer(GradReducer):
    """Cost-model-driven per-bucket strategy choice (see module doc).

    Args (beyond the base): ``cost`` — a :class:`CostModel`;
    ``measured`` — a sweep table from :func:`measure_strategies`
    overriding the model where it has data; ``lossy`` — allow the
    quantized (bf16, no error feedback — this strategy is stateless)
    candidate; ``intra`` — fast-tier width, as in
    :class:`~chainermn_tpu.collectives.hierarchical.HierarchicalReducer`;
    ``topology`` — an explicit
    :class:`~chainermn_tpu.tuning.topology.Topology` for cost
    estimation (default: lifted from ``comm``/``cost``/``intra``);
    ``profile`` — a :class:`~chainermn_tpu.tuning.profile_db.ProfileDB`
    (or path, or ``True`` for the default location) whose persisted
    ``measure_strategies`` sweep for this topology fingerprint seeds
    ``measured`` (an explicit ``measured=`` entry wins per key);
    ``wire_format`` — the wire the quantized candidate uses AND is
    priced at (default ``'bf16'``, the historical behavior; the block
    formats make the quantized candidate ~4–8x cheaper on beta, so the
    cost model can actually choose it). ``wire_format='f32'`` disables
    the lossy candidate outright (an uncompressed "quantized" wire is
    the flat strategy). Implies nothing unless ``lossy=True`` — a
    strategy named "auto" must not silently change numerics.
    """

    name = "auto"
    wire_formats = ("f32", "bf16", "int8", "int8-block", "int4-block")

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 intra: Optional[int] = None,
                 cost: Optional[CostModel] = None,
                 measured: Optional[Dict[Tuple[str, int], float]] = None,
                 lossy: bool = False,
                 bucket_order: str = "emission",
                 topology=None,
                 profile=None,
                 wire_format: Optional[str] = None):
        super().__init__(comm, op, bucket_bytes, bucket_order)
        if wire_format is not None and wire_format not in self.wire_formats:
            raise ValueError(
                f"unknown wire_format {wire_format!r}; expected one of "
                f"{self.wire_formats}")
        if wire_format == "f32":
            lossy = False
        self.wire_format = (wire_format if wire_format not in (None, "f32")
                            else "bf16")
        self.topology = HierTopology(comm, intra=intra)
        self.cost = cost or CostModel()
        #: multi-tier cost-side description (the collective kernels
        #: still run on the two-tier HierTopology above)
        self.topo_desc = (topology if topology is not None
                          else self.cost.as_topology(comm, intra=intra))
        self.measured = dict(measured or {})
        if profile is not None:
            from chainermn_tpu.tuning.profile_db import ProfileDB

            pdb = profile if isinstance(profile, ProfileDB) else ProfileDB(
                profile if isinstance(profile, str) else None)
            persisted = pdb.measured_for(self.topo_desc)
            self.measured = {**persisted, **self.measured}
        self.lossy = lossy

    def _estimate(self, strategy: str, nbytes: int) -> float:
        if self.measured:
            pts = [(abs(sz - nbytes), us) for (s, sz), us
                   in self.measured.items() if s == strategy]
            if pts:  # nearest measured size wins over the model
                return min(pts)[1]
        return self.topo_desc.estimate_us(strategy, nbytes,
                                          wire_format=self.wire_format)

    def choose(self, nbytes: int) -> str:
        cands = ["flat", "hierarchical"] + (
            ["quantized"] if self.lossy else [])
        # stable tie-break: flat first (fewest launches, exact)
        return min(cands, key=lambda s: (self._estimate(s, nbytes),
                                         cands.index(s)))

    def reduce(self, grads, state=()):
        comm = self.comm
        axes = comm.axis_names
        n = comm.size
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        out = [None] * len(leaves)
        passthrough, groups = group_leaves_for_buckets(
            leaves, axes, self.bucket_bytes, order=self.bucket_order)
        for i in passthrough:
            out[i] = leaves[i] / n if self.op == "mean" else leaves[i]
        for (va, cdt), buckets in groups.items():
            full_tier = tuple(va) == tuple(axes)
            lossy_ok = self.lossy and jnp.issubdtype(cdt, jnp.floating)
            for bucket in buckets:
                flat = jnp.concatenate(
                    [leaves[i].astype(cdt).ravel() for i in bucket])
                nbytes = flat.size * cdt.itemsize
                algo = self.choose(nbytes)
                if algo == "hierarchical" and full_tier:
                    red = self.topology.allreduce(flat)
                elif algo == "quantized" and lossy_ok:
                    red = quantize_allreduce(flat, va, self.wire_format)[0]
                else:
                    red = lax.psum(flat, va)
                off = 0
                for i in bucket:
                    l = leaves[i]
                    piece = red[off:off + l.size].reshape(l.shape).astype(
                        l.dtype)
                    off += l.size
                    out[i] = piece / n if self.op == "mean" else piece
        return jax.tree_util.tree_unflatten(treedef, out), state

    def reduce_scatter_flat(self, g, ax: str, n: int):
        nbytes = g.size * g.dtype.itemsize
        if self.choose(nbytes) == "hierarchical":
            return self.topology.reduce_scatter(g, ax) / n
        return lax.psum_scatter(g, ax, tiled=True) / n

    def plan(self, tree):
        rows = super().plan(tree)
        for b in rows:
            algo = self.choose(b["bytes"])
            b["algorithm"] = f"auto:{algo}"
            b["wire_bytes"] = (
                quantized_wire_bytes(b["bytes"], self.wire_format)
                if algo == "quantized" else b["bytes"])
            b["est_us"] = round(self._estimate(algo, b["bytes"]), 2)
        return rows


register_reducer("auto", AutoReducer)

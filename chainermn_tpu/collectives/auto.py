"""The ``auto`` strategy: a bytes/hop-latency cost model picks flat vs
hierarchical vs quantized per bucket.

The model is the standard alpha-beta form per tier (latency ``alpha`` +
bytes/bandwidth ``beta``), with ring-allreduce byte counts
(``2·b·(k-1)/k`` per rank over a k-ring). Defaults are v5e-flavored
order-of-magnitude numbers (ICI ~100 GB/s per link / ~1 µs, DCN
~25 GB/s per host / ~100 µs — docs/scaling_model.md); the point is the
*crossover structure*, not the absolute numbers:

* tiny buckets are launch-latency bound → ``flat`` (one collective);
* large buckets on a multi-tier mesh → ``hierarchical`` (the inter tier
  carries ``1/intra`` of the bytes);
* with ``lossy=True``, very large buckets → ``quantized`` bf16 (half
  the wire bytes; OFF by default — a strategy named "auto" must not
  silently change numerics).

Override with measurement (:func:`measure_strategies`): on TPU it times
real compiled reductions per size and the picker interpolates the
table; off TPU it returns ``{}`` untimed — on a CPU host-platform mesh
every "collective" is a memcpy and the numbers would be fiction (the
``ops/autotune.py`` honest-null convention; BASELINE.md records the
null).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.collectives.base import (
    GradReducer,
    group_leaves_for_buckets,
    register_reducer,
)
from chainermn_tpu.collectives.hierarchical import HierTopology
from chainermn_tpu.collectives.quantized import (
    WIRE_ITEMSIZE,
    quantize_allreduce,
)


@dataclasses.dataclass
class CostModel:
    """Per-tier alpha-beta parameters, microseconds and GB/s."""

    ici_latency_us: float = 1.0
    ici_bw_gbps: float = 100.0
    dcn_latency_us: float = 100.0
    dcn_bw_gbps: float = 25.0
    quant_overhead_us: float = 2.0  # quantize/dequantize kernels

    @staticmethod
    def _xfer_us(nbytes: float, bw_gbps: float) -> float:
        return nbytes / (bw_gbps * 1e3)  # 1 GB/s == 1e3 bytes/us

    def estimate_us(self, strategy: str, nbytes: int,
                    topo: HierTopology) -> float:
        """Modeled time for ONE reduction of ``nbytes`` payload."""
        n, intra, inter = topo.n, topo.intra, topo.inter
        ring = lambda b, k: 2.0 * b * (k - 1) / max(k, 1)
        slow_lat = self.dcn_latency_us if inter > 1 else self.ici_latency_us
        slow_bw = self.dcn_bw_gbps if inter > 1 else self.ici_bw_gbps
        if strategy == "flat":
            # one allreduce whose ring crosses the slowest tier
            return slow_lat + self._xfer_us(ring(nbytes, n), slow_bw)
        if strategy == "hierarchical":
            t = 2 * self.ici_latency_us + self._xfer_us(
                ring(nbytes, intra), self.ici_bw_gbps)  # rs + ag, ICI
            if inter > 1:
                t += self.dcn_latency_us + self._xfer_us(
                    ring(nbytes / intra, inter), self.dcn_bw_gbps)
            return t
        if strategy == "quantized":
            wire = nbytes * WIRE_ITEMSIZE["bf16"] / 4.0
            return (slow_lat + self.quant_overhead_us
                    + self._xfer_us(ring(wire, n), slow_bw))
        raise ValueError(f"unknown strategy {strategy!r}")


_CACHE: Dict[tuple, Dict[Tuple[str, int], float]] = {}


def measure_strategies(
    comm,
    sizes: Sequence[int] = (1 << 16, 1 << 20, 1 << 22, 1 << 24),
    strategies: Sequence[str] = ("flat", "hierarchical", "quantized"),
    steps: int = 10,
    intra: Optional[int] = None,
) -> Dict[Tuple[str, int], float]:
    """Measured sweep: {(strategy, payload_bytes): microseconds}.

    Times real compiled reductions on the communicator's mesh. Memoized
    per (mesh shape, sizes, strategies). Off TPU this returns ``{}``
    UNTIMED — host-platform "collectives" are memcpys and any number
    would mislead the picker (honest-null convention, BASELINE.md).
    Feed the result to ``AutoReducer(measured=...)``.
    """
    key = (tuple(comm.mesh.devices.shape), tuple(comm.axis_names),
           tuple(sizes), tuple(strategies), intra)
    if key in _CACHE:
        return _CACHE[key]
    if jax.devices()[0].platform != "tpu":
        _CACHE[key] = {}
        return {}
    import time

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    topo = HierTopology(comm, intra=intra)
    axes = comm.axis_names
    ax = axes if len(axes) > 1 else axes[0]
    out: Dict[Tuple[str, int], float] = {}
    for nbytes in sizes:
        nelem = max(1, nbytes // 4)
        x = jnp.ones((comm.size, nelem), jnp.float32)
        kernels = {
            "flat": lambda v: lax.psum(v, axes),
            "hierarchical": lambda v: topo.allreduce(v),
            "quantized": lambda v: quantize_allreduce(v, axes, "bf16")[0],
        }
        for s in strategies:
            f = jax.jit(shard_map(
                lambda v: kernels[s](v[0])[None], mesh=comm.mesh,
                in_specs=P(ax), out_specs=P(ax)))
            f(x).block_until_ready()  # compile
            t0 = time.perf_counter()
            for _ in range(steps):
                r = f(x)
            r.block_until_ready()
            out[(s, nbytes)] = (time.perf_counter() - t0) / steps * 1e6
    _CACHE[key] = out
    return out


class AutoReducer(GradReducer):
    """Cost-model-driven per-bucket strategy choice (see module doc).

    Args (beyond the base): ``cost`` — a :class:`CostModel`;
    ``measured`` — a sweep table from :func:`measure_strategies`
    overriding the model where it has data; ``lossy`` — allow the
    quantized (bf16, no error feedback — this strategy is stateless)
    candidate; ``intra`` — fast-tier width, as in
    :class:`~chainermn_tpu.collectives.hierarchical.HierarchicalReducer`.
    """

    name = "auto"

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 intra: Optional[int] = None,
                 cost: Optional[CostModel] = None,
                 measured: Optional[Dict[Tuple[str, int], float]] = None,
                 lossy: bool = False):
        super().__init__(comm, op, bucket_bytes)
        self.topology = HierTopology(comm, intra=intra)
        self.cost = cost or CostModel()
        self.measured = dict(measured or {})
        self.lossy = lossy

    def _estimate(self, strategy: str, nbytes: int) -> float:
        if self.measured:
            pts = [(abs(sz - nbytes), us) for (s, sz), us
                   in self.measured.items() if s == strategy]
            if pts:  # nearest measured size wins over the model
                return min(pts)[1]
        return self.cost.estimate_us(strategy, nbytes, self.topology)

    def choose(self, nbytes: int) -> str:
        cands = ["flat", "hierarchical"] + (
            ["quantized"] if self.lossy else [])
        # stable tie-break: flat first (fewest launches, exact)
        return min(cands, key=lambda s: (self._estimate(s, nbytes),
                                         cands.index(s)))

    def reduce(self, grads, state=()):
        comm = self.comm
        axes = comm.axis_names
        n = comm.size
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        out = [None] * len(leaves)
        passthrough, groups = group_leaves_for_buckets(
            leaves, axes, self.bucket_bytes)
        for i in passthrough:
            out[i] = leaves[i] / n if self.op == "mean" else leaves[i]
        for (va, cdt), buckets in groups.items():
            full_tier = tuple(va) == tuple(axes)
            lossy_ok = self.lossy and jnp.issubdtype(cdt, jnp.floating)
            for bucket in buckets:
                flat = jnp.concatenate(
                    [leaves[i].astype(cdt).ravel() for i in bucket])
                nbytes = flat.size * cdt.itemsize
                algo = self.choose(nbytes)
                if algo == "hierarchical" and full_tier:
                    red = self.topology.allreduce(flat)
                elif algo == "quantized" and lossy_ok:
                    red = quantize_allreduce(flat, va, "bf16")[0]
                else:
                    red = lax.psum(flat, va)
                off = 0
                for i in bucket:
                    l = leaves[i]
                    piece = red[off:off + l.size].reshape(l.shape).astype(
                        l.dtype)
                    off += l.size
                    out[i] = piece / n if self.op == "mean" else piece
        return jax.tree_util.tree_unflatten(treedef, out), state

    def reduce_scatter_flat(self, g, ax: str, n: int):
        nbytes = g.size * g.dtype.itemsize
        if self.choose(nbytes) == "hierarchical":
            return self.topology.reduce_scatter(g, ax) / n
        return lax.psum_scatter(g, ax, tiled=True) / n

    def plan(self, tree):
        rows = super().plan(tree)
        for b in rows:
            algo = self.choose(b["bytes"])
            b["algorithm"] = f"auto:{algo}"
            b["wire_bytes"] = (
                int(b["bytes"] * WIRE_ITEMSIZE["bf16"] / 4)
                if algo == "quantized" else b["bytes"])
            b["est_us"] = round(self._estimate(algo, b["bytes"]), 2)
        return rows


register_reducer("auto", AutoReducer)

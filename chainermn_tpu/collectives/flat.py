"""The flat strategy — today's psum, the numerical reference.

Reference: pure_nccl_communicator.py — pack, ONE ring allreduce, unpack.
By default it simply delegates to ``XlaCommunicator.allreduce_grad``, so
``grad_reducer='flat'`` is **bit-identical** to not passing a reducer at
all (same primitives in the same order; the acceptance bar for every
other strategy is measured against this one).

A TUNED flat reducer — constructed with explicit ``bucket_bytes`` or a
non-default ``bucket_order`` (the schedtune knobs, docs/tuning.md) —
switches to its own bucketed psum path: ``allreduce_grad``'s bucketing
follows the *communicator's* ``dcn_bucket_bytes``, which the tuner must
be able to override per plan. The bucketed path changes only the
packing; every element is still reduced by the same psum over the same
ranks, so per-element addend order — and therefore numerics — is
unchanged (bitwise-equal to the delegating path on integer-valued
floats; last-ulp identical elsewhere, same contract as the
communicator's own bucketing).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.collectives.base import (
    GradReducer,
    group_leaves_for_buckets,
    register_reducer,
)


class FlatReducer(GradReducer):
    """One flat (bucketed, if the communicator buckets) psum per leaf
    group — exactly ``comm.allreduce_grad`` — unless tuned knobs pin an
    explicit bucket plan (see module docstring)."""

    name = "flat"

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 bucket_order: str = "emission"):
        super().__init__(comm, op, bucket_bytes, bucket_order)
        self._explicit = (bucket_bytes is not None
                          or bucket_order != "emission")

    def reduce(self, grads, state=()):
        if not self._explicit:
            return self.comm.allreduce_grad(grads, self.op), state
        comm = self.comm
        axes = comm.axis_names
        cdt = comm._grad_dtype
        n = comm.size
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        out = [None] * len(leaves)
        passthrough, groups = group_leaves_for_buckets(
            leaves, axes, self.bucket_bytes,
            comm_dtype_of=(lambda l: cdt) if cdt is not None else None,
            order=self.bucket_order)
        for i in passthrough:  # already global sums under vma tracking
            out[i] = leaves[i] / n if self.op == "mean" else leaves[i]
        for (va, comm_dtype), buckets in groups.items():
            for bucket in buckets:
                flat = jnp.concatenate(
                    [leaves[i].astype(comm_dtype).ravel() for i in bucket])
                red = lax.psum(flat, va)
                off = 0
                for i in bucket:
                    l = leaves[i]
                    piece = red[off:off + l.size].reshape(l.shape).astype(
                        l.dtype)
                    off += l.size
                    out[i] = piece / n if self.op == "mean" else piece
        return jax.tree_util.tree_unflatten(treedef, out), state


register_reducer("flat", FlatReducer)

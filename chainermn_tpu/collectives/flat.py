"""The flat strategy — today's psum, the numerical reference.

Reference: pure_nccl_communicator.py — pack, ONE ring allreduce, unpack.
Here it simply delegates to ``XlaCommunicator.allreduce_grad``, so
``grad_reducer='flat'`` is **bit-identical** to not passing a reducer at
all (same primitives in the same order; the acceptance bar for every
other strategy is measured against this one).
"""

from __future__ import annotations

from chainermn_tpu.collectives.base import GradReducer, register_reducer


class FlatReducer(GradReducer):
    """One flat (bucketed, if the communicator buckets) psum per leaf
    group — exactly ``comm.allreduce_grad``."""

    name = "flat"

    def reduce(self, grads, state=()):
        return self.comm.allreduce_grad(grads, self.op), state


register_reducer("flat", FlatReducer)

"""Quantized allreduce / reduce-scatter with error feedback.

Reference: pure_nccl_communicator.py's ``allreduce_grad_dtype`` (fp16
communication for fp32 parameters) is the lossy-compression end of the
communicator zoo; EQuARX (arxiv 2506.17615) shows block-scaled
quantized allreduce inside XLA recovering near-full model quality at
about half the communication bytes.

Wire formats (``WIRE_ITEMSIZE`` maps each to its bytes/element):

* ``bf16`` — cast the (error-compensated) gradient to bfloat16 and
  psum in bf16: half the wire bytes, rounding error ~2^-8;
* ``int8`` — per-bucket GLOBAL scale ``pmax(|g|)/127``, symmetric
  round-to-nearest, accumulate the allreduce in int32 (no overflow up
  to 2^24 ranks), dequantize with the shared scale: quarter the wire
  bytes;
* ``int8-block`` — per-BLOCK scales (``QUANT_BLOCK`` = 256 elements,
  ``pmax`` shared across ranks per block), int32 accumulation, fused
  dequant: quarter the wire bytes plus one f32 scale per block
  (~0.254x), but the scale tracks each block's own dynamic range —
  one outlier no longer crushes the whole bucket's resolution;
* ``int4-block`` — per-block scales with 4-bit symmetric values in
  [-7, 7]; on storage wires (serving weight publish,
  :func:`pack_int4`) two values pack per byte for ~0.129x; the
  in-program collective accumulates the 4-bit codes in int32 (a sum of
  packed nibbles is not the packed sum), so the compiled HLO carries
  the same narrow-integer collective as int8-block with 16x coarser
  values.

The dequantize is FUSED into the reduction epilogue: the collective
itself runs on the narrow/int tensor and the ``* scale`` lands on the
collective's output (for reduce-scatter, on the 1/N tile with that
tile's slice of the scales) — the compiled HLO carries a narrow-dtype
collective, never quantize -> wide allreduce -> dequantize (pinned by
analysis pass DL205 and tests/collectives_tests/test_hlo_structure.py).

**Error feedback** (``ef=True``, the default): the quantization
residual ``e = g' - dequant(quant(g'))`` is carried as explicit reducer
state and re-injected next step (``g' = g + e``), so compression error
accumulates into the *next* update instead of being lost — the
difference between a convergent and a visibly-degraded run
(tests/collectives_tests/test_reducers.py measures both). The residual
is PER-RANK state: globally it is a ``(comm.size, bucket_len)`` array
sharded over the comm axis, threaded through the train step inside the
optimizer state (``create_multi_node_optimizer`` wraps it;
``make_data_parallel_train_step`` shards it), and it rides checkpoints
like any other optimizer-state leaf. The ZeRO-1/2 flat paths thread
the same state through :meth:`QuantizedReducer.reduce_scatter_flat_ef`
— the residual lives in the FLAT-BUCKET frame (full padded vector per
rank, layout identical to the gradient the rank quantizes), so it is
indifferent to which tile the scatter hands each rank and survives the
ZeRO tile layout and checkpoint resharding.

The bucket plan is a pure function of leaf shapes/dtypes (NOT of
varying-axis types), so the state structure is stable across traces and
checkpoint round-trips. Leaves that are already global sums under vma
tracking are pre-scaled by the over-count factor and psummed with the
rest of their bucket — algebraically the identity, so one static plan
serves both vma modes.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.collectives.base import (
    GradReducer,
    register_reducer,
    varying_axes,
)
from chainermn_tpu.comm.xla import plan_buckets
from chainermn_tpu.utils import match_vma

#: wire bytes per element, by format ("f32" is the uncompressed
#: reference — kept here so cost models price every format off one
#: table). int4-block is 0.5 on a packed storage wire (pack_int4).
WIRE_ITEMSIZE = {"f32": 4.0, "bf16": 2.0, "int8": 1.0,
                 "int8-block": 1.0, "int4-block": 0.5}

#: formats QuantizedReducer actually compresses to (f32 is 'use flat')
QUANT_MODES = ("bf16", "int8", "int8-block", "int4-block")

#: elements per scale block for the blockwise formats
QUANT_BLOCK = 256

_QMAX = {"int8": 127.0, "int8-block": 127.0, "int4-block": 7.0}


def wire_ratio(fmt: str) -> float:
    """Wire bytes per f32 payload byte for ``fmt``, INCLUDING the f32
    scale sidecar of the blockwise formats (one scale per
    ``QUANT_BLOCK`` elements = 1/256 extra). Pure arithmetic — the cost
    models (collectives/auto.py, tuning/topology.py) price candidates
    off this ratio."""
    r = WIRE_ITEMSIZE[fmt] / 4.0
    if fmt.endswith("-block"):
        r += 1.0 / QUANT_BLOCK
    return r


def quantized_wire_bytes(payload_bytes: int, fmt: str) -> int:
    """Exact wire bytes for one reduction of ``payload_bytes`` of f32
    payload in format ``fmt`` (values + scales)."""
    if fmt == "f32":
        return int(payload_bytes)
    elems = payload_bytes / 4.0
    val = int(math.ceil(elems * WIRE_ITEMSIZE[fmt]))
    if fmt.endswith("-block"):
        return val + 4 * int(math.ceil(elems / QUANT_BLOCK))
    if fmt == "int8":
        return val + 4  # one global f32 scale
    return val  # bf16: the scale is implicit in the exponent


# -- int4 packing (storage wire) ----------------------------------------

def pack_int4(q):
    """Pack int values in [-8, 7] two per byte (low nibble first; odd
    lengths pad a zero nibble). Exact round-trip with
    :func:`unpack_int4` on every representable value — the serving
    weight plane and any host-side wire use this as the 0.5 B/elem
    storage format."""
    q = jnp.asarray(q).astype(jnp.int32).reshape(-1)
    if q.size % 2:
        q = jnp.concatenate([q, jnp.zeros((1,), q.dtype)])
    lo = q[0::2] & 0xF
    hi = q[1::2] & 0xF
    return (lo | (hi << 4)).astype(jnp.uint8)


def unpack_int4(packed, n: int):
    """Inverse of :func:`pack_int4`: ``n`` sign-extended int32 values
    from the packed bytes."""
    p = jnp.asarray(packed).astype(jnp.int32).reshape(-1)
    lo = p & 0xF
    hi = (p >> 4) & 0xF
    both = jnp.stack([lo, hi], axis=-1).reshape(-1)
    both = jnp.where(both >= 8, both - 16, both)
    return both[:n]


# -- blockwise codec ----------------------------------------------------

def _block_scale(b, qmax: float, axes=None):
    """Per-block symmetric scale for a ``(nblocks, block)`` array; with
    ``axes``, the scale is pmax-shared across ranks so every rank
    quantizes onto the same grid (the precondition for integer
    accumulation)."""
    amax = jnp.max(jnp.abs(b), axis=1)
    if axes is not None:
        amax = lax.pmax(amax, axes)
    return jnp.where(amax > 0, amax / qmax, 1.0).astype(b.dtype)


def block_quantize(v, mode: str = "int8-block", block: int = QUANT_BLOCK):
    """Blockwise-quantize a flat float vector. Returns ``(q, scale)``:
    ``q`` is int8 codes (``int8-block``) or packed uint8 two-per-byte
    (``int4-block``); ``scale`` is one f32-ish scale per block (the
    input's dtype). Host- and device-safe; the serving weight plane
    reuses exactly this codec (manifest-recorded scales)."""
    if mode not in ("int8-block", "int4-block"):
        raise ValueError(f"block_quantize: unknown mode {mode!r}")
    qmax = _QMAX[mode]
    v = jnp.asarray(v).reshape(-1)
    pad = (-v.size) % block
    vp = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)]) if pad else v
    b = vp.reshape(-1, block)
    scale = _block_scale(b, qmax)
    q = jnp.clip(jnp.round(b / scale[:, None]), -qmax, qmax)
    q = q.reshape(-1).astype(jnp.int8)
    if mode == "int4-block":
        return pack_int4(q), scale
    return q, scale


def block_dequantize(q, scale, size: int, mode: str = "int8-block",
                     dtype=jnp.float32, block: int = QUANT_BLOCK):
    """Inverse of :func:`block_quantize` (``size`` = original length)."""
    if mode == "int4-block":
        codes = unpack_int4(q, size + ((-size) % block))
    else:
        codes = jnp.asarray(q).astype(jnp.int32).reshape(-1)
    scale = jnp.asarray(scale)
    out = (codes.reshape(-1, block).astype(dtype)
           * scale[:, None].astype(dtype)).reshape(-1)
    return out[:size]


def quantize_allreduce(v, axes, mode: str):
    """Quantized psum of a flat float vector over ``axes``.

    Returns ``(reduced_sum, local_dequant)`` — the second output is this
    rank's dequantized contribution, which error feedback subtracts from
    the pre-quantization value to form the residual. The dequantize is
    fused onto the collective output (narrow-dtype collective in the
    compiled HLO — DL205).
    """
    dt = v.dtype
    if mode == "bf16":
        q = v.astype(jnp.bfloat16)
        return lax.psum(q, axes).astype(dt), q.astype(dt)
    if mode == "int8":
        amax = lax.pmax(jnp.max(jnp.abs(v)), axes)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(dt)
        q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int32)
        return lax.psum(q, axes).astype(dt) * scale, q.astype(dt) * scale
    if mode in ("int8-block", "int4-block"):
        qmax = _QMAX[mode]
        pad = (-v.size) % QUANT_BLOCK
        vp = (jnp.concatenate([v, jnp.zeros((pad,), dt)]) if pad else v)
        b = vp.reshape(-1, QUANT_BLOCK)
        scale = _block_scale(b, qmax, axes)
        q = jnp.clip(jnp.round(b / scale[:, None]),
                     -qmax, qmax).astype(jnp.int32)
        red = lax.psum(q, axes)  # s32 on the wire (narrow — DL205)
        deq = (red.astype(dt) * scale[:, None]).reshape(-1)
        loc = (q.astype(dt) * scale[:, None]).reshape(-1)
        return deq[:v.size], loc[:v.size]
    raise ValueError(f"unknown quantization mode {mode!r}")


class QuantizedReducer(GradReducer):
    """Scaled quantized allreduce / reduce-scatter with error feedback.

    Args (beyond the base): ``mode`` (alias ``wire_format``) — one of
    :data:`QUANT_MODES` (``'bf16'`` default); ``ef`` — carry
    error-feedback residuals (default True; ``ef=False`` is stateless
    and the degraded baseline the convergence tests compare against).
    Stateful operation works in the DP path (residuals ride
    ``_ReducerWrappedState``) AND the ZeRO-1/2 flat paths
    (:meth:`reduce_scatter_flat_ef` — the ZeRO step factories thread
    the residual automatically).
    """

    name = "quantized"
    wire_formats = QUANT_MODES

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 mode: str = "bf16", ef: bool = True,
                 bucket_order: str = "emission",
                 wire_format: Optional[str] = None):
        # bucket_order intentionally NOT forwarded to _plan: the EF
        # residual layout is pinned to the dtype-grouped pytree-order
        # plan (checkpoints depend on it) — accepted for signature
        # parity, validated by the base
        super().__init__(comm, op, bucket_bytes, bucket_order)
        if wire_format is not None:
            if wire_format == "f32":
                raise ValueError(
                    "wire_format='f32' is the uncompressed wire — use "
                    "the 'flat' strategy instead of QuantizedReducer")
            mode = wire_format
        if mode not in QUANT_MODES:
            raise ValueError(f"unknown quantization mode {mode!r}; "
                             f"expected one of {QUANT_MODES}")
        self.mode = mode
        self.ef = ef
        self.stateful = bool(ef)

    # -- the static bucket plan -----------------------------------------
    def _plan(self, leaves) -> List[Tuple[jnp.dtype, bool, List[int]]]:
        """``[(dtype, quantize?, [leaf indices])]`` — groups leaves by
        dtype in pytree order; non-float leaves take an exact psum (a
        quantized integer gradient is nonsense) and carry no residual."""
        by_dt = defaultdict(list)
        for i, l in enumerate(leaves):
            by_dt[jnp.dtype(l.dtype)].append(i)
        plan = []
        for dt, idxs in by_dt.items():
            quant = bool(jnp.issubdtype(dt, jnp.floating))
            for bucket in plan_buckets(
                    [(i, leaves[i].size * dt.itemsize) for i in idxs],
                    self.bucket_bytes):
                plan.append((dt, quant, bucket))
        return plan

    def _bucket_lens(self, params):
        leaves = jax.tree_util.tree_leaves(params)
        return [(dt, sum(leaves[i].size for i in b))
                for dt, quant, b in self._plan(leaves) if quant]

    def init(self, params):
        if not self.ef:
            return ()
        return tuple(jnp.zeros((ln,), dt)
                     for dt, ln in self._bucket_lens(params))

    def init_global(self, params):
        if not self.ef:
            return ()
        n = self.comm.size
        return tuple(jnp.zeros((n, ln), dt)
                     for dt, ln in self._bucket_lens(params))

    # -- the hot path ---------------------------------------------------
    def reduce(self, grads, state=()):
        comm = self.comm
        axes = comm.axis_names
        n = comm.size
        sizes = dict(zip(comm.mesh.axis_names, comm.mesh.devices.shape))
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        plan = self._plan(leaves)
        if self.ef:
            n_q = sum(1 for _, q, _ in plan if q)
            if len(state) != n_q:
                raise ValueError(
                    f"quantized reducer state has {len(state)} residual "
                    f"buckets but the gradient tree plans {n_q}; was the "
                    "state initialized against a different model?")
        # full-variance template: pre-scaled invariant leaves are pcast
        # onto it so the whole bucket psums over every comm axis
        tmpl = sum(lax.axis_index(a) for a in axes)
        out = [None] * len(leaves)
        new_state, si = [], 0
        for dt, quant, bucket in plan:
            parts = []
            for i in bucket:
                l = leaves[i]
                va = varying_axes(l, axes)
                # psum over ALL axes over-counts an invariant axis by its
                # size — pre-divide so the bucket psum is the global sum
                m = n // math.prod([sizes[a] for a in va] or [1])
                v = l.ravel().astype(dt)
                if m > 1:
                    v = v / m
                parts.append(match_vma(v, tmpl))
            flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            if quant:
                if self.ef:
                    flat = flat + state[si]
                red, local_deq = quantize_allreduce(flat, axes, self.mode)
                if self.ef:
                    new_state.append(flat - local_deq)
                    si += 1
            else:
                red = lax.psum(flat, axes)
            off = 0
            for i in bucket:
                l = leaves[i]
                piece = red[off:off + l.size].reshape(l.shape).astype(
                    l.dtype)
                off += l.size
                out[i] = piece / n if self.op == "mean" else piece
        return (jax.tree_util.tree_unflatten(treedef, out),
                tuple(new_state) if self.ef else state)

    # -- ZeRO flat-vector hooks -----------------------------------------
    def _quantize_scatter(self, v, ax: str, n: int):
        """Quantized sum-reduce-scatter of one flat vector (length a
        multiple of ``n``): the collective runs on the narrow/int tensor
        and the dequant lands on the scattered tile with that tile's
        slice of the scales. Returns ``(tile_sum, local_dequant)`` —
        ``local_dequant`` is full-length (this rank's dequantized
        contribution, the error-feedback subtrahend)."""
        dt = v.dtype
        if self.mode == "bf16":
            q = v.astype(jnp.bfloat16)
            s = lax.psum_scatter(q, ax, tiled=True)
            return s.astype(dt), q.astype(dt)
        if self.mode == "int8":
            amax = lax.pmax(jnp.max(jnp.abs(v)), ax)
            scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(dt)
            q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int32)
            s = lax.psum_scatter(q, ax, tiled=True)
            return s.astype(dt) * scale, q.astype(dt) * scale
        # blockwise: the block must divide the tile so no scale block
        # straddles a tile boundary. ZeRO's padding quantum (256, zero.py
        # _padded_size) makes tiles multiples of QUANT_BLOCK/n, so the
        # gcd stays >= 256/n on any axis size dividing 256.
        qmax = _QMAX[self.mode]
        tile = v.size // n
        blk = math.gcd(QUANT_BLOCK, tile) or 1
        b = v.reshape(-1, blk)
        scale = _block_scale(b, qmax, ax)
        q = jnp.clip(jnp.round(b / scale[:, None]),
                     -qmax, qmax).astype(jnp.int32)
        s = lax.psum_scatter(q.reshape(-1), ax, tiled=True)  # s32 wire
        tb = tile // blk
        ts = lax.dynamic_slice_in_dim(scale, lax.axis_index(ax) * tb, tb)
        tile_sum = (s.reshape(tb, blk).astype(dt)
                    * ts[:, None]).reshape(-1)
        local = (q.astype(dt) * scale[:, None]).reshape(-1)
        return tile_sum, local

    def reduce_scatter_flat(self, g, ax: str, n: int):
        if self.ef:
            raise RuntimeError(
                "QuantizedReducer(ef=True) threads per-rank residual "
                "state through reduce_scatter_flat_ef — the ZeRO step "
                "factories do this automatically; call "
                "reduce_scatter_flat only on stateless (ef=False) "
                "reducers")
        tile_sum, _ = self._quantize_scatter(g, ax, n)
        return tile_sum / n

    def reduce_scatter_flat_ef(self, g, e, ax: str, n: int):
        """Error-feedback mean-reduce-scatter: ``e`` is this rank's
        residual in the FLAT-BUCKET frame (full padded vector — the
        frame the rank quantizes in, independent of which tile the
        scatter hands it, so the state survives the ZeRO tile layout
        and resharding). Returns ``(tile_mean, new_residual)``."""
        v = g + e
        tile_sum, local = self._quantize_scatter(v, ax, n)
        return tile_sum / n, v - local

    def wire_bytes(self, payload_bytes: int) -> int:
        # payload is in the leaf dtype (4 B f32 typical); the wire
        # carries the quantized values plus the f32 scales (one per
        # bucket for int8, one per QUANT_BLOCK elements for the block
        # formats; bf16's scale is implicit in the exponent)
        return quantized_wire_bytes(payload_bytes, self.mode)


register_reducer("quantized", QuantizedReducer)

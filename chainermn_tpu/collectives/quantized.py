"""Quantized allreduce with error feedback.

Reference: pure_nccl_communicator.py's ``allreduce_grad_dtype`` (fp16
communication for fp32 parameters) is the lossy-compression end of the
communicator zoo; EQuARX (arxiv 2506.17615) shows block-scaled
quantized allreduce inside XLA recovering near-full model quality at
about half the communication bytes.

Two wire formats:

* ``bf16`` — cast the (error-compensated) gradient to bfloat16 and
  psum in bf16: half the wire bytes, rounding error ~2^-8;
* ``int8`` — per-bucket global scale ``pmax(|g|)/127``, symmetric
  round-to-nearest, accumulate the allreduce in int32 (no overflow up
  to 2^24 ranks), dequantize with the shared scale: quarter the wire
  bytes.

**Error feedback** (``ef=True``, the default): the quantization
residual ``e = g' - dequant(quant(g'))`` is carried as explicit reducer
state and re-injected next step (``g' = g + e``), so compression error
accumulates into the *next* update instead of being lost — the
difference between a convergent and a visibly-degraded run
(tests/collectives_tests/test_reducers.py measures both). The residual
is PER-RANK state: globally it is a ``(comm.size, bucket_len)`` array
sharded over the comm axis, threaded through the train step inside the
optimizer state (``create_multi_node_optimizer`` wraps it;
``make_data_parallel_train_step`` shards it), and it rides checkpoints
like any other optimizer-state leaf.

The bucket plan is a pure function of leaf shapes/dtypes (NOT of
varying-axis types), so the state structure is stable across traces and
checkpoint round-trips. Leaves that are already global sums under vma
tracking are pre-scaled by the over-count factor and psummed with the
rest of their bucket — algebraically the identity, so one static plan
serves both vma modes.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.collectives.base import (
    GradReducer,
    register_reducer,
    varying_axes,
)
from chainermn_tpu.comm.xla import plan_buckets
from chainermn_tpu.utils import match_vma

WIRE_ITEMSIZE = {"bf16": 2, "int8": 1}


def quantize_allreduce(v, axes, mode: str):
    """Quantized psum of a flat float vector over ``axes``.

    Returns ``(reduced_sum, local_dequant)`` — the second output is this
    rank's dequantized contribution, which error feedback subtracts from
    the pre-quantization value to form the residual.
    """
    dt = v.dtype
    if mode == "bf16":
        q = v.astype(jnp.bfloat16)
        return lax.psum(q, axes).astype(dt), q.astype(dt)
    if mode == "int8":
        amax = lax.pmax(jnp.max(jnp.abs(v)), axes)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(dt)
        q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int32)
        return lax.psum(q, axes).astype(dt) * scale, q.astype(dt) * scale
    raise ValueError(f"unknown quantization mode {mode!r}")


class QuantizedReducer(GradReducer):
    """Per-bucket scaled quantized allreduce with error feedback.

    Args (beyond the base): ``mode`` — ``'bf16'`` (default) or
    ``'int8'``; ``ef`` — carry error-feedback residuals (default True;
    ``ef=False`` is stateless — usable in the ZeRO reduce-scatter paths,
    and the degraded baseline the convergence tests compare against).
    """

    name = "quantized"

    def __init__(self, comm, op: str = "mean",
                 bucket_bytes: Optional[int] = None,
                 mode: str = "bf16", ef: bool = True,
                 bucket_order: str = "emission"):
        # bucket_order intentionally NOT forwarded to _plan: the EF
        # residual layout is pinned to the dtype-grouped pytree-order
        # plan (checkpoints depend on it) — accepted for signature
        # parity, validated by the base
        super().__init__(comm, op, bucket_bytes, bucket_order)
        if mode not in WIRE_ITEMSIZE:
            raise ValueError(f"unknown quantization mode {mode!r}")
        self.mode = mode
        self.ef = ef
        self.stateful = bool(ef)

    # -- the static bucket plan -----------------------------------------
    def _plan(self, leaves) -> List[Tuple[jnp.dtype, bool, List[int]]]:
        """``[(dtype, quantize?, [leaf indices])]`` — groups leaves by
        dtype in pytree order; non-float leaves take an exact psum (a
        quantized integer gradient is nonsense) and carry no residual."""
        by_dt = defaultdict(list)
        for i, l in enumerate(leaves):
            by_dt[jnp.dtype(l.dtype)].append(i)
        plan = []
        for dt, idxs in by_dt.items():
            quant = bool(jnp.issubdtype(dt, jnp.floating))
            for bucket in plan_buckets(
                    [(i, leaves[i].size * dt.itemsize) for i in idxs],
                    self.bucket_bytes):
                plan.append((dt, quant, bucket))
        return plan

    def _bucket_lens(self, params):
        leaves = jax.tree_util.tree_leaves(params)
        return [(dt, sum(leaves[i].size for i in b))
                for dt, quant, b in self._plan(leaves) if quant]

    def init(self, params):
        if not self.ef:
            return ()
        return tuple(jnp.zeros((ln,), dt)
                     for dt, ln in self._bucket_lens(params))

    def init_global(self, params):
        if not self.ef:
            return ()
        n = self.comm.size
        return tuple(jnp.zeros((n, ln), dt)
                     for dt, ln in self._bucket_lens(params))

    # -- the hot path ---------------------------------------------------
    def reduce(self, grads, state=()):
        comm = self.comm
        axes = comm.axis_names
        n = comm.size
        sizes = dict(zip(comm.mesh.axis_names, comm.mesh.devices.shape))
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        plan = self._plan(leaves)
        if self.ef:
            n_q = sum(1 for _, q, _ in plan if q)
            if len(state) != n_q:
                raise ValueError(
                    f"quantized reducer state has {len(state)} residual "
                    f"buckets but the gradient tree plans {n_q}; was the "
                    "state initialized against a different model?")
        # full-variance template: pre-scaled invariant leaves are pcast
        # onto it so the whole bucket psums over every comm axis
        tmpl = sum(lax.axis_index(a) for a in axes)
        out = [None] * len(leaves)
        new_state, si = [], 0
        for dt, quant, bucket in plan:
            parts = []
            for i in bucket:
                l = leaves[i]
                va = varying_axes(l, axes)
                # psum over ALL axes over-counts an invariant axis by its
                # size — pre-divide so the bucket psum is the global sum
                m = n // math.prod([sizes[a] for a in va] or [1])
                v = l.ravel().astype(dt)
                if m > 1:
                    v = v / m
                parts.append(match_vma(v, tmpl))
            flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            if quant:
                if self.ef:
                    flat = flat + state[si]
                red, local_deq = quantize_allreduce(flat, axes, self.mode)
                if self.ef:
                    new_state.append(flat - local_deq)
                    si += 1
            else:
                red = lax.psum(flat, axes)
            off = 0
            for i in bucket:
                l = leaves[i]
                piece = red[off:off + l.size].reshape(l.shape).astype(
                    l.dtype)
                off += l.size
                out[i] = piece / n if self.op == "mean" else piece
        return (jax.tree_util.tree_unflatten(treedef, out),
                tuple(new_state) if self.ef else state)

    def reduce_scatter_flat(self, g, ax: str, n: int):
        if self.ef:
            raise RuntimeError(
                "QuantizedReducer(ef=True) carries per-rank residual "
                "state, which the ZeRO flat-vector paths cannot thread; "
                "use ef=False here, or the data-parallel step "
                "(make_data_parallel_train_step) for error feedback")
        dt = g.dtype
        if self.mode == "bf16":
            s = lax.psum_scatter(g.astype(jnp.bfloat16), ax, tiled=True)
            return s.astype(dt) / n
        amax = lax.pmax(jnp.max(jnp.abs(g)), ax)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(dt)
        q = jnp.clip(jnp.round(g / scale), -127, 127).astype(jnp.int32)
        return lax.psum_scatter(q, ax, tiled=True).astype(dt) * scale / n

    def wire_bytes(self, payload_bytes: int) -> int:
        # payload is in the leaf dtype (4 B f32 typical); the wire
        # carries the quantized format (+ nothing for bf16's implicit
        # scale, + one f32 scale per bucket for int8)
        ratio = WIRE_ITEMSIZE[self.mode] / 4.0
        extra = 4 if self.mode == "int8" else 0
        return int(payload_bytes * ratio) + extra


register_reducer("quantized", QuantizedReducer)

#!/usr/bin/env python
"""ckpt — offline snapshot inspection, verification, and reshard planning.

Operates on a checkpointer job directory (the ``<path>/<name>`` tree
holding ``snapshot_iter_<N>.<rank>`` files, their ``.json`` sidecar
manifests, and the ``replicas/`` ring copies) WITHOUT a communicator or
any device work — everything here reads sidecar JSON and the small
geometry keys inside each npz (gshape/nshards/idx); shard payloads are
only hashed, never deserialized.

Usage::

    python tools/ckpt.py inspect  DIR [--iteration N]
    python tools/ckpt.py verify   DIR [--iteration N]
    python tools/ckpt.py reshard-dry-run DIR --target data=2,model=2 \\
        [--iteration N]

``inspect`` lists every iteration's file set, its manifest summary
(saving world, mesh axes, bytes), and the per-leaf shard-coverage
report — which global index ranges the surviving files actually hold.

``verify`` recomputes each file's SHA-256 and byte size against its
sidecar manifest (the same check the consensus election runs) and
exits 1 on any mismatch; files without a manifest are reported but
tolerated, matching the checkpointer's compatibility behavior.

``reshard-dry-run`` plans the splice a resume onto ``--target`` (an
``axis=size`` map for the NEW mesh) would perform: per leaf, which
saved shards supply each target shard range, whether coverage is
complete, and which world-stacked EF residual frames would regroup
(``checkpointing/reshard.py:ef_frame_regroup``) instead of splicing.
The per-dim split is a heuristic — offline, the template's
PartitionSpec is unknown, so a dim is matched to a mesh axis by its
saved cut count — but coverage itself is exact interval arithmetic.

Exit status: 0 clean, 1 findings/failures, 2 usage error.
"""

import argparse
import hashlib
import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

_SNAP_RE = re.compile(r"snapshot_iter_(\d+)\.(\d+)$")


def _read_manifest(fn):
    """Sidecar JSON for snapshot file ``fn`` (None when missing/torn).
    Local copy of extensions/checkpoint.py:read_manifest so plain
    verification needs no package import."""
    try:
        with open(fn + ".json", "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _sha256_file(fn):
    h = hashlib.sha256()
    with open(fn, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _scan(path):
    """{iteration: [files]} across the job dir and its replicas/."""
    out = {}
    for d in (path, os.path.join(path, "replicas")):
        if not os.path.isdir(d):
            continue
        for f in sorted(os.listdir(d)):
            m = _SNAP_RE.match(f)
            fn = os.path.join(d, f)
            if m and not os.path.isdir(fn):
                out.setdefault(int(m.group(1)), []).append(fn)
    return out


def _file_leaf_intervals(fn):
    """{leaf: [interval bounds]} held by ONE file — the per-file half of
    reshard.leaf_coverage's aggregate. Reads only geometry keys."""
    out = {}
    with np.load(fn, allow_pickle=False) as z:
        keys = set(z.files)
        for k in keys:
            m = re.match(r"leaf_(\d+)_nshards$", k)
            if m:
                i = int(m.group(1))
                gshape = tuple(int(d) for d in z[f"leaf_{i}_gshape"])
                ivs = out.setdefault(i, [])
                for s in range(int(z[k])):
                    idx = np.asarray(z[f"leaf_{i}_idx{s}"])
                    ivs.append(tuple(
                        (int(a), int(b) if b != -1 else d)
                        for (a, b), d in zip(idx, gshape)))
                continue
            m = re.match(r"leaf_(\d+)$", k)
            if m:
                i = int(m.group(1))
                out.setdefault(i, []).append(tuple(
                    (0, d) for d in z[k].shape))
    return out


def _coverage(files):
    """Aggregate per-leaf coverage across a file set, with file
    attribution: {leaf: {gshape, intervals: {bounds: [files]},
    covered, volume}}."""
    leaves = {}
    for fn in files:
        for i, ivs in _file_leaf_intervals(fn).items():
            with np.load(fn, allow_pickle=False) as z:
                if f"leaf_{i}_gshape" in z.files:
                    gshape = tuple(int(d) for d in z[f"leaf_{i}_gshape"])
                else:
                    gshape = tuple(int(d) for d in z[f"leaf_{i}"].shape)
            rec = leaves.setdefault(i, {"gshape": gshape, "intervals": {}})
            for bounds in ivs:
                rec["intervals"].setdefault(bounds, []).append(fn)
    for rec in leaves.values():
        total = int(np.prod(rec["gshape"], dtype=np.int64)) \
            if rec["gshape"] else 1
        vol = sum(int(np.prod([b - a for a, b in iv], dtype=np.int64))
                  for iv in rec["intervals"])
        rec["volume"] = vol
        rec["covered"] = vol == total  # saved intervals are a partition
    return leaves


def _best_manifest(files):
    best = None
    for fn in files:
        mf = _read_manifest(fn)
        if mf is None:
            continue
        if "axes" in mf or "leaves" in mf:
            return mf
        best = best or mf
    return best


def _pick_iteration(snaps, iteration):
    if not snaps:
        print("no snapshot files found", file=sys.stderr)
        return None
    if iteration is None:
        return max(snaps)
    if iteration not in snaps:
        print(f"iteration {iteration} not found "
              f"(have: {sorted(snaps)})", file=sys.stderr)
        return None
    return iteration


def _fmt_bounds(bounds):
    return "[" + ", ".join(f"{a}:{b}" for a, b in bounds) + "]"


# -- subcommands ---------------------------------------------------------

def cmd_inspect(args):
    snaps = _scan(args.dir)
    if not snaps:
        print("no snapshot files found", file=sys.stderr)
        return 1
    iters = [args.iteration] if args.iteration is not None else sorted(snaps)
    for it in iters:
        if it not in snaps:
            print(f"iteration {it} not found", file=sys.stderr)
            return 1
        files = snaps[it]
        mf = _best_manifest(files) or {}
        axes = mf.get("axes")
        print(f"iteration {it}: {len(files)} file(s), "
              f"world={mf.get('world', '?')}, "
              f"axes={axes if axes else '?'}")
        for fn in files:
            sz = os.path.getsize(fn)
            tag = " (replica)" if os.sep + "replicas" + os.sep in fn else ""
            print(f"  {os.path.basename(fn)}  {sz:,} bytes{tag}")
        if mf.get("layout"):
            print(f"  layout: {mf['layout'].get('kind', '?')}")
        for i, rec in sorted(_coverage(files).items()):
            nshards = len(rec["intervals"])
            state = "complete" if rec["covered"] else \
                f"INCOMPLETE ({rec['volume']}/" \
                f"{int(np.prod(rec['gshape'], dtype=np.int64))} elements)"
            print(f"  leaf {i}: gshape={rec['gshape']} "
                  f"{nshards} saved range(s) — {state}")
    return 0


def cmd_verify(args):
    snaps = _scan(args.dir)
    if not snaps:
        print("no snapshot files found", file=sys.stderr)
        return 1
    iters = [args.iteration] if args.iteration is not None else sorted(snaps)
    failures = 0
    for it in iters:
        if it not in snaps:
            print(f"iteration {it} not found", file=sys.stderr)
            return 1
        for fn in snaps[it]:
            mf = _read_manifest(fn)
            name = os.path.basename(fn)
            if mf is None:
                print(f"  {name}: no manifest (pre-hardening snapshot "
                      "— tolerated)")
                continue
            size = os.path.getsize(fn)
            if mf.get("bytes") not in (None, size):
                print(f"  {name}: FAIL — size {size} != manifest "
                      f"{mf.get('bytes')}")
                failures += 1
                continue
            sha = _sha256_file(fn)
            if sha != mf.get("sha256"):
                print(f"  {name}: FAIL — sha256 mismatch")
                failures += 1
            else:
                print(f"  {name}: ok ({size:,} bytes, "
                      f"sha256 {sha[:12]}…)")
    print(f"verify: {failures} failure(s)")
    return 1 if failures else 0


def _parse_target(spec):
    axes = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad --target entry {part!r} "
                             "(expected axis=size)")
        k, v = part.split("=", 1)
        axes[k.strip()] = int(v)
    if not axes:
        raise ValueError("--target parsed to no axes")
    return axes


def cmd_reshard_dry_run(args):
    try:
        target = _parse_target(args.target)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    snaps = _scan(args.dir)
    it = _pick_iteration(snaps, args.iteration)
    if it is None:
        return 1
    files = snaps[it]
    mf = _best_manifest(files) or {}
    saved_axes = mf.get("axes")
    saved_world = mf.get("world")
    t_world = 1
    for v in target.values():
        t_world *= v
    print(f"reshard dry run: iteration {it}")
    print(f"  saved mesh:  axes={saved_axes if saved_axes else '?'} "
          f"world={saved_world if saved_world is not None else '?'}")
    print(f"  target mesh: axes={target} world={t_world}")
    problems = 0
    for i, rec in sorted(_coverage(files).items()):
        gshape = rec["gshape"]
        intervals = rec["intervals"]
        print(f"  leaf {i}: gshape={gshape}")
        if not rec["covered"]:
            total = int(np.prod(gshape, dtype=np.int64))
            print(f"    INCOMPLETE — saved ranges cover "
                  f"{rec['volume']}/{total} elements; splice would fail")
            problems += 1
            continue
        # which dims the SAVED layout actually cut
        cuts = [sorted({iv[d] for iv in intervals})
                for d in range(len(gshape))]
        sharded_dims = [d for d in range(len(gshape))
                        if len(cuts[d]) > 1 or
                        (cuts[d] and cuts[d][0] != (0, gshape[d]))]
        # world-stacked EF frame? leading dim == saving world and the
        # target world differs -> regroup, not splice
        if (len(gshape) == 2 and saved_world is not None
                and gshape[0] == saved_world and t_world != saved_world):
            n_old, n_new = saved_world, t_world
            if n_old % n_new == 0 or n_new % n_old == 0:
                how = (f"mean over groups of {n_old // n_new}"
                       if n_old % n_new == 0
                       else f"repeat x{n_new // n_old}")
                print(f"    EF frame ({n_old}, {gshape[1]}): regroup "
                      f"-> ({n_new}, {gshape[1]}) ({how}, "
                      "mean-preserving)")
            else:
                print(f"    EF frame: CANNOT regroup {n_old} -> "
                      f"{n_new} ranks (neither divides the other)")
                problems += 1
            continue
        if not sharded_dims:
            print(f"    replicated — any of {len(intervals)} saved "
                  "copy(ies) restores it on every target device")
            continue
        for d in sharded_dims:
            n_saved = len(cuts[d])
            # match the cut count to a saved axis, then read the
            # target's size for that axis (heuristic; see module doc)
            axis = None
            if saved_axes:
                for a, s in saved_axes.items():
                    if int(s) == n_saved:
                        axis = a
                        break
            n_target = int(target.get(axis, t_world)) if axis \
                else t_world
            print(f"    dim {d}: {n_saved} saved range(s)"
                  + (f" over axis {axis!r}" if axis else "")
                  + f" -> {n_target} target range(s)")
            if gshape[d] % n_target:
                print(f"      WARNING: dim size {gshape[d]} not "
                      f"divisible by {n_target} — uneven target tiles")
            step = max(1, gshape[d] // n_target)
            for t in range(n_target):
                lo = t * step
                hi = (t + 1) * step if t < n_target - 1 else gshape[d]
                sources = sorted({
                    os.path.basename(f)
                    for bounds, fs in intervals.items()
                    if bounds[d][0] < hi and bounds[d][1] > lo
                    for f in fs})
                print(f"      target [{lo}:{hi}] <- "
                      f"{len(sources)} source file(s): "
                      + ", ".join(sources[:4])
                      + (" …" if len(sources) > 4 else ""))
                if not sources:
                    problems += 1
    print(f"dry run: {'OK — splice plan complete' if not problems else str(problems) + ' problem(s)'}")
    return 1 if problems else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ckpt", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("inspect", cmd_inspect), ("verify", cmd_verify),
                     ("reshard-dry-run", cmd_reshard_dry_run)):
        p = sub.add_parser(name)
        p.add_argument("dir", help="checkpointer job directory "
                                   "(<path>/<name>)")
        p.add_argument("--iteration", type=int, default=None)
        p.set_defaults(fn=fn)
        if name == "reshard-dry-run":
            p.add_argument("--target", required=True,
                           help="target mesh axes, e.g. data=2,model=2")
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Compile the data-parallel train step for a 2-slice TPU topology (AOT,
no chips needed) and report whether the optimized schedule interleaves
the gradient all-reduces with backward compute.

This turns docs/scaling_model.md §2's central assumption — "the gradient
all-reduce hides inside the backward window via XLA's latency-hiding
scheduler" — into compiler-emitted evidence: in the scheduled entry
computation, the FIRST gradient all-reduce must be placed before the
LAST backward op (ops carry ``transpose(jvp`` metadata), i.e. XLA issues
gradient collectives while backward compute remains, rather than
serializing them after it. Prints one JSON line::

    {"ok": true, "first_allreduce": 46, "last_backward": 90,
     "n_sched_ops": 97, "n_allreduce": 2, ...}

Also certifies (r5) the 1F1B PIPELINE schedule: the tick's wire
ppermutes must lower to async collective-permute-start/done pairs with
stage compute scheduled between them (the per-tick wire hop hides
behind compute — docs/scaling_model.md §6), reported under the
``pipeline_1f1b`` key and folded into ``ok``.

Run on any machine with the TPU compiler plugin (the topology is
described, not attached): ``python tools/check_overlap_schedule.py``.
The test suite asserts ok=true via tests/comm_tests/test_overlap_schedule.py.
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def scheduled_entry_ops(hlo_text):
    """(op_kind, metadata) per instruction of the ENTRY computation, in
    schedule order (the module is scheduled: is_scheduled=true)."""
    ops = []
    in_entry = False
    for ln in hlo_text.splitlines():
        if ln.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if ln.startswith("}"):
                break
            s = ln.strip()
            if not re.match(r"%?[\w.-]+ = ", s):
                continue
            # the opcode is the token right before the operand list;
            # match it AFTER the (possibly tuple, space-containing)
            # result type by anchoring on "opcode(%" — every entry op
            # of interest takes at least one %operand
            m = re.search(r" ([a-z][\w-]*)\(%", s)
            if m:
                ops.append((m.group(1), s))
    return ops


def main():
    import numpy as np

    import jax
    import jax.numpy as jnp

    try:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x4")
    except Exception as e:  # no TPU compiler plugin on this machine
        print(json.dumps({"ok": None, "skip": f"no TPU topology: {e}"}))
        return

    import optax
    from flax import linen as nn
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import chainermn_tpu
    from chainermn_tpu.comm.xla import XlaCommunicator
    from chainermn_tpu.training.step import make_data_parallel_train_step

    class Big(nn.Module):
        """~35M params (141 MB f32 grads): large enough that XLA's
        all-reduce combiner keeps >1 combined collective, so the
        schedule has something to interleave."""

        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            for _ in range(3):
                x = nn.relu(nn.Dense(4096)(x))
            return nn.Dense(10)(x)

    mesh = Mesh(np.asarray(topo.devices).reshape(2, 4), ("dcn", "ici"))
    comm = XlaCommunicator(mesh=mesh)
    model = Big()
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 28, 28), jnp.float32))["params"])
    opt = optax.sgd(0.1)
    mnopt = chainermn_tpu.create_multi_node_optimizer(opt, comm)
    state = (params, jax.eval_shape(opt.init, params))
    step = make_data_parallel_train_step(model, mnopt, comm, donate=False)

    dsh = NamedSharding(mesh, P(("dcn", "ici")))
    rep = NamedSharding(mesh, P())
    x = jax.ShapeDtypeStruct((64, 28, 28), jnp.float32, sharding=dsh)
    y = jax.ShapeDtypeStruct((64,), jnp.int32, sharding=dsh)
    state = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=rep),
        state)

    def analyze(compiled):
        txt = compiled.as_text()
        ops = scheduled_entry_ops(txt)
        ar = [i for i, (k, _) in enumerate(ops)
              if k in ("all-reduce", "all-reduce-start")]
        bwd = [i for i, (_, s) in enumerate(ops) if "transpose(jvp" in s]
        out = {
            "is_scheduled": "is_scheduled=true" in txt,
            "n_sched_ops": len(ops),
            "n_allreduce": len(ar),
            "first_allreduce": min(ar) if ar else None,
            "last_backward": max(bwd) if bwd else None,
            "backward_ops_after_first_allreduce": (
                sum(1 for i in bwd if i > min(ar)) if ar else 0),
            "async_pairs": bool(re.search(r"all-reduce-start", txt)),
        }
        out["ok"] = bool(
            out["is_scheduled"] and ar and bwd and min(ar) < max(bwd))
        return out

    opts = {
        "xla_tpu_enable_latency_hiding_scheduler": "true",
        "xla_enable_async_all_reduce": "true",
    }
    out = analyze(jax.jit(lambda s, x, y: step(s, x, y)).lower(
        state, x, y).compile(opts))

    # second configuration: the EXPLICITLY bucketed allreduce_grad (the
    # hierarchical communicator's DCN path — one psum per plan_buckets
    # bucket in the jaxpr), asserting the compiler schedules those
    # bucket collectives into the backward window too
    from jax import shard_map

    bcomm = XlaCommunicator(mesh=mesh, dcn_bucket_bytes=16 * 2 ** 20)

    def local_step(p, xb, yb):
        def loss(p):
            logits = model.apply({"params": p}, xb)
            one = jax.nn.one_hot(yb, 10)
            return jnp.mean((logits - one) ** 2)

        l, g = jax.value_and_grad(loss)(p)
        g = bcomm.allreduce_grad(g, "mean")
        newp = jax.tree_util.tree_map(
            lambda a, b: a - 0.1 * b, p, g)
        return jax.lax.pmean(l, ("dcn", "ici")), newp

    sm = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(("dcn", "ici")), P(("dcn", "ici"))),
        out_specs=(P(), P()))
    pab = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=rep),
        params)
    out2 = analyze(jax.jit(sm).lower(pab, x, y).compile(opts))
    out["bucketed_allreduce_grad"] = out2
    out["ok"] = bool(out["ok"] and out2["ok"])

    # third configuration: the 1F1B PIPELINE schedule (VERDICT r4 #5).
    # The pipeline compiles to ONE while loop whose body is the schedule
    # tick: stage compute, then the fwd/bwd wire ppermutes. The claim to
    # certify is that the WIRE HOP OVERLAPS TICK COMPUTE — XLA lowers
    # the ppermutes to async collective-permute-start/done pairs and
    # schedules real fusions between start and done, so the per-tick
    # wire cost (docs/scaling_model.md §6) is hidden behind compute
    # rather than added to it. Analyze the while-BODY computation (the
    # entry schedule only shows the while op itself).
    out["pipeline_1f1b"] = _analyze_pipeline_1f1b(mesh)
    out["ok"] = bool(out["ok"] and out["pipeline_1f1b"]["ok"])
    print(json.dumps(out))


def _split_computations(hlo_text):
    """name -> [(op_kind, result_name, [operand_names])] per HLO
    computation, in schedule order."""
    comps, cur = {}, None
    for ln in hlo_text.splitlines():
        m = re.match(r"^%?([\w.-]+) \(.*\{\s*$", ln)
        if m:
            cur = m.group(1)
            comps[cur] = []
            continue
        if cur is not None:
            if ln.startswith("}"):
                cur = None
                continue
            s = ln.strip()
            mm = re.match(r"%?([\w.-]+) = .*? ([a-z][\w-]*)\((.*)", s)
            if mm:
                operands = re.findall(r"%([\w.-]+)", mm.group(3))
                comps[cur].append((mm.group(2), mm.group(1), operands))
    return comps


def _analyze_pipeline_1f1b(mesh):
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from chainermn_tpu.parallel import (
        pipeline_1f1b_value_and_grad,
        stack_stage_params,
    )

    devs = mesh.devices.reshape(-1)
    smesh = jax.sharding.Mesh(devs, ("stage",))
    S = devs.size
    feat, M = 512, 2 * S  # big stage matmul; M ≥ 2S keeps bubbles small

    plist = [{"w": np.eye(feat, dtype=np.float32)} for _ in range(S)]
    xs = np.ones((M, 4, feat), np.float32)
    tgt = np.zeros((M, 4, feat), np.float32)

    def pp_run(stacked, xs, tgt):
        my = jax.tree_util.tree_map(lambda l: l[0], stacked)
        loss, grads = pipeline_1f1b_value_and_grad(
            lambda p, h: jnp.tanh(h @ p["w"]),
            lambda o, t: jnp.mean((o - t) ** 2),
            my, xs, tgt, axis_name="stage")
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    sm = shard_map(pp_run, mesh=smesh,
                   in_specs=(P("stage"), P(), P()),
                   out_specs=(P(), P("stage")))

    def absify(l, spec):
        return jax.ShapeDtypeStruct(
            np.shape(l), jnp.asarray(l).dtype,
            sharding=NamedSharding(smesh, spec))

    compiled = jax.jit(sm).lower(
        jax.tree_util.tree_map(lambda l: absify(l, P("stage")),
                               stack_stage_params(plist)),
        absify(xs, P()), absify(tgt, P())).compile(
            {"xla_tpu_enable_latency_hiding_scheduler": "true"})
    txt = compiled.as_text()

    best = None
    for name, ops in _split_computations(txt).items():
        starts = [(i, res) for i, (k, res, _) in enumerate(ops)
                  if k == "collective-permute-start"]
        if not starts:
            continue
        fusions = [i for i, (k, _, _) in enumerate(ops)
                   if k in ("fusion", "dot", "custom-call")]
        # match each start to ITS done (the done consuming its result):
        # compute counted inside an unrelated pair's gap must not
        # certify an individually-serialized hop
        pairs = []
        for si, res in starts:
            done = next((i for i, (k, _, opr) in enumerate(ops)
                         if i > si and k == "collective-permute-done"
                         and res in opr), None)
            if done is not None:
                pairs.append(
                    (si, done,
                     sum(1 for f in fusions if si < f < done)))
        if not pairs:
            continue
        cand = {
            "body": name,
            "n_body_ops": len(ops),
            "n_permute_pairs": len(pairs),
            "pairs": [{"start": s, "done": d, "compute_inside": c}
                      for s, d, c in pairs],
            "min_compute_inside_any_pair": min(c for _, _, c in pairs),
            "n_compute": len(fusions),
        }
        if best is None or cand["n_permute_pairs"] > best["n_permute_pairs"]:
            best = cand

    out = best or {"n_permute_pairs": 0}
    out["sync_permutes"] = len(
        re.findall(r"= *\S* *collective-permute\(", txt))
    # ok = both rings async, EVERY hop hides >=1 real compute op inside
    # its own start->done window, and nothing fell back to a synchronous
    # collective-permute
    out["ok"] = bool(best and best["n_permute_pairs"] >= 2
                     and best["min_compute_inside_any_pair"] >= 1
                     and out["sync_permutes"] == 0)
    return out


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Compile the data-parallel train step for a 2-slice TPU topology (AOT,
no chips needed) and report whether the optimized schedule interleaves
the gradient all-reduces with backward compute.

This turns docs/scaling_model.md §2's central assumption — "the gradient
all-reduce hides inside the backward window via XLA's latency-hiding
scheduler" — into compiler-emitted evidence. The analysis itself lives
in :mod:`chainermn_tpu.analysis.hlo_passes` (rules DL201/DL203 — see
docs/static_analysis.md); this tool is the thin wrapper that builds the
representative programs, compiles them against the described topology,
and runs the passes. Prints one JSON line::

    {"ok": true, "first_allreduce": 46, "last_backward": 90,
     "n_sched_ops": 97, "n_allreduce": 2, ...}

Also certifies (r5) the 1F1B PIPELINE schedule: the tick's wire
ppermutes must lower to async collective-permute-start/done pairs with
stage compute scheduled between them (the per-tick wire hop hides
behind compute — docs/scaling_model.md §6), reported under the
``pipeline_1f1b`` key and folded into ``ok``.

Run on any machine with the TPU compiler plugin (the topology is
described, not attached): ``python tools/check_overlap_schedule.py``.
The test suite asserts ok=true via tests/comm_tests/test_overlap_schedule.py.

``--assert-min-overlap FRAC`` additionally gates the DL201 overlap
FRACTION (the schedtune objective — docs/tuning.md): exit 1 when any
compiled DP configuration's fraction of backward ops scheduled after
the first gradient all-reduce falls below FRAC. This is the regression
gate for the bench harness: a schedule that still technically overlaps
(ok=true) but has drifted from, say, 0.9 to 0.3 of the backward window
now fails loudly. The plugin-missing skip stays exit 0 — no machine
should fail CI for lacking a compiler.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from chainermn_tpu.analysis.hlo_passes import (  # noqa: E402
    check_dp_overlap,
    check_pipeline_permute_overlap,
    scheduled_entry_ops,  # noqa: F401  (re-export: judge scripts import it)
)


def analyze(compiled):
    """DL201 on a compiled computation (kept for standalone callers)."""
    return check_dp_overlap(compiled.as_text())


def _parse_min_overlap(argv):
    for i, a in enumerate(argv):
        if a.startswith("--assert-min-overlap"):
            if "=" in a:
                return float(a.split("=", 1)[1])
            if i + 1 >= len(argv):
                raise SystemExit("--assert-min-overlap needs a fraction")
            return float(argv[i + 1])
    return None


def main():
    min_overlap = _parse_min_overlap(sys.argv[1:])
    # AOT-only tool: the topology is described, never attached, so the
    # TPU plugin's GCP-metadata discovery is pure startup cost (~6 min
    # of retrying a 403ing metadata server off-TPU). Opt out unless the
    # caller explicitly set the knob.
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")

    import numpy as np

    import jax
    import jax.numpy as jnp

    try:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x4")
    except Exception as e:  # no TPU compiler plugin on this machine
        print(json.dumps({"ok": None, "skip": f"no TPU topology: {e}"}))
        return

    import optax
    from flax import linen as nn
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import chainermn_tpu
    from chainermn_tpu.comm.xla import XlaCommunicator
    from chainermn_tpu.training.step import make_data_parallel_train_step

    class Big(nn.Module):
        """~35M params (141 MB f32 grads): large enough that XLA's
        all-reduce combiner keeps >1 combined collective, so the
        schedule has something to interleave."""

        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            for _ in range(3):
                x = nn.relu(nn.Dense(4096)(x))
            return nn.Dense(10)(x)

    mesh = Mesh(np.asarray(topo.devices).reshape(2, 4), ("dcn", "ici"))
    comm = XlaCommunicator(mesh=mesh)
    model = Big()
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 28, 28), jnp.float32))["params"])
    opt = optax.sgd(0.1)
    mnopt = chainermn_tpu.create_multi_node_optimizer(opt, comm)
    state = (params, jax.eval_shape(opt.init, params))
    step = make_data_parallel_train_step(model, mnopt, comm, donate=False)

    dsh = NamedSharding(mesh, P(("dcn", "ici")))
    rep = NamedSharding(mesh, P())
    x = jax.ShapeDtypeStruct((64, 28, 28), jnp.float32, sharding=dsh)
    y = jax.ShapeDtypeStruct((64,), jnp.int32, sharding=dsh)
    state = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=rep),
        state)

    opts = {
        "xla_tpu_enable_latency_hiding_scheduler": "true",
        "xla_enable_async_all_reduce": "true",
    }
    out = analyze(jax.jit(lambda s, x, y: step(s, x, y)).lower(
        state, x, y).compile(opts))

    # second configuration: the EXPLICITLY bucketed allreduce_grad (the
    # hierarchical communicator's DCN path — one psum per plan_buckets
    # bucket in the jaxpr), asserting the compiler schedules those
    # bucket collectives into the backward window too
    from jax import shard_map

    bcomm = XlaCommunicator(mesh=mesh, dcn_bucket_bytes=16 * 2 ** 20)

    def local_step(p, xb, yb):
        def loss(p):
            logits = model.apply({"params": p}, xb)
            one = jax.nn.one_hot(yb, 10)
            return jnp.mean((logits - one) ** 2)

        l, g = jax.value_and_grad(loss)(p)
        g = bcomm.allreduce_grad(g, "mean")
        newp = jax.tree_util.tree_map(
            lambda a, b: a - 0.1 * b, p, g)
        return jax.lax.pmean(l, ("dcn", "ici")), newp

    sm = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(("dcn", "ici")), P(("dcn", "ici"))),
        out_specs=(P(), P()))
    pab = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=rep),
        params)
    out2 = analyze(jax.jit(sm).lower(pab, x, y).compile(opts))
    out["bucketed_allreduce_grad"] = out2
    out["ok"] = bool(out["ok"] and out2["ok"])

    # third configuration: the 1F1B PIPELINE schedule (VERDICT r4 #5).
    # The pipeline compiles to ONE while loop whose body is the schedule
    # tick: stage compute, then the fwd/bwd wire ppermutes. The claim to
    # certify is that the WIRE HOP OVERLAPS TICK COMPUTE — XLA lowers
    # the ppermutes to async collective-permute-start/done pairs and
    # schedules real fusions between start and done, so the per-tick
    # wire cost (docs/scaling_model.md §6) is hidden behind compute
    # rather than added to it. The pass scans every computation and
    # scores the while-BODY (the entry schedule only shows the while op).
    out["pipeline_1f1b"] = check_pipeline_permute_overlap(
        _compile_pipeline_1f1b(mesh).as_text())
    out["ok"] = bool(out["ok"] and out["pipeline_1f1b"]["ok"])
    if min_overlap is not None:
        # gate on the WORST DP configuration's DL201 overlap fraction
        fracs = [out.get("overlap_fraction", 0.0),
                 out["bucketed_allreduce_grad"].get(
                     "overlap_fraction", 0.0)]
        out["min_overlap_fraction"] = min(fracs)
        out["assert_min_overlap"] = min_overlap
        out["overlap_gate_ok"] = out["min_overlap_fraction"] >= min_overlap
        out["ok"] = bool(out["ok"] and out["overlap_gate_ok"])
    print(json.dumps(out))
    if min_overlap is not None and not out["overlap_gate_ok"]:
        sys.exit(1)


def _compile_pipeline_1f1b(mesh):
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from chainermn_tpu.parallel import (
        pipeline_1f1b_value_and_grad,
        stack_stage_params,
    )

    devs = mesh.devices.reshape(-1)
    smesh = jax.sharding.Mesh(devs, ("stage",))
    S = devs.size
    feat, M = 512, 2 * S  # big stage matmul; M ≥ 2S keeps bubbles small

    plist = [{"w": np.eye(feat, dtype=np.float32)} for _ in range(S)]
    xs = np.ones((M, 4, feat), np.float32)
    tgt = np.zeros((M, 4, feat), np.float32)

    def pp_run(stacked, xs, tgt):
        my = jax.tree_util.tree_map(lambda l: l[0], stacked)
        loss, grads = pipeline_1f1b_value_and_grad(
            lambda p, h: jnp.tanh(h @ p["w"]),
            lambda o, t: jnp.mean((o - t) ** 2),
            my, xs, tgt, axis_name="stage")
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    sm = shard_map(pp_run, mesh=smesh,
                   in_specs=(P("stage"), P(), P()),
                   out_specs=(P(), P("stage")))

    def absify(l, spec):
        return jax.ShapeDtypeStruct(
            np.shape(l), jnp.asarray(l).dtype,
            sharding=NamedSharding(smesh, spec))

    return jax.jit(sm).lower(
        jax.tree_util.tree_map(lambda l: absify(l, P("stage")),
                               stack_stage_params(plist)),
        absify(xs, P()), absify(tgt, P())).compile(
            {"xla_tpu_enable_latency_hiding_scheduler": "true"})


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""dlint — distributed-correctness lint for the whole stack.

Runs the :mod:`chainermn_tpu.analysis` source passes — the per-file
AST rules (DL101–DL112, DL117) and the whole-program project rules
(DL113–DL116 call-graph sequence/lock checks, DL118–DL122 value-level
dataflow checks) — and prints one ``path:line: RULE message`` finding
per line. Exit status: 0 clean, 1 findings, 2 usage error.

Usage::

    python tools/dlint.py --all                 # lint the whole repo
    python tools/dlint.py chainermn_tpu/comm    # lint specific paths
    python tools/dlint.py --rules DL101,DL113 tests/
    python tools/dlint.py --list-rules          # catalogue + docs anchors
    python tools/dlint.py --all --format sarif  # SARIF 2.1.0 to stdout
    python tools/dlint.py --all --baseline tools/dlint_baseline.json
    python tools/dlint.py --all --write-baseline tools/dlint_baseline.json
    python tools/dlint.py --changed             # only files in the git diff
    python tools/dlint.py --all --report-suppressions
    python tools/dlint.py --all --timings dlint_timings.json

``--timings`` records per-pass wall time; the suite compares a full
``--all`` run against the budget in ``tools/dlint_budget.json`` so a
new pass cannot silently eat the tier-1 verify window.

``--baseline`` gates on NEW findings only: anything fingerprinted in
the baseline file passes (the ratchet — old debt burns down
explicitly, new debt is blocked). ``--changed [REF]`` lints only files
changed vs REF (default HEAD, staged+unstaged) while the whole-program
passes still analyze every root for call-graph context.

The compiled-HLO passes (DL2xx) take HLO text, not source files — run
them via :mod:`chainermn_tpu.analysis.hlo_passes` on a compiled
computation (see ``tools/check_overlap_schedule.py``) or point
``--hlo FILE`` at a saved ``compiled.as_text()`` dump to run the
argument-free ones (DL201, DL203).

Suppress an intentional finding with ``# dlint: disable=RULE`` (plus a
rationale) on the flagged line, the line above, or the first line of
the enclosing statement. ``--report-suppressions`` lists suppressions
that absorbed zero findings so dead ones get removed as rules evolve.
The suite keeps the repo clean via
tests/analysis_tests/test_repo_clean.py.
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

#: what --all means: every Python tree that ships or exercises
#: distributed behavior
REPO_ROOTS = ("chainermn_tpu", "examples", "tests", "tools")


def _changed_files(repo: str, ref: str):
    """Python files changed vs ``ref`` (committed, staged, and
    unstaged), absolute paths, existing files only."""
    out = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        cwd=repo, capture_output=True, text=True, check=True).stdout
    files = []
    for line in out.splitlines():
        line = line.strip()
        if not line.endswith(".py"):
            continue
        path = os.path.join(repo, line)
        if os.path.isfile(path):
            files.append(path)
    return sorted(set(files))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--all", action="store_true",
                    help="lint the standard repo roots: "
                         + ", ".join(REPO_ROOTS))
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--format", dest="fmt", default="text",
                    choices=("text", "sarif"),
                    help="finding output format (default: text)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="gate only on findings NOT fingerprinted in "
                         "this baseline file")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="record the run's findings as the new "
                         "baseline and exit 0")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="report only files changed vs REF (default "
                         "HEAD); whole-program passes still see every "
                         "repo root")
    ap.add_argument("--report-suppressions", action="store_true",
                    help="list '# dlint: disable' comments that "
                         "suppressed zero findings (exit 1 if any)")
    ap.add_argument("--timings", metavar="FILE", default=None,
                    help="write per-pass wall-time JSON to FILE "
                         "('-' for stderr) — CI compares the total "
                         "against tools/dlint_budget.json")
    ap.add_argument("--hlo", metavar="FILE", default=None,
                    help="also run argument-free HLO passes on a saved "
                         "compiled.as_text() dump")
    args = ap.parse_args(argv)

    from chainermn_tpu.analysis import (
        RULES,
        filter_new,
        load_baseline,
        run_lint,
        to_sarif,
        write_baseline,
    )
    from chainermn_tpu.analysis import hlo_passes

    if args.list_rules:
        for rule in sorted(RULES.values(), key=lambda r: r.rule_id):
            print(f"{rule.rule_id}  [{rule.kind}]  {rule.name}  "
                  f"({rule.doc})")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"dlint: unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    only = None
    if args.changed is not None:
        try:
            only = _changed_files(repo, args.changed)
        except subprocess.CalledProcessError as e:
            print(f"dlint: git diff failed: {e.stderr.strip()}",
                  file=sys.stderr)
            return 2
        # whole-program context needs every root regardless of the diff
        paths = [os.path.join(repo, r) for r in REPO_ROOTS
                 if os.path.isdir(os.path.join(repo, r))]
    elif args.all:
        paths = [os.path.join(repo, r) for r in REPO_ROOTS
                 if os.path.isdir(os.path.join(repo, r))]
    else:
        paths = args.paths
    if not paths and not args.hlo:
        ap.print_usage(sys.stderr)
        print("dlint: give paths, --all, --changed, or --hlo FILE",
              file=sys.stderr)
        return 2

    import time as _time
    t_run = _time.perf_counter()
    run = run_lint(paths, rules=rules, only=only) if paths else None
    t_run = _time.perf_counter() - t_run
    findings = run.findings if run is not None else []

    if args.timings and run is not None:
        payload = {
            "total_seconds": round(t_run, 3),
            "passes": {k: round(v, 4)
                       for k, v in sorted(run.timings.items())},
        }
        text = json.dumps(payload, indent=2, sort_keys=True) + "\n"
        if args.timings == "-":
            sys.stderr.write(text)
        else:
            with open(args.timings, "w", encoding="utf-8") as fh:
                fh.write(text)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings, root=repo)
        print(f"dlint: baseline written to {args.write_baseline} "
              f"({len(findings)} finding(s))", file=sys.stderr)
        return 0

    gated = findings
    if args.baseline:
        try:
            known = load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"dlint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        gated = filter_new(findings, known, root=repo)

    if args.fmt == "sarif":
        sups = run.suppressions if run is not None else None
        print(json.dumps(to_sarif(gated, root=repo, suppressions=sups),
                         indent=2, sort_keys=True))
    else:
        for f in gated:
            print(f.format())

    dead = run.dead_suppressions if run is not None else []
    if args.report_suppressions:
        for s in dead:
            print(f"dead suppression: {s.format()}")
        if not dead:
            print("dlint: no dead suppressions", file=sys.stderr)

    hlo_bad = 0
    if args.hlo:
        with open(args.hlo, encoding="utf-8") as fh:
            txt = fh.read()
        for check in (hlo_passes.check_dp_overlap,
                      hlo_passes.check_pipeline_permute_overlap,
                      hlo_passes.check_quantized_wire_dtype):
            out = check(txt)
            if rules is not None and out["rule"] not in rules:
                continue
            print(json.dumps(out))
            if out["ok"] is False:
                hlo_bad += 1

    n = len(gated) + hlo_bad
    if args.report_suppressions:
        n += len(dead)
    if n:
        print(f"dlint: {n} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

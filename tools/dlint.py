#!/usr/bin/env python
"""dlint — distributed-correctness lint for the whole stack.

Runs the :mod:`chainermn_tpu.analysis` AST passes (DL1xx) over Python
sources and prints one ``path:line: RULE message`` finding per line.
Exit status: 0 clean, 1 findings, 2 usage error.

Usage::

    python tools/dlint.py --all                 # lint the whole repo
    python tools/dlint.py chainermn_tpu/comm    # lint specific paths
    python tools/dlint.py --rules DL101,DL103 tests/
    python tools/dlint.py --list-rules          # catalogue + docs anchors

The compiled-HLO passes (DL2xx) take HLO text, not source files — run
them via :mod:`chainermn_tpu.analysis.hlo_passes` on a compiled
computation (see ``tools/check_overlap_schedule.py``) or point
``--hlo FILE`` at a saved ``compiled.as_text()`` dump to run the
argument-free ones (DL201, DL203).

Suppress an intentional finding with ``# dlint: disable=RULE`` (plus a
rationale) on the flagged line or the line above. The suite keeps the
repo clean via tests/analysis_tests/test_repo_clean.py.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

#: what --all means: every Python tree that ships or exercises
#: distributed behavior
REPO_ROOTS = ("chainermn_tpu", "examples", "tests", "tools")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--all", action="store_true",
                    help="lint the standard repo roots: "
                         + ", ".join(REPO_ROOTS))
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--hlo", metavar="FILE", default=None,
                    help="also run argument-free HLO passes on a saved "
                         "compiled.as_text() dump")
    args = ap.parse_args(argv)

    from chainermn_tpu.analysis import RULES, lint_paths
    from chainermn_tpu.analysis import hlo_passes

    if args.list_rules:
        for rule in sorted(RULES.values(), key=lambda r: r.rule_id):
            print(f"{rule.rule_id}  [{rule.kind}]  {rule.name}  "
                  f"({rule.doc})")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"dlint: unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.all:
        paths = [os.path.join(repo, r) for r in REPO_ROOTS
                 if os.path.isdir(os.path.join(repo, r))]
    else:
        paths = args.paths
    if not paths and not args.hlo:
        ap.print_usage(sys.stderr)
        print("dlint: give paths, --all, or --hlo FILE", file=sys.stderr)
        return 2

    findings = lint_paths(paths, rules=rules) if paths else []
    for f in findings:
        print(f.format())

    hlo_bad = 0
    if args.hlo:
        with open(args.hlo, encoding="utf-8") as fh:
            txt = fh.read()
        for check in (hlo_passes.check_dp_overlap,
                      hlo_passes.check_pipeline_permute_overlap,
                      hlo_passes.check_quantized_wire_dtype):
            out = check(txt)
            if rules is not None and out["rule"] not in rules:
                continue
            print(json.dumps(out))
            if out["ok"] is False:
                hlo_bad += 1

    n = len(findings) + hlo_bad
    if n:
        print(f"dlint: {n} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Perf sweep for the ResNet-50 bench: batch size, scan-amortized dispatch,
space-to-depth stem, gradient-reduction strategy. Prints one JSON line
per variant.

Usage: python tools/bench_sweep.py BATCH N_SCAN S2D
                                   [--grad-reducer=flat,hierarchical,...]
                                   [--wire-format=f32,bf16,int8-block,...]
                                   [--tune[=DB_PATH]]
  --grad-reducer sweeps collectives/ strategies; each line carries the
  strategy's per-step payload and wire bytes from the reducer's bucket
  plan. Off TPU the throughput deltas are an honest null (BASELINE.md);
  the byte accounting is exact everywhere.
  --wire-format sweeps the quantized wire formats
  (docs/collectives.md#quantized-wire-formats; narrow formats default
  the strategy to 'quantized'); each line carries exact wire bytes and
  the wire/payload compression ratio.
  --tune builds the optimizer from the schedtune profile DB
  (docs/tuning.md; run tools/schedtune.py first) and adds the plan's
  tuning/overlap_frac + tuning/bucket_bytes keys to the JSON line."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def run_variant(batch, n_scan, s2d, n_iters=10, grad_reducer=None,
                tune=None, wire_format=None):
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import chainermn_tpu
    from chainermn_tpu.models.resnet import ResNet50
    from chainermn_tpu.training.step import make_data_parallel_train_step

    comm = chainermn_tpu.create_communicator("xla")
    n_dev = comm.size
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     space_to_depth=s2d)
    image = np.zeros((2, 224, 224, 3), np.float32)
    mutable = ("batch_stats",)

    global_batch = batch * n_dev
    variables = model.init(jax.random.PRNGKey(0), image)
    params = comm.bcast_data(variables["params"])
    extra = {k: comm.bcast_data(variables[k]) for k in mutable}
    reducer = None
    wf = None if wire_format in (None, "f32") else wire_format
    if grad_reducer or wf:
        from chainermn_tpu.collectives import make_grad_reducer

        # a narrow wire with no explicit strategy means 'quantized'
        reducer = make_grad_reducer(grad_reducer or "quantized", comm,
                                    wire_format=wf)
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm, grad_reducer=reducer,
        tune=tune)
    plan = getattr(opt, "plan", None)
    if plan is not None and reducer is None:
        reducer = opt.grad_reducer  # the plan-built reducer
    state = (params, opt.init(params), extra)
    step = make_data_parallel_train_step(model, opt, comm, mutable=mutable)

    x = np.random.RandomState(0).rand(
        global_batch, 224, 224, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(
        0, 1000, size=(global_batch,)).astype(np.int32)
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    x = jax.device_put(x, dsh)
    y = jax.device_put(y, dsh)

    if n_scan > 1:
        base = step

        def multi(state, x, y):
            def body(s, _):
                s, m = base(s, x, y)
                return s, m
            return lax.scan(body, state, None, length=n_scan)
        multi = jax.jit(multi, donate_argnums=(0,))
        for _ in range(3):  # compile + the tunnel's deferred one-time cost
            state, m = multi(state, x, y)
            float(jax.tree_util.tree_leaves(m)[0][-1])
        t0 = time.perf_counter()
        reps = max(1, n_iters // n_scan)
        for _ in range(reps):
            state, m = multi(state, x, y)
        float(jax.tree_util.tree_leaves(m)[0][-1])
        dt = time.perf_counter() - t0
        total = reps * n_scan * global_batch
    else:
        for _ in range(3):  # compile + the tunnel's deferred one-time cost
            state, m = step(state, x, y)
            float(m["main/loss"])
        t0 = time.perf_counter()
        for _ in range(n_iters):
            # timed region: sync once at the end — device-throughput
            # methodology, same as bench_lm.py
            state, m = step(state, x, y)  # dlint: disable=DL104
        float(m["main/loss"])
        dt = time.perf_counter() - t0
        total = n_iters * global_batch

    per_chip = total / dt / n_dev
    line = {
        "batch": batch, "scan": n_scan, "s2d": s2d,
        "images_per_sec_per_chip": round(per_chip, 1),
    }
    if reducer is not None:
        rows = reducer.plan(params)
        payload = sum(r["bytes"] for r in rows)
        wire = sum(r["wire_bytes"] for r in rows)
        line["grad_reducer"] = reducer.name
        line["comm_bytes_per_step"] = payload
        line["comm_wire_bytes_per_step"] = wire
        line["comm_wire_compression"] = round(
            wire / payload, 6) if payload else 1.0
    if wire_format is not None:
        line["wire_format"] = wire_format
    if plan is not None:
        line["tuning/overlap_frac"] = plan.overlap_fraction
        line["tuning/bucket_bytes"] = plan.bucket_bytes
        line["tuning/strategy"] = plan.strategy
    print(json.dumps(line), flush=True)


if __name__ == "__main__":
    argv = sys.argv[1:]
    reducers = [None]
    for a in list(argv):
        if a.startswith("--grad-reducer"):
            reducers = a.split("=", 1)[1].split(",")
            argv.remove(a)
    wire_formats = [None]
    for a in list(argv):
        if a.startswith("--wire-format"):
            wire_formats = a.split("=", 1)[1].split(",")
            argv.remove(a)
    tune = None
    for a in list(argv):
        if a.startswith("--tune"):
            tune = a.split("=", 1)[1] if "=" in a else True
            argv.remove(a)
    batch = int(argv[0])
    n_scan = int(argv[1])
    s2d = argv[2] == "1"
    for gr in reducers:
        for wfmt in wire_formats:
            run_variant(batch, n_scan, s2d, grad_reducer=gr, tune=tune,
                        wire_format=wfmt)

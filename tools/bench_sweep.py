#!/usr/bin/env python
"""Perf sweep for the ResNet-50 bench: batch size, scan-amortized dispatch,
space-to-depth stem. Prints one JSON line per variant."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def run_variant(batch, n_scan, s2d, n_iters=10):
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import chainermn_tpu
    from chainermn_tpu.models.resnet import ResNet50
    from chainermn_tpu.training.step import make_data_parallel_train_step

    comm = chainermn_tpu.create_communicator("xla")
    n_dev = comm.size
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     space_to_depth=s2d)
    image = np.zeros((2, 224, 224, 3), np.float32)
    mutable = ("batch_stats",)

    global_batch = batch * n_dev
    variables = model.init(jax.random.PRNGKey(0), image)
    params = comm.bcast_data(variables["params"])
    extra = {k: comm.bcast_data(variables[k]) for k in mutable}
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm)
    state = (params, opt.init(params), extra)
    step = make_data_parallel_train_step(model, opt, comm, mutable=mutable)

    x = np.random.RandomState(0).rand(
        global_batch, 224, 224, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(
        0, 1000, size=(global_batch,)).astype(np.int32)
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    x = jax.device_put(x, dsh)
    y = jax.device_put(y, dsh)

    if n_scan > 1:
        base = step

        def multi(state, x, y):
            def body(s, _):
                s, m = base(s, x, y)
                return s, m
            return lax.scan(body, state, None, length=n_scan)
        multi = jax.jit(multi, donate_argnums=(0,))
        for _ in range(3):  # compile + the tunnel's deferred one-time cost
            state, m = multi(state, x, y)
            float(jax.tree_util.tree_leaves(m)[0][-1])
        t0 = time.perf_counter()
        reps = max(1, n_iters // n_scan)
        for _ in range(reps):
            state, m = multi(state, x, y)
        float(jax.tree_util.tree_leaves(m)[0][-1])
        dt = time.perf_counter() - t0
        total = reps * n_scan * global_batch
    else:
        for _ in range(3):  # compile + the tunnel's deferred one-time cost
            state, m = step(state, x, y)
            float(m["main/loss"])
        t0 = time.perf_counter()
        for _ in range(n_iters):
            # timed region: sync once at the end — device-throughput
            # methodology, same as bench_lm.py
            state, m = step(state, x, y)  # dlint: disable=DL104
        float(m["main/loss"])
        dt = time.perf_counter() - t0
        total = n_iters * global_batch

    per_chip = total / dt / n_dev
    print(json.dumps({
        "batch": batch, "scan": n_scan, "s2d": s2d,
        "images_per_sec_per_chip": round(per_chip, 1),
    }), flush=True)


if __name__ == "__main__":
    batch = int(sys.argv[1])
    n_scan = int(sys.argv[2])
    s2d = sys.argv[3] == "1"
    run_variant(batch, n_scan, s2d)

#!/usr/bin/env python
"""bench_serve — continuous-batching serving benchmark + recompile proof.

Two parts, one JSON line on stdout:

1. **Cached vs full-recompute head-to-head** (the DL108 proof). The
   same greedy decode runs twice: through the paged KV cache
   (``serving/kv_cache.py`` — fixed shapes, ONE compiled decode
   program) and as the naive full-forward recompute whose input grows
   every token. Trace counters incremented at trace time count actual
   compiles; the bench **asserts** ``cached_traces == 1`` and
   ``recompute_traces == n_new_tokens`` — the structural claim that
   holds on every backend, independent of wall-clock noise — and exits
   non-zero if either fails.
2. **Offered-load sweep**. Poisson-less open-loop arrivals at each
   offered rate drive a real Engine; the ServingReport yields TTFT
   p50/p99, per-token latency, tokens/s, queue depth, and occupancy
   per load point.

Honest null: on a CPU mesh the latency/throughput numbers measure the
XLA CPU backend, not a TPU — they are real wall-clock but not
representative, and the JSON says so (``"honest_null": true``). The
trace-count assertion is platform-independent and is the part tier-1
consumes (tests/serving_tests/test_engine.py pins the same invariant).

    python tools/bench_serve.py --loads 2,8,32 --requests 16
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _model(args):
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                          n_heads=args.n_heads, n_layers=args.n_layers,
                          d_ff=2 * args.d_model, max_len=args.capacity,
                          attention="reference", pos_emb="rope")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def measure_recompute(model, params, prompt, n_new):
    """The naive decode: full forward over a sequence that grows by one
    token per step — shape-polymorphic dispatch compiles once per
    length. The trace counter bumps at trace time only."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    traces = [0]

    def fwd(p, t):
        traces[0] += 1
        return model.apply({"params": p}, t)[:, -1]

    step = jax.jit(fwd)
    toks = jnp.asarray(prompt)
    t0 = time.perf_counter()
    for _ in range(n_new):
        logits = step(params, toks)
        nxt = jnp.argmax(logits, axis=-1)[:, None]
        np.asarray(nxt)                 # per-iteration sync
        toks = jnp.concatenate([toks, nxt.astype(jnp.int32)], axis=1)
    wall = time.perf_counter() - t0
    return {"traces": traces[0], "wall_s": round(wall, 4),
            "tokens_per_s": round(n_new / wall, 2),
            "tokens": np.asarray(toks)[0, prompt.shape[1]:].tolist()}


def measure_cached(model, params, prompt, n_new, capacity):
    """The same decode through the paged KV cache: every step sees the
    same shapes, so the decode program compiles exactly once."""
    import numpy as np

    from chainermn_tpu.serving.kv_cache import ServingStep

    steps = ServingStep(model, params, n_slots=1, capacity=capacity)
    lengths = np.full((1,), prompt.shape[1], np.int32)
    slot_ids = np.zeros((1,), np.int32)
    t0 = time.perf_counter()
    logits = np.asarray(steps.prefill(np.asarray(prompt, np.int32),
                                      lengths, slot_ids))
    out = [int(np.argmax(logits[0]))]
    cur = np.asarray(out, np.int32)
    for _ in range(n_new - 1):
        logits = np.asarray(steps.decode(cur))
        out.append(int(np.argmax(logits[0])))
        cur = np.asarray(out[-1:], np.int32)
    wall = time.perf_counter() - t0
    return {"traces": steps.decode_traces,
            "prefill_traces": sum(steps.prefill_traces.values()),
            "wall_s": round(wall, 4),
            "tokens_per_s": round(n_new / wall, 2),
            "tokens": out}


def sweep_point(model, params, offered_rps, args):
    """Open-loop arrivals at ``offered_rps`` requests/s against a real
    Engine; returns the ServingReport summary for the load point."""
    import numpy as np

    from chainermn_tpu.serving import Engine, EngineConfig, ServingReport

    rep = ServingReport()
    eng = Engine(model, params,
                 EngineConfig(n_slots=args.slots, capacity=args.capacity,
                              max_new_tokens=args.max_new_tokens,
                              prefill_cohort=1,
                              buckets=[args.prompt_len, args.capacity]),
                 report=rep)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, args.vocab, (args.prompt_len,))
               .astype(np.int32) for _ in range(args.requests)]
    t0 = time.monotonic()
    arrivals = [i / offered_rps for i in range(args.requests)]
    i = 0
    while i < len(prompts) or not eng.idle():
        now = time.monotonic() - t0
        while i < len(prompts) and arrivals[i] <= now:
            eng.submit(prompts[i])
            i += 1
        if eng.idle():
            time.sleep(min(0.001, max(0.0, arrivals[i] - now)))
            continue
        eng.step()  # dlint: disable=DL104 — syncs via np.asarray
    s = rep.summary()
    return {
        "offered_rps": offered_rps,
        "tokens_per_s": round(s["tokens_per_s"], 2),
        "ttft_ms_p50": round(s["ttft_ms"]["p50"], 3),
        "ttft_ms_p99": round(s["ttft_ms"]["p99"], 3),
        "token_ms_p50": round(s["token_latency_ms"]["p50"], 3),
        "token_ms_p99": round(s["token_latency_ms"]["p99"], 3),
        "queue_depth_max": s["queue_depth"]["max"],
        "occupancy_mean": round(s["slot_occupancy"]["mean"], 3),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="bench_serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--loads", default="2,8,32",
                    help="offered loads to sweep, requests/s (CSV)")
    ap.add_argument("--requests", type=int, default=12,
                    help="requests per load point")
    ap.add_argument("--new-tokens", type=int, default=24,
                    help="decode length for the head-to-head")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--skip-sweep", action="store_true")
    args = ap.parse_args(argv)

    import numpy as np

    import jax

    model, params = _model(args)
    backend = jax.default_backend()
    prompt = np.arange(1, 1 + args.prompt_len,
                       dtype=np.int32)[None] % args.vocab

    cached = measure_cached(model, params, prompt, args.new_tokens,
                            args.capacity)
    recompute = measure_recompute(model, params, prompt, args.new_tokens)

    # the structural proof: identical greedy streams, one compile vs
    # one compile PER LENGTH
    ok = (cached["tokens"] == recompute["tokens"]
          and cached["traces"] == 1
          and recompute["traces"] == args.new_tokens)
    record = {
        "metric": "serving_decode",
        "platform": backend,
        "honest_null": backend != "tpu",
        "n_new_tokens": args.new_tokens,
        "cached": cached,
        "recompute": recompute,
        "compile_ratio": recompute["traces"] / cached["traces"],
        "streams_identical": cached["tokens"] == recompute["tokens"],
        "trace_assertion_ok": ok,
    }
    if not args.skip_sweep:
        record["sweep"] = [
            sweep_point(model, params, float(l), args)
            for l in args.loads.split(",") if l.strip()]
    print(json.dumps(record))
    if not ok:
        print("bench_serve: trace-count assertion FAILED "
              f"(cached={cached['traces']}, "
              f"recompute={recompute['traces']})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""bench_serve — continuous-batching serving benchmark + recompile proof.

Two parts, one JSON line on stdout:

1. **Cached vs full-recompute head-to-head** (the DL108 proof). The
   same greedy decode runs FOUR ways: through the paged KV cache
   (``serving/kv_cache.py`` — fixed shapes, ONE compiled decode
   program), as the naive full-forward recompute whose input grows
   every token, through the multi-token ``decode_k`` program
   (on-device sampling, k tokens per dispatch), and through the
   speculative engine (``serving/speculative.py`` — a seeded
   ``--draft-layers`` draft proposing ``--spec-k`` tokens per target
   verify dispatch). Trace counters incremented at trace time count
   actual compiles; the bench **asserts** ``cached_traces == 1``,
   ``recompute_traces == n_new_tokens``, ``decode_k_traces == 1``,
   one propose + one verify trace with the speculative stream
   bitwise-identical, a self-draft control accepting every proposal
   (``spec_k + 1`` tokens per dispatch — the acceptance machinery's
   structural ceiling), identical greedy streams, and ≤ 8 device→host
   bytes per decoded token (DL110's observable) — the structural
   claims that hold on every backend, independent of wall-clock noise
   — and exits non-zero if any fails.
2. **Offered-load sweep**. Poisson-less open-loop arrivals at each
   offered rate drive a real Engine; the ServingReport yields TTFT
   p50/p99, per-token latency, tokens/s, queue depth, and occupancy
   per load point.

Honest null: on a CPU mesh the latency/throughput numbers measure the
XLA CPU backend, not a TPU — they are real wall-clock but not
representative, and the JSON says so (``"honest_null": true``). The
trace-count assertion is platform-independent and is the part tier-1
consumes (tests/serving_tests/test_engine.py pins the same invariant).

    python tools/bench_serve.py --loads 2,8,32 --requests 16
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _model(args):
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                          n_heads=args.n_heads, n_layers=args.n_layers,
                          d_ff=2 * args.d_model, max_len=args.capacity,
                          attention="reference", pos_emb="rope")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def measure_recompute(model, params, prompt, n_new):
    """The naive decode: full forward over a sequence that grows by one
    token per step — shape-polymorphic dispatch compiles once per
    length. The trace counter bumps at trace time only."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    traces = [0]

    def fwd(p, t):
        traces[0] += 1
        return model.apply({"params": p}, t)[:, -1]

    step = jax.jit(fwd)
    toks = jnp.asarray(prompt)
    t0 = time.perf_counter()
    for _ in range(n_new):
        logits = step(params, toks)
        nxt = jnp.argmax(logits, axis=-1)[:, None]
        np.asarray(nxt)                 # per-iteration sync
        toks = jnp.concatenate([toks, nxt.astype(jnp.int32)], axis=1)
    wall = time.perf_counter() - t0
    return {"traces": traces[0], "wall_s": round(wall, 4),
            "tokens_per_s": round(n_new / wall, 2),
            "tokens": np.asarray(toks)[0, prompt.shape[1]:].tolist()}


def measure_cached(model, params, prompt, n_new, capacity):
    """The same decode through the paged KV cache: every step sees the
    same shapes, so the decode program compiles exactly once. The argmax
    runs ON DEVICE — the per-step host pull is one int32 id, not the
    [1, vocab] logits row (the DL110 discipline)."""
    import numpy as np

    import jax.numpy as jnp

    from chainermn_tpu.serving.kv_cache import ServingStep

    steps = ServingStep(model, params, n_slots=1, capacity=capacity)
    lengths = np.full((1,), prompt.shape[1], np.int32)
    slot_ids = np.zeros((1,), np.int32)
    t0 = time.perf_counter()
    logits = np.asarray(steps.prefill(np.asarray(prompt, np.int32),
                                      lengths, slot_ids))
    out = [int(np.argmax(logits[0]))]
    cur = np.asarray(out, np.int32)
    for _ in range(n_new - 1):
        cur = np.asarray(jnp.argmax(steps.decode(cur), -1), np.int32)
        out.append(int(cur[0]))
    wall = time.perf_counter() - t0
    return {"traces": steps.decode_traces,
            "prefill_traces": sum(steps.prefill_traces.values()),
            "wall_s": round(wall, 4),
            "tokens_per_s": round(n_new / wall, 2),
            "tokens": out}


def measure_decode_k(model, params, prompt, n_new, capacity, k=4):
    """The multi-token program end to end: a 1-slot Engine drives
    ``decode_k`` dispatches (sampling on device, k tokens committed per
    host round trip) and the ServingReport counts the actual device→host
    bytes on the emit path. The structural claims: ONE decode_k trace
    (DL108 extended) and ≤ 8 host bytes/token (the DL110 observable —
    the full-logits pull this replaces moved vocab × 4)."""
    from chainermn_tpu.serving import Engine, EngineConfig

    eng = Engine(model, params,
                 EngineConfig(n_slots=1, capacity=capacity,
                              max_new_tokens=n_new, prefill_cohort=1,
                              buckets=[prompt.shape[1], capacity],
                              decode_k=k))
    t0 = time.perf_counter()
    req = eng.submit(prompt[0])
    eng.run_until_drained()
    wall = time.perf_counter() - t0
    s = eng.report.summary()
    return {"decode_k": k,
            "traces": eng.steps.decode_k_traces,
            "wall_s": round(wall, 4),
            "tokens_per_s": round(n_new / wall, 2),
            "host_bytes_per_token": round(s["host_bytes_per_token"], 2),
            "tokens": req.tokens}


def measure_speculative(model, params, draft, draft_params, prompt,
                        n_new, capacity, spec_k):
    """One speculative decode end to end: a 1-slot SpeculativeEngine
    drives draft-propose/target-verify rounds. Called twice from
    ``main``: once with a small seeded draft (the honest configuration
    — a random draft accepts ~0 proposals, so acceptance there is data,
    not a gate) and once SELF-DRAFTED (draft == target) where the
    acceptance machinery must structurally yield acceptance 1.0 and
    ``spec_k + 1`` tokens per dispatch. The trace claims hold in both:
    ONE propose trace + ONE verify trace (DL108 over both programs) and
    the greedy stream bitwise-equal to the plain cached decode. On a
    CPU mesh the draft is not actually cheaper per-FLOP, so wall-clock
    speedup is an honest null — acceptance_rate and tokens_per_dispatch
    are the platform-independent part."""
    from chainermn_tpu.serving import (EngineConfig, ServingReport,
                                       SpeculativeEngine)

    eng = SpeculativeEngine(
        model, params, draft, draft_params,
        EngineConfig(n_slots=1, capacity=capacity,
                     max_new_tokens=n_new, prefill_cohort=1,
                     buckets=[prompt.shape[1], capacity]),
        spec_k=spec_k, report=ServingReport())
    t0 = time.perf_counter()
    req = eng.submit(prompt[0])
    eng.run_until_drained()
    wall = time.perf_counter() - t0
    s = eng.report.summary()
    return {"spec_k": spec_k,
            "draft_layers": draft.n_layers,
            "n_new_tokens": n_new,
            "propose_traces": eng.draft.propose_traces,
            "verify_traces": eng.verify_traces,
            "wall_s": round(wall, 4),
            "tokens_per_s": round(n_new / wall, 2),
            "acceptance_rate": round(s["acceptance_rate"], 4),
            "tokens_per_dispatch": round(s["tokens_per_dispatch"], 4),
            "tokens": req.tokens}


def sweep_point(model, params, offered_rps, args):
    """Open-loop arrivals at ``offered_rps`` requests/s against a real
    Engine; returns the ServingReport summary for the load point."""
    import numpy as np

    from chainermn_tpu.serving import Engine, EngineConfig, ServingReport

    rep = ServingReport()
    eng = Engine(model, params,
                 EngineConfig(n_slots=args.slots, capacity=args.capacity,
                              max_new_tokens=args.max_new_tokens,
                              prefill_cohort=1,
                              buckets=[args.prompt_len, args.capacity]),
                 report=rep)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, args.vocab, (args.prompt_len,))
               .astype(np.int32) for _ in range(args.requests)]
    t0 = time.monotonic()
    arrivals = [i / offered_rps for i in range(args.requests)]
    i = 0
    while i < len(prompts) or not eng.idle():
        now = time.monotonic() - t0
        while i < len(prompts) and arrivals[i] <= now:
            eng.submit(prompts[i])
            i += 1
        if eng.idle():
            time.sleep(min(0.001, max(0.0, arrivals[i] - now)))
            continue
        eng.step()  # dlint: disable=DL104 — syncs via np.asarray
    s = rep.summary()
    return {
        "offered_rps": offered_rps,
        "tokens_per_s": round(s["tokens_per_s"], 2),
        "ttft_ms_p50": round(s["ttft_ms"]["p50"], 3),
        "ttft_ms_p99": round(s["ttft_ms"]["p99"], 3),
        "itl_ms_p50": round(s["itl_ms"]["p50"], 3),
        "itl_ms_p99": round(s["itl_ms"]["p99"], 3),
        "token_ms_p50": round(s["token_latency_ms"]["p50"], 3),
        "token_ms_p99": round(s["token_latency_ms"]["p99"], 3),
        "host_bytes_per_token": round(s["host_bytes_per_token"], 2),
        "queue_depth_max": s["queue_depth"]["max"],
        "occupancy_mean": round(s["slot_occupancy"]["mean"], 3),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="bench_serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--loads", default="2,8,32",
                    help="offered loads to sweep, requests/s (CSV)")
    ap.add_argument("--requests", type=int, default=12,
                    help="requests per load point")
    ap.add_argument("--new-tokens", type=int, default=24,
                    help="decode length for the head-to-head")
    ap.add_argument("--decode-k", type=int, default=4,
                    help="tokens per decode_k dispatch in the "
                         "multi-token measurement")
    ap.add_argument("--spec-k", type=int, default=3,
                    help="draft tokens per round in the speculative "
                         "measurement")
    ap.add_argument("--draft-layers", type=int, default=1,
                    help="draft-model depth for the speculative "
                         "measurement (0 disables it)")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--skip-sweep", action="store_true")
    args = ap.parse_args(argv)

    import numpy as np

    import jax

    model, params = _model(args)
    backend = jax.default_backend()
    prompt = np.arange(1, 1 + args.prompt_len,
                       dtype=np.int32)[None] % args.vocab

    cached = measure_cached(model, params, prompt, args.new_tokens,
                            args.capacity)
    recompute = measure_recompute(model, params, prompt, args.new_tokens)
    multi = measure_decode_k(model, params, prompt, args.new_tokens,
                             args.capacity, k=args.decode_k)
    spec = spec_self = None
    if args.draft_layers > 0:
        import jax.numpy as jnp

        from chainermn_tpu.models.transformer import TransformerLM

        draft = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                              n_heads=args.n_heads,
                              n_layers=args.draft_layers,
                              d_ff=2 * args.d_model,
                              max_len=args.capacity,
                              attention="reference", pos_emb="rope")
        draft_params = draft.init(jax.random.PRNGKey(1),
                                  jnp.zeros((1, 4), jnp.int32))["params"]
        spec = measure_speculative(model, params, draft, draft_params,
                                   prompt, args.new_tokens,
                                   args.capacity, args.spec_k)
        # self-draft control: prefill emits the first token, so the
        # largest 1 + R*(spec_k+1) <= n_new keeps every round FULL —
        # acceptance must then be exactly 1.0
        r = max(1, (args.new_tokens - 1) // (args.spec_k + 1))
        spec_self = measure_speculative(
            model, params, model, params, prompt,
            1 + r * (args.spec_k + 1), args.capacity, args.spec_k)

    # the structural proof: identical greedy streams, one compile vs
    # one compile PER LENGTH — and the multi-token program emits the
    # SAME stream from one trace while moving ≤ 8 host bytes/token
    ok = (cached["tokens"] == recompute["tokens"]
          and cached["traces"] == 1
          and recompute["traces"] == args.new_tokens
          and multi["tokens"] == cached["tokens"]
          and multi["traces"] == 1
          and multi["host_bytes_per_token"] <= 8.0)
    if spec is not None:
        # the speculative engine must emit the SAME greedy stream from
        # one propose trace + one verify trace; the self-draft control
        # must accept EVERY proposal (spec_k + 1 tokens per dispatch)
        # while staying on that same stream
        n_self = len(spec_self["tokens"])
        ok = (ok and spec["tokens"] == cached["tokens"]
              and spec["propose_traces"] == 1
              and spec["verify_traces"] == 1
              and spec_self["tokens"] == cached["tokens"][:n_self]
              and spec_self["acceptance_rate"] == 1.0
              and spec_self["tokens_per_dispatch"] == args.spec_k + 1)
    record = {
        "metric": "serving_decode",
        "platform": backend,
        "honest_null": backend != "tpu",
        "n_new_tokens": args.new_tokens,
        "cached": cached,
        "recompute": recompute,
        "decode_k": multi,
        "compile_ratio": recompute["traces"] / cached["traces"],
        "streams_identical": (cached["tokens"] == recompute["tokens"]
                              == multi["tokens"]),
        "trace_assertion_ok": ok,
    }
    if spec is not None:
        record["speculative"] = spec
        record["speculative_self_draft"] = spec_self
        record["streams_identical"] = (record["streams_identical"]
                                       and spec["tokens"]
                                       == cached["tokens"])
    if not args.skip_sweep:
        record["sweep"] = [
            sweep_point(model, params, float(l), args)
            for l in args.loads.split(",") if l.strip()]
    print(json.dumps(record))
    if not ok:
        print("bench_serve: trace-count assertion FAILED "
              f"(cached={cached['traces']}, "
              f"recompute={recompute['traces']}, "
              f"decode_k={multi['traces']}, "
              f"host_bytes/token={multi['host_bytes_per_token']}"
              + (f", propose={spec['propose_traces']}, "
                 f"verify={spec['verify_traces']}, "
                 f"self_draft_acceptance={spec_self['acceptance_rate']}, "
                 f"self_draft_tpd={spec_self['tokens_per_dispatch']}"
                 if spec is not None else "")
              + ")",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

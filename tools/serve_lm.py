#!/usr/bin/env python
"""serve_lm — one supervised serving replica over the continuous-
batching engine.

Builds a seeded TransformerLM, warm-loads weights from a published
snapshot when one exists (publishing on first boot so restarts never
re-initialise), queues a deterministic batch of prompts, and drains the
engine while exposed to ``$CHAINERMN_TPU_CHAOS``. Completed streams are
appended to a JSONL file *idempotently*: a restarted incarnation skips
request ids already on disk, so a chaos kill mid-decode heals to the
same final output the unkilled run would have produced. That replay
guarantee survives sampling too: each request's PRNG seed is derived
from its id (``--seed + request_id``), so ``--temperature``/``--top-k``
streams are as replayable as greedy ones (serving/sampling.py's
one-split-per-token contract). ``--draft N`` swaps in the speculative
engine (``serving/speculative.py``) with an N-layer draft model and
``--kv-dtype int8-block`` selects quantized resident pages; both keep
every replay guarantee because speculative streams are bitwise-
identical to the plain engine's.

Wrap it in the per-host restart loop for the fleet drill::

    CHAINERMN_TPU_CHAOS='kill@step=6,run=0' \\
        python tools/supervise.py --max-restarts 2 -- \\
        python tools/serve_lm.py --out /tmp/streams.jsonl

Exit status follows the supervisor contract (resilience/supervisor.py):
0 clean, 75 on a watchdog abort, anything else is a crash.

Signal contract: **SIGUSR1 requests a graceful drain.** The replica
stops admitting (queued requests are shed — the next incarnation's
idempotent JSONL replay re-submits exactly the ids not yet on disk),
finishes every in-flight stream, flushes its report, and exits 0 —
which ``classify_exit`` counts as ``clean``, so a supervisor never
bills the crash budget for a requested retirement. SIGUSR1 is the
single-replica half of the fleet's drain story; ``tools/fleet_lm.py``
additionally MIGRATES in-flight sessions to surviving replicas.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _log(msg):
    print(f"serve_lm: {msg}", file=sys.stderr, flush=True)


def _done_ids(path):
    """Request ids already drained to the JSONL (prior incarnations)."""
    done = set()
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    done.add(json.loads(line)["request_id"])
    return done


def serve(args):
    import numpy as np

    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (Engine, EngineConfig, ServingReport,
                                       SpeculativeEngine, load_weights,
                                       publish_weights)
    from chainermn_tpu.serving.weights import WeightsError

    model = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                          n_heads=args.n_heads, n_layers=args.n_layers,
                          d_ff=2 * args.d_model, max_len=args.capacity,
                          attention="reference", pos_emb="rope")
    init = model.init(jax.random.PRNGKey(args.seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
    if args.weights:
        try:
            params, src = load_weights(args.weights, like=init)
            _log(f"warm weights loaded from {src}")
        except WeightsError:
            params = init
            publish_weights(params, args.weights)
            _log(f"cold boot: published weights to {args.weights}")
    else:
        params = init

    cfg = EngineConfig(n_slots=args.slots, capacity=args.capacity,
                       max_new_tokens=args.max_new_tokens,
                       prefill_cohort=1,
                       buckets=[args.prompt_len, args.capacity],
                       decode_k=args.decode_k,
                       prefill_chunk=args.prefill_chunk,
                       token_budget=args.token_budget,
                       kv_dtype=args.kv_dtype)
    if args.draft:
        # the draft model is derived from the seed, never warm-loaded:
        # it only decides how far a round advances, so the replayed
        # streams stay identical across restarts either way
        draft = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                              n_heads=args.n_heads, n_layers=args.draft,
                              d_ff=2 * args.d_model,
                              max_len=args.capacity,
                              attention="reference", pos_emb="rope")
        draft_params = draft.init(jax.random.PRNGKey(args.seed + 1),
                                  jnp.zeros((1, 4), jnp.int32))["params"]
        eng = SpeculativeEngine(model, params, draft, draft_params, cfg,
                                spec_k=args.spec_k, report=ServingReport())
        _log(f"speculative: {args.draft}-layer draft, spec_k={args.spec_k}")
    else:
        eng = Engine(model, params, cfg, report=ServingReport())

    done = _done_ids(args.out)
    rng = np.random.RandomState(args.seed)
    reqs = {}
    for i in range(args.requests):
        prompt = rng.randint(0, args.vocab,
                             (args.prompt_len,)).astype(np.int32)
        if i in done:
            continue                   # drained by a prior incarnation
        reqs[i] = (eng.submit(prompt, temperature=args.temperature,
                              top_k=args.top_k, seed=args.seed + i),
                   prompt)
    _log(f"queued {len(reqs)} of {args.requests} requests "
         f"({len(done)} already drained)")

    # SIGUSR1 = graceful drain (see module docstring). The handler only
    # flips a flag; the scheduler loop does the actual shedding at its
    # next iteration boundary, so a signal mid-step never tears state.
    import signal
    drain = {"requested": False}

    def _on_drain(signum, frame):
        drain["requested"] = True

    try:
        signal.signal(signal.SIGUSR1, _on_drain)
    except ValueError:
        pass                           # not the main thread (tests)

    emitted = {}
    shed = False
    with open(args.out, "a") as out:
        while not eng.idle():
            if drain["requested"] and not shed:
                shed = True
                dropped = 0
                while eng.queue:
                    req = eng.queue.popleft()
                    req.state = "aborted"
                    eng.report.record_retire(req.request_id, aborted=True)
                    dropped += 1
                _log(f"SIGUSR1: drain — shed {dropped} queued, finishing "
                     f"{len(eng.active) + len(eng.prefilling)} in flight")
            eng.step()                 # chaos.on_step fires in here
            for i, (req, prompt) in reqs.items():
                if req.state == "done" and i not in emitted:
                    emitted[i] = True
                    out.write(json.dumps(
                        {"request_id": i,
                         "prompt": prompt.tolist(),
                         "tokens": req.tokens}) + "\n")
                    out.flush()
                    os.fsync(out.fileno())
    _log(("drained (SIGUSR1 retirement); " if shed else "drained; ")
         + f"report: {eng.report.json()}")
    if args.report:
        with open(args.report, "w") as f:
            f.write(eng.report.json())
    return None


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="serve_lm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", required=True,
                    help="JSONL of completed streams (append, idempotent)")
    ap.add_argument("--weights", default=None,
                    help="published-weights path: warm-load when present, "
                         "publish on cold boot")
    ap.add_argument("--report", default=None,
                    help="write the ServingReport JSON here on drain")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--prompt-len", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--capacity", type=int, default=32)
    # decode-k defaults to 1: the chaos drill's kill@step=N timing
    # counts scheduler iterations, and one token per iteration keeps a
    # mid-decode kill meaning what the drill scripts expect
    ap.add_argument("--decode-k", type=int, default=1,
                    help="tokens committed per decode dispatch")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked prefill width (default: monolithic "
                         "per-bucket prefill)")
    ap.add_argument("--token-budget", type=int, default=None,
                    help="per-iteration token budget shared by decode "
                         "and prefill (default: unbounded)")
    ap.add_argument("--draft", type=int, default=0, metavar="N_LAYERS",
                    help="speculative decode with an N_LAYERS draft "
                         "model (seeded from --seed + 1); streams are "
                         "bitwise-identical to the plain engine "
                         "(default: off)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens proposed per speculative round")
    ap.add_argument("--kv-dtype", default=None,
                    choices=["f32", "int8-block"],
                    help="paged-KV storage mode (int8-block trades a "
                         "calibrated logit-error bound for ~4x slots)")
    ap.add_argument("--temperature", type=float, default=None,
                    help="sampling temperature (default: greedy argmax)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="top-k truncation for sampled decode")
    ap.add_argument("--vocab", type=int, default=43)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from chainermn_tpu.resilience.supervisor import main_exit_code

    return main_exit_code(lambda: serve(args))


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Input-pipeline overlap proof: loader-fed vs pre-staged throughput.

The tunneled bench chip cannot take host→device traffic at training rate
(~10 MB/s tunnel vs ~375 MB/s needed — docs/resnet50_roofline.md §4), so
the HOST-side loader path is proven here on the virtual 8-device CPU mesh,
where transfers are memcpy-speed and the native C++ double-buffered gather
(native/chainermn_native.cpp) can actually overlap with device compute.

Prints pre-staged img/s, loader-fed img/s, and the ratio. VERDICT round-1
acceptance: ratio ≥ 0.95.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from chainermn_tpu.utils import ensure_platform

ensure_platform()  # re-assert JAX_PLATFORMS=cpu over any site hook

import jax
import jax.numpy as jnp
import numpy as np
import optax

import chainermn_tpu
from chainermn_tpu.models.resnet import CifarResNet
from chainermn_tpu.training.loader import PrefetchingLoader
from chainermn_tpu.training.step import classifier_loss, \
    make_data_parallel_train_step
from jax.sharding import NamedSharding, PartitionSpec as P


def main():
    comm = chainermn_tpu.create_communicator("xla")
    model = CifarResNet(num_classes=10, depth=8)
    B = 8 * comm.size
    N, H = 512, 32

    def u8_loss(model, params, x, y, **kw):
        x = x.astype(jnp.float32) / 255.0
        return classifier_loss(model, params, x, y, **kw)

    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((2, H, H, 3), np.float32))
    params = comm.bcast_data(variables["params"])
    extra = {"batch_stats": comm.bcast_data(variables["batch_stats"])}
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.1), comm)
    state0 = (params, opt.init(params), extra)
    step = make_data_parallel_train_step(
        model, opt, comm, mutable=("batch_stats",), loss_fn=u8_loss,
        donate=False)

    rs = np.random.RandomState(0)
    xs = rs.randint(0, 256, (N, H, H, 3), dtype=np.uint8)
    ys = rs.randint(0, 10, size=N).astype(np.int32)
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    iters = 30

    # --- A: pre-staged device tensors, no input pipeline ---------------
    xd = jax.device_put(xs[:B], dsh)
    yd = jax.device_put(ys[:B], dsh)
    state = state0
    for _ in range(3):
        state, m = step(state, xd, yd)
        float(m["main/loss"])  # per-iter sync (1-core rendezvous rule)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, xd, yd)
        float(m["main/loss"])
    pre = iters * B / (time.perf_counter() - t0)

    # --- B: every batch through the native prefetch loader -------------
    loader = PrefetchingLoader(xs, ys, B, shuffle=True, seed=0)
    state = state0
    for _ in range(3):
        xb, yb = next(loader)
        state, m = step(state, jax.device_put(xb, dsh),
                        jax.device_put(yb, dsh))
        float(m["main/loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        xb, yb = next(loader)
        state, m = step(state, jax.device_put(xb, dsh),
                        jax.device_put(yb, dsh))
        float(m["main/loss"])
    fed = iters * B / (time.perf_counter() - t0)
    loader.close()

    print(f"pre-staged: {pre:.1f} img/s   loader-fed: {fed:.1f} img/s   "
          f"ratio: {fed / pre:.3f}")
    return fed / pre


if __name__ == "__main__":
    ok = main() >= 0.95
    sys.exit(0 if ok else 1)

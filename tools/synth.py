#!/usr/bin/env python
"""synth — enumerate, price, and emit synthesized collective programs.

The offline companion to :mod:`chainermn_tpu.synthesis`: describe what
the enumerator would propose for a machine shape, and run the canned
tuner to persist a winning synthesized schedule into the profile DB —
the same DB ``create_multi_node_optimizer(tune=...)`` consumes.

Usage::

    python tools/synth.py --describe --intra 4 --inter 2 \\
        [--bytes N] [--lossy]
    python tools/synth.py --describe \\
        --tiers ici:4:1:100,dcn:2:100:25 [--lossy]
    python tools/synth.py --emit DB_PATH --intra 4 --inter 2 \\
        [--bytes N] [--lossy] [--model-key KEY]

``--describe`` lists every program the deterministic enumerator emits
for the topology — its step sequence, validity verdict, modeled cost at
``--bytes``, and exact per-tier wire bytes — next to the fixed-strategy
prices, so you can see what the program search adds before trusting it.

``--emit`` runs the full canned tune (fixed strategies AND programs)
and stores the winning plan under the topology's fingerprint in the
profile DB at ``DB_PATH`` — but only when the winner is a synthesized
program with strictly higher DL201 overlap than the best fixed
candidate; otherwise nothing is written and the findings are reported.
Re-running with the same arguments rewrites the identical plan (the
tune is deterministic), so ``--emit`` is idempotent.

Topology: ``--intra/--inter`` builds the classic two-tier ICI×DCN shape
with default parameters; ``--tiers name:size:latency_us:bw_gbps,...``
(innermost first) describes arbitrary hierarchies.

Exit status: 0 clean, 1 findings (invalid program, or no synthesized
improvement to emit), 2 usage error.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

DEFAULT_BYTES = 51 << 20


def _parse_tiers(spec):
    from chainermn_tpu.tuning.topology import Tier
    tiers = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 4:
            raise ValueError(
                f"bad --tiers entry {part!r} "
                "(expected name:size:latency_us:bw_gbps)")
        name, size, lat, bw = fields
        tiers.append(Tier(name, int(size), float(lat), float(bw)))
    if not tiers:
        raise ValueError("--tiers parsed to no tiers")
    return tuple(tiers)


def _topology(args):
    from chainermn_tpu.tuning.topology import Topology, two_tier
    if args.tiers:
        return Topology(_parse_tiers(args.tiers))
    if args.intra is None or args.inter is None:
        raise ValueError("need --tiers, or both --intra and --inter")
    if args.intra < 1 or args.inter < 1:
        raise ValueError("--intra/--inter must be >= 1")
    return two_tier(args.intra, args.inter)


def cmd_describe(args, topology):
    from chainermn_tpu.synthesis import (
        check_program,
        enumerate_programs,
        program_cost_us,
        program_wire_bytes,
    )
    nbytes = args.bytes
    print(f"topology: {topology.describe()}")
    print(f"fingerprint: {topology.fingerprint()}")
    print(f"payload: {nbytes:,} bytes")
    for strategy in ("flat", "hierarchical"):
        print(f"  fixed {strategy}: "
              f"{topology.estimate_us(strategy, nbytes):,.1f} us")
    programs = enumerate_programs(topology, lossy=args.lossy)
    if not programs:
        print("no programs (single-tier topology: the enumerator only "
              "helps when there are tiers to compose across)")
        return 0
    findings = 0
    for prog in programs:
        errs = check_program(prog)
        if errs:
            findings += 1
            print(f"  {prog.name}: INVALID — {'; '.join(errs)}")
            continue
        cost = program_cost_us(prog, topology, nbytes)
        per_tier = program_wire_bytes(prog, nbytes)
        wire = " ".join(
            f"{topology.tiers[i].name}={int(b):,}B"
            for i, b in sorted(per_tier.items()))
        print(f"  {prog.name}: {cost:,.1f} us  wire[{wire}]")
        print(f"    {prog.describe()}")
    print(f"{len(programs)} program(s), {findings} invalid")
    return 1 if findings else 0


def cmd_emit(args, topology):
    from chainermn_tpu.tuning import ProfileDB
    from chainermn_tpu.tuning.tuner import tune_canned
    result = tune_canned(topology, args.bytes, lossy=args.lossy,
                         model_key=args.model_key)
    plan = result.plan
    fixed = [r for r in result.rows
             if r["candidate"]["strategy"] != "synth"]
    best_fixed = max(r["overlap_fraction"] for r in fixed)
    print(f"winner: {plan.strategy} "
          f"(overlap {plan.overlap_fraction} vs best fixed "
          f"{best_fixed})")
    if plan.strategy != "synth" or plan.overlap_fraction <= best_fixed:
        print("no synthesized improvement — nothing emitted")
        return 1
    print(f"  program: {plan.program['name']} "
          f"steps={len(plan.program['steps'])} "
          f"wire={plan.wire_format}")
    db = ProfileDB(args.emit)
    prior = db.plan_for(plan.fingerprint, args.model_key)
    if prior == plan:
        print(f"unchanged: identical plan already stored in {db.path}")
        return 0
    db.put_plan(plan)
    db.save()
    print(f"emitted plan for {plan.fingerprint!r} "
          f"(model_key={args.model_key!r}) -> {db.path}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="synth", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--describe", action="store_true",
                      help="list enumerated programs with costs")
    mode.add_argument("--emit", metavar="DB_PATH",
                      help="tune and store a winning synth plan")
    ap.add_argument("--intra", type=int, default=None,
                    help="fast-tier size (with --inter)")
    ap.add_argument("--inter", type=int, default=None,
                    help="slow-tier size (with --intra)")
    ap.add_argument("--tiers", default=None,
                    help="name:size:latency_us:bw_gbps,... "
                         "(innermost first; overrides --intra/--inter)")
    ap.add_argument("--bytes", type=int, default=DEFAULT_BYTES,
                    help=f"payload bytes to price (default "
                         f"{DEFAULT_BYTES})")
    ap.add_argument("--lossy", action="store_true",
                    help="include quantized-wire programs")
    ap.add_argument("--model-key", default="default",
                    help="profile-DB model key for --emit")
    args = ap.parse_args(argv)
    if args.bytes < 1:
        print("--bytes must be >= 1", file=sys.stderr)
        return 2
    try:
        topology = _topology(args)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.describe:
        return cmd_describe(args, topology)
    return cmd_emit(args, topology)


if __name__ == "__main__":
    sys.exit(main())

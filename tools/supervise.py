#!/usr/bin/env python
"""supervise — wrap a training command in the per-host restart loop.

Launches the command, classifies each exit (clean / preempted /
aborted / crash — see chainermn_tpu/resilience/supervisor.py), and
relaunches with jittered backoff until the run finishes cleanly or the
crash budget (N counted restarts per rolling window) trips. Each
incarnation gets ``$CHAINERMN_TPU_RESTART_COUNT`` in its environment.

Run ONE supervisor per host, wrapping that host's training process::

    python tools/supervise.py --max-restarts 5 --window-s 3600 -- \\
        python examples/mnist/train_mnist.py

Exit status: the child's own code for terminal outcomes (0 clean,
143 preempted with --no-restart-on-preempt), 112 when the restart
budget is exhausted (crash loop — human needed), 2 usage error.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="supervise", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--max-restarts", type=int, default=5,
                    help="counted restarts allowed per rolling window "
                         "(default 5; preemptions are free)")
    ap.add_argument("--window-s", type=float, default=3600.0,
                    help="rolling budget window in seconds (default 3600)")
    ap.add_argument("--no-restart-on-preempt", action="store_true",
                    help="exit 143 on preemption instead of relaunching "
                         "(for platforms that reschedule the job "
                         "themselves)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="training command to supervise (prefix with --)")
    args = ap.parse_args(argv)

    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.print_usage(sys.stderr)
        print("supervise: give a command to wrap (after '--')",
              file=sys.stderr)
        return 2

    from chainermn_tpu.resilience.supervisor import Supervisor

    sup = Supervisor(cmd, max_restarts=args.max_restarts,
                     window_s=args.window_s,
                     restart_on_preempt=not args.no_restart_on_preempt)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())

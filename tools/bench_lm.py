#!/usr/bin/env python
"""Transformer-LM training throughput (tokens/sec) on the available chips.

Secondary benchmark (the driver's recorded metric is bench.py's ResNet-50,
which also folds this number into its JSON line as the LM regression
gate): a GPT-small-ish causal LM on the flash-attention path, bf16
compute, data-parallel step factory. Prints one JSON line per config.

Usage: python tools/bench_lm.py [d_model n_layers seq_len batch
                                 [loss [d_head [qkv_layout]]]]
                                [--autotune-blocks] [--tune[=DB_PATH]]
                                [--grad-reducer=flat,hierarchical,...]
                                [--wire-format=f32,bf16,int8,int8-block,int4-block]
  --tune: build the optimizer from the schedtune profile DB
  (create_multi_node_optimizer(tune=...), docs/tuning.md; default DB
  path unless =DB_PATH given — run tools/schedtune.py first). The JSON
  line gains the chosen plan's ``tuning/overlap_frac``,
  ``tuning/bucket_bytes``, and ``tuning/strategy``; off TPU the
  throughput delta of the tuned plan is the same honest null as below.
  --grad-reducer: comma-separated gradient-reduction strategies
  (collectives/ registry: flat | hierarchical | quantized | auto); one
  JSON line per strategy, with the strategy's per-step payload and wire
  bytes from the reducer's bucket plan. Off TPU the throughput deltas
  are meaningless (host-platform collectives are memcpys — BASELINE.md
  records the honest null); the byte accounting is exact everywhere.
  --wire-format: comma-separated wire formats
  (docs/collectives.md#quantized-wire-formats); one JSON line per
  format. 'f32' runs the flat reference; the narrow formats default the
  strategy to 'quantized' when --grad-reducer is absent. Each line's
  ``comm_wire_bytes_per_step`` is EXACT (scale sidecars included) and
  ``comm_wire_compression`` is wire/payload — byte accounting is
  host-side and correct off-TPU, like --grad-reducer.
  --autotune-blocks: time the flash-attention (block_q, block_k)
  candidates for this shape (ops/autotune.py) and build the model with
  the winner; off-TPU the tuner returns the defaults untimed (recorded
  as an honest null in BASELINE.md)
  loss: 'unfused' (default) or 'fused' — the fused head+CE Pallas kernel
  (ops/fused_ce.py; measured throughput-neutral, −2 GB logits memory)
  d_head: head dim (default 64; 128 halves the QK^T MXU inefficiency the
  roofline attributes to d=64 — docs/lm_roofline.md: +26% measured)
  qkv_layout: 'blhd' (default) or 'bhld' — head-major pivot-free
  attention tensors (+3% measured; BASELINE.md r4)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def measure(d_model=768, n_layers=12, seq_len=2048, batch=8,
            loss_kind="unfused", d_head=64, scan_k=4, n_iters=6,
            qkv_layout="blhd", autotune_blocks=False, grad_reducer=None,
            tune=None, wire_format=None):
    """Measure LM training throughput; returns (tokens_per_sec_per_chip,
    config dict). Importable — bench.py reuses this as its LM gate."""
    import jax
    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu.models.transformer import (TransformerLM,
                                                  lm_loss_with_aux)
    from chainermn_tpu.training.step import make_data_parallel_train_step

    if loss_kind not in ("unfused", "fused"):
        raise ValueError(f"loss must be 'unfused' or 'fused', got "
                         f"{loss_kind!r}")
    if d_model % d_head:
        raise ValueError(f"d_head {d_head} must divide d_model {d_model}")

    comm = chainermn_tpu.create_communicator("xla")
    blocks = None
    if autotune_blocks:
        from chainermn_tpu.ops.autotune import tune_flash_blocks

        blocks = tune_flash_blocks(batch, seq_len, d_model // d_head,
                                   d_head, dtype=jnp.bfloat16)
    model = TransformerLM(
        vocab=32768, d_model=d_model, n_heads=d_model // d_head,
        n_layers=n_layers, d_ff=4 * d_model, max_len=seq_len,
        pos_emb="rope", attention="flash", dtype=jnp.bfloat16,
        qkv_layout=qkv_layout, attention_blocks=blocks)

    toks = np.random.RandomState(0).randint(
        0, 32768, size=(batch * comm.size, seq_len + 1)).astype(np.int32)
    params = comm.bcast_data(
        model.init(jax.random.PRNGKey(0), toks[:1, :-1])["params"])
    reducer = None
    wf = None if wire_format in (None, "f32") else wire_format
    if grad_reducer or wf:
        from chainermn_tpu.collectives import make_grad_reducer

        # a narrow wire with no explicit strategy means 'quantized'
        reducer = make_grad_reducer(grad_reducer or "quantized", comm,
                                    wire_format=wf)
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adamw(3e-4), comm, grad_reducer=reducer, tune=tune)
    plan = getattr(opt, "plan", None)
    if plan is not None and reducer is None:
        reducer = opt.grad_reducer  # the plan-built reducer
    # K steps per dispatch: measures the device, not the tunnel's ~100 ms
    # dispatch round-trip (same methodology as bench.py; the token stack
    # reuses ONE device batch K times to avoid the ~10 MB/s tunnel)
    if loss_kind == "fused":
        from chainermn_tpu.ops import fused_lm_loss

        lf = fused_lm_loss
    else:
        lf = lm_loss_with_aux
    step = make_data_parallel_train_step(
        model, opt, comm, loss_fn=lf, scan_steps=scan_k)
    state = (params, opt.init(params))

    from jax.sharding import NamedSharding, PartitionSpec as P

    dsh = NamedSharding(comm.mesh,
                        P(None, comm.axis_names[0]))
    xs = jax.device_put(np.broadcast_to(
        toks[None, :, :-1], (scan_k,) + toks[:, :-1].shape).copy(), dsh)
    ys = jax.device_put(np.broadcast_to(
        toks[None, :, 1:], (scan_k,) + toks[:, 1:].shape).copy(), dsh)

    # three warmup executions: compile, plus the tunneled chip's deferred
    # one-time second-execution cost (see bench.py)
    for _ in range(3):
        state, m = step(state, xs, ys)
        float(m["main/loss"][-1])
    t0 = time.perf_counter()
    for _ in range(n_iters):
        # timed region syncs ONCE at the end on purpose: the figure is
        # device throughput, and a per-iteration sync would add the full
        # tunnel round-trip to every dispatch (see profile_lm.py, r5)
        state, m = step(state, xs, ys)  # dlint: disable=DL104
    final = float(m["main/loss"][-1])
    dt = time.perf_counter() - t0
    assert final == final, "loss is NaN"

    tokens_per_sec = n_iters * scan_k * batch * comm.size * seq_len / dt
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    config = {"d_model": d_model, "n_layers": n_layers,
              "seq_len": seq_len, "batch_per_chip": batch,
              "d_head": d_head,
              "params_m": round(n_params / 1e6, 1),
              "loss": loss_kind, "qkv_layout": qkv_layout,
              "attention_blocks": blocks}
    if reducer is not None:
        rows = reducer.plan(params)
        payload = sum(r["bytes"] for r in rows)
        wire = sum(r["wire_bytes"] for r in rows)
        config["grad_reducer"] = reducer.name
        config["comm_bytes_per_step"] = payload
        config["comm_wire_bytes_per_step"] = wire
        config["comm_wire_compression"] = round(
            wire / payload, 6) if payload else 1.0
    if wire_format is not None:
        config["wire_format"] = wire_format
    if plan is not None:
        config["tuning/overlap_frac"] = plan.overlap_fraction
        config["tuning/bucket_bytes"] = plan.bucket_bytes
        config["tuning/strategy"] = plan.strategy
        config["tuning/source"] = plan.source
    return tokens_per_sec / comm.size, config


def wire_report(wire_format="f32", d_model=768, n_layers=12,
                seq_len=2048, d_head=64):
    """Exact per-step wire accounting for the LM bench config WITHOUT
    running a step: abstract params (``jax.eval_shape`` of the model
    init — zero FLOPs, zero device memory) through the reducer's bucket
    plan. Works anywhere; bench.py's wire gate is built on this."""
    import jax
    import jax.numpy as jnp

    import chainermn_tpu
    from chainermn_tpu.collectives import make_grad_reducer
    from chainermn_tpu.models.transformer import TransformerLM

    comm = chainermn_tpu.create_communicator("xla")
    model = TransformerLM(
        vocab=32768, d_model=d_model, n_heads=d_model // d_head,
        n_layers=n_layers, d_ff=4 * d_model, max_len=seq_len,
        pos_emb="rope", attention="flash", dtype=jnp.bfloat16)
    toks = jax.ShapeDtypeStruct((1, seq_len), jnp.int32)
    params = jax.eval_shape(
        lambda t: model.init(jax.random.PRNGKey(0), t)["params"], toks)
    wf = None if wire_format in (None, "f32") else wire_format
    reducer = make_grad_reducer("quantized" if wf else "flat", comm,
                                wire_format=wf)
    rows = reducer.plan(params)
    payload = sum(r["bytes"] for r in rows)
    wire = sum(r["wire_bytes"] for r in rows)
    return {"wire_format": wire_format or "f32",
            "payload_bytes": payload,
            "wire_bytes": wire,
            "compression": round(wire / payload, 6) if payload else 1.0}


def main():
    argv = sys.argv[1:]
    autotune = "--autotune-blocks" in argv
    if autotune:
        argv.remove("--autotune-blocks")
    reducers = [None]
    for a in list(argv):
        if a.startswith("--grad-reducer"):
            reducers = a.split("=", 1)[1].split(",")
            argv.remove(a)
    wire_formats = [None]
    for a in list(argv):
        if a.startswith("--wire-format"):
            wire_formats = a.split("=", 1)[1].split(",")
            argv.remove(a)
    tune = None
    for a in list(argv):
        if a.startswith("--tune"):
            tune = a.split("=", 1)[1] if "=" in a else True
            argv.remove(a)
    d_model = int(argv[0]) if len(argv) > 0 else 768
    n_layers = int(argv[1]) if len(argv) > 1 else 12
    seq_len = int(argv[2]) if len(argv) > 2 else 2048
    batch = int(argv[3]) if len(argv) > 3 else 8
    loss_kind = argv[4] if len(argv) > 4 else "unfused"
    d_head = int(argv[5]) if len(argv) > 5 else 64
    qkv_layout = argv[6] if len(argv) > 6 else "blhd"
    for gr in reducers:
        for wfmt in wire_formats:
            try:
                per_chip, config = measure(d_model, n_layers, seq_len,
                                           batch, loss_kind, d_head,
                                           qkv_layout=qkv_layout,
                                           autotune_blocks=autotune,
                                           grad_reducer=gr, tune=tune,
                                           wire_format=wfmt)
            except ValueError as e:
                raise SystemExit(str(e))
            print(json.dumps({
                "metric": "transformer_lm_tokens_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "tokens/sec/chip",
                "config": config,
            }), flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""schedtune CLI: search the collective-schedule knob space, print the
chosen schedule + its predicted DL201 overlap fraction, and write the
winner into the per-topology profile DB.

The search (chainermn_tpu/tuning/, docs/tuning.md) sweeps bucket_bytes,
bucket emission order, double-buffering (only with --allow-stale) and
reducer strategy, scoring each candidate's scheduled HLO with the real
dlint DL201/DL203 passes plus the multi-tier Topology cost model. Two
schedule sources:

* default: the canned scheduled-HLO emulator — deterministic, runs
  anywhere, no compiler needed;
* ``--aot``: AOT-compile the actual data-parallel train step per
  candidate against a described TPU topology (needs the TPU compiler
  plugin; no chips — same machinery as tools/check_overlap_schedule.py).
  Prints a skip JSON when the plugin is absent.

Usage:
  python tools/schedtune.py [--grad-bytes N] [--db PATH] [--model-key K]
                            [--intra N] [--inter N] [--lossy]
                            [--allow-stale] [--aot [v5e:2x4]] [--no-write]

Prints one JSON line: the chosen plan, the untuned-default score row,
and the full candidate table. Exit 0 always (a tuner that found no
improvement still found the answer). A run whose winner strictly beats
the default's overlap fraction sets ``"improves_overlap": true`` — the
acceptance bar for recording the plan.
"""

import json
import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

#: ResNet-50 bf16 grads ≈ 51 MiB — 13 buckets at the 4 MiB default, a
#: representative payload for the canned search
DEFAULT_GRAD_BYTES = 51 << 20


def _flag(argv, name, default=None, has_value=True):
    for a in list(argv):
        if a == name and not has_value:
            argv.remove(a)
            return True
        if a == name and has_value:
            i = argv.index(a)
            argv.pop(i)
            return argv.pop(i)
        if has_value and a.startswith(name + "="):
            argv.remove(a)
            return a.split("=", 1)[1]
    return default


def _aot_compile_fn(topology_name):
    """Per-candidate AOT compilation of the real DP train step against a
    described TPU topology; returns (compile_fn, topology, total_bytes)
    or None when the compiler plugin is missing."""
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    import numpy as np

    import jax
    import jax.numpy as jnp

    try:
        from jax.experimental import topologies

        tdesc = topologies.get_topology_desc(platform="tpu",
                                             topology_name=topology_name)
    except Exception:
        return None

    import optax
    from flax import linen as nn
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import chainermn_tpu
    from chainermn_tpu.comm.xla import XlaCommunicator
    from chainermn_tpu.training.step import make_data_parallel_train_step
    from chainermn_tpu.tuning import Topology

    class Big(nn.Module):
        # same ~35M-param model as tools/check_overlap_schedule.py:
        # large enough that the all-reduce combiner keeps >1 collective
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            for _ in range(3):
                x = nn.relu(nn.Dense(4096)(x))
            return nn.Dense(10)(x)

    devs = np.asarray(tdesc.devices)
    mesh = Mesh(devs.reshape(2, devs.size // 2), ("dcn", "ici"))
    model = Big()
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 28, 28), jnp.float32))["params"])
    total_bytes = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(params))
    dsh = NamedSharding(mesh, P(("dcn", "ici")))
    rep = NamedSharding(mesh, P())
    x = jax.ShapeDtypeStruct((64, 28, 28), jnp.float32, sharding=dsh)
    y = jax.ShapeDtypeStruct((64,), jnp.int32, sharding=dsh)
    opts = {
        "xla_tpu_enable_latency_hiding_scheduler": "true",
        "xla_enable_async_all_reduce": "true",
    }

    def compile_fn(cand):
        comm = XlaCommunicator(mesh=mesh,
                               dcn_bucket_bytes=cand.bucket_bytes)
        opt = optax.sgd(0.1)
        from chainermn_tpu.collectives import make_grad_reducer

        extra = {}
        if getattr(cand, "program", None) is not None:
            extra["program"] = cand.program  # 'synth' candidates
        reducer = make_grad_reducer(
            cand.strategy, comm, bucket_bytes=cand.bucket_bytes,
            bucket_order=cand.bucket_order,
            wire_format=(cand.wire_format
                         if cand.wire_format != "f32" else None),
            **extra)
        mnopt = chainermn_tpu.create_multi_node_optimizer(
            opt, comm, grad_reducer=reducer,
            double_buffering=cand.double_buffering)
        state = (params, jax.eval_shape(opt.init, params))
        if cand.double_buffering:
            state = (params, jax.eval_shape(mnopt.init, params))
        step = make_data_parallel_train_step(model, mnopt, comm,
                                             donate=False)
        astate = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=rep),
            state)
        return jax.jit(lambda s, a, b: step(s, a, b)).lower(
            astate, x, y).compile(opts).as_text()

    return compile_fn, Topology.from_comm(XlaCommunicator(mesh=mesh)), \
        total_bytes


def main():
    argv = sys.argv[1:]
    grad_bytes = int(_flag(argv, "--grad-bytes", DEFAULT_GRAD_BYTES))
    db_path = _flag(argv, "--db")
    model_key = _flag(argv, "--model-key", "default")
    intra = _flag(argv, "--intra")
    inter = _flag(argv, "--inter")
    lossy = bool(_flag(argv, "--lossy", False, has_value=False))
    allow_stale = bool(_flag(argv, "--allow-stale", False,
                             has_value=False))
    no_write = bool(_flag(argv, "--no-write", False, has_value=False))
    aot = None
    for a in list(argv):  # --aot is optionally valued: --aot[=NAME]
        if a == "--aot":
            argv.remove(a)
            aot = "v5e:2x4"
        elif a.startswith("--aot="):
            argv.remove(a)
            aot = a.split("=", 1)[1]
    if argv:
        raise SystemExit(f"unknown arguments: {argv} (see module doc)")

    from chainermn_tpu.tuning import (ProfileDB, tune, tune_canned,
                                      two_tier)

    source = "canned"
    if aot:
        built = _aot_compile_fn(aot)
        if built is None:
            print(json.dumps({
                "ok": None,
                "skip": f"no TPU compiler plugin for --aot {aot}"}))
            return
        compile_fn, topology, total_bytes = built
        result = tune(topology, total_bytes, compile_fn, lossy=lossy,
                      allow_stale=allow_stale, model_key=model_key,
                      source="aot")
        source = "aot"
        grad_bytes = total_bytes
    else:
        if intra or inter:
            topology = two_tier(int(intra or 8), int(inter or 1))
        else:
            # describe the local communicator's mesh (CPU or TPU)
            import chainermn_tpu
            from chainermn_tpu.tuning import Topology

            comm = chainermn_tpu.create_communicator("xla")
            topology = Topology.from_comm(comm)
        db_probe = ProfileDB(db_path)
        measured = db_probe.measured_for(topology) or None
        result = tune_canned(topology, grad_bytes, lossy=lossy,
                             allow_stale=allow_stale, model_key=model_key,
                             measured=measured)

    plan = result.plan
    db = ProfileDB(db_path)
    written = None
    if not no_write:
        db.put_plan(plan)
        written = db.save()

    k = max(1, math.ceil(grad_bytes / plan.bucket_bytes))
    print(f"chosen schedule  : {plan.strategy} bucket_bytes="
          f"{plan.bucket_bytes:,} ({k} buckets) order={plan.bucket_order}"
          f"{' wire=' + plan.wire_format if plan.wire_format != 'f32' else ''}"
          f"{' +double_buffering' if plan.double_buffering else ''}",
          file=sys.stderr)
    print(f"overlap fraction : {plan.overlap_fraction:.4f} (default "
          f"flat: {result.default['overlap_fraction']:.4f})",
          file=sys.stderr)
    print(json.dumps({
        "ok": True,
        "source": source,
        "topology": plan.fingerprint,
        "grad_bytes": grad_bytes,
        "chosen": plan.to_dict(),
        "default": result.default,
        "improves_overlap": result.improves_overlap,
        "n_candidates": len(result.rows),
        "candidates": result.rows,
        "db": written,
    }))


if __name__ == "__main__":
    main()

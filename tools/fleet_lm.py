#!/usr/bin/env python
"""fleet_lm — a serving FLEET in one process: N engine replicas behind
the router, or a disaggregated prefill/decode pair.

Builds one seeded TransformerLM, shares its weights across every
replica (warm-loading from a published snapshot when one exists, like
serve_lm.py), queues a deterministic batch of prompts, and drains:

* default — ``fleet.Router`` over ``--replicas`` engines, each in its
  own worker thread: load-aware + session-affine placement, queue-depth
  backpressure, and heartbeat-driven replica health. With
  ``$CHAINERMN_TPU_CHAOS='kill_replica@step=N,replica=R'`` the targeted
  worker dies mid-stream and the router re-queues its slots onto
  survivors — the drill asserts every stream still completes with zero
  dropped or duplicated tokens (seeded replay, serving/sampling.py).
* ``--disaggregate`` — ``fleet.DisaggregatedFleet``: prefill engine →
  KVHandoff wire (``--wire-format`` f32 | int8-block) → decode engine,
  exposed to ``corrupt_handoff`` faults (fallback = clean re-prefill).
  Add ``--async-conveyor`` to overlap the wire with decode steps.
* ``--hosts N --host-rank R`` — REAL cross-process disaggregation:
  ranks 0..P-1 (``--prefill-hosts P``, default 1) prefill and ship
  seq/SHA-framed handoffs; ranks P..N-1 adopt and decode. The wire is
  picked by ``--transport``: ``fs`` (default) is the restart-tolerant
  on-disk ``FsObjectPlane`` under ``--plane-dir``; ``socket`` is the
  TCP ``comm.socket_plane.SocketObjectPlane`` over the ``--endpoints``
  host:port list (one per rank). Destination choice is m×n: each
  prefill host ships every ready handoff to the least-loaded decode
  host that is not currently suspect (its last send failed — the
  saturated-survivor precheck), announcing ownership first with an
  ``{"kind": "expect", "sid": i}`` control frame on tag 7003 and
  closing its run with one ``{"kind": "eof"}`` per decode host.
  ``--streamed`` ships each handoff as format-5 per-layer chunk
  frames + a closing manifest — a corrupt chunk NACKs and re-sends
  alone. Wire-level chaos (``drop_handoff``/``delay_handoff``/
  ``dup_handoff``/``corrupt_handoff``, plus the socket-level
  ``reset_conn``/``partial_write``/``stall_accept``) tears at the
  frames in flight; ``kill@step=`` SIGKILLs a prefill process
  mid-transfer — under ``resilience.Supervisor`` the restarted
  incarnation re-prefills every unfinished stream and the receivers'
  fences answer already-adopted replays with duplicate acks (zero
  dropped or duplicated tokens).

Completed streams append to ``--out`` idempotently (request ids already
on disk are skipped), so a supervised restart heals to the same final
JSONL the unkilled run would have produced — per-request seeds are
``--seed + request_id``, making sampled streams as replayable as greedy
ones. In ``--hosts`` mode each decode host writes a per-incarnation
part file ``<out>.h<rank>.r<restart>`` instead (a restarted process
never appends to a file a SIGKILL may have torn mid-line); ``_done_ids``
merges base + parts and skips torn trailing lines. Exit status follows
the supervisor contract: 0 clean, 75 on a watchdog abort, anything else
is a crash.

Signal contract: **SIGUSR1 requests a graceful drain** (same contract
as serve_lm.py; ``classify_exit`` bills neither the exit nor an
unhandled -SIGUSR1 to the crash budget). Router mode sheds the
never-placed backlog (``Router.shed_pending``) and finishes every
in-flight stream — in-flight sessions on a replica being RETIRED move
with ``Router.drain``'s live migration, not this signal; the
disaggregated and ``--hosts`` modes finish their in-flight sessions.
Either way the process flushes its reports and exits 0, and the shed
ids are re-submitted by the next incarnation's idempotent replay.

**SIGHUP requests a live rolling weight update** (router mode): with
``--rollout PATH`` naming a published candidate snapshot, the serving
loop runs ``fleet.RolloutController`` over the live router — bitwise
canary gate, chunked relay, per-replica DRAIN → SWAP → READMIT — while
traffic keeps flowing; the JSONL stays idempotent across the swap. On
a COMPLETED rollout the candidate is atomically re-published to
``--weights``, so a later restart warm-loads the new version; a
SIGKILL inside the rollout window classifies as a crash
(``classify_exit``) and the supervised restart converges to whichever
version its verified local manifest names — the new one after the
publish commit point, the old one before it. A canary miscompare or a
relay failure leaves (or rolls back to) the incumbent version, fleet
still serving. SIGHUP without ``--rollout`` is logged and ignored.
"""

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _log(msg):
    print(f"fleet_lm: {msg}", file=sys.stderr, flush=True)


def _done_ids(path):
    """Request ids already drained to the JSONL — the base file plus any
    per-host/per-incarnation part files (``--hosts`` mode). A SIGKILLed
    incarnation can leave its newest line torn, so undecodable lines are
    skipped: the request they would have recorded re-runs, and seeded
    replay makes the re-run emit the identical stream."""
    done = set()
    for p in [path] + sorted(glob.glob(path + ".h*")):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    done.add(json.loads(line)["request_id"])
                except (ValueError, KeyError):
                    continue     # torn trailing line from a killed run
    return done


def _emit(out, i, prompt, tokens, reason=None):
    rec = {"request_id": i, "prompt": prompt.tolist(),
           "tokens": list(tokens)}
    if reason is not None:
        # the stream fell back to a clean re-prefill; say WHY — the
        # per-frame defect history the transport attached to the failure
        rec["fallback_reason"] = reason
    out.write(json.dumps(rec) + "\n")
    out.flush()
    os.fsync(out.fileno())


def _engine_factory(args):
    """Shared model/params/engine construction. Params come from the
    seeded init (identical in every process — the cross-host bitwise
    contract needs no weight shipping) unless ``--weights`` names a
    published snapshot to warm-load or cold-publish."""
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (Engine, EngineConfig,
                                       load_weights, publish_weights)
    from chainermn_tpu.serving.weights import WeightsError

    model = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                          n_heads=args.n_heads, n_layers=args.n_layers,
                          d_ff=2 * args.d_model, max_len=args.capacity,
                          attention="reference", pos_emb="rope")
    init = model.init(jax.random.PRNGKey(args.seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
    if args.weights:
        try:
            params, src = load_weights(args.weights, like=init)
            _log(f"warm weights loaded from {src}")
        except WeightsError:
            params = init
            publish_weights(params, args.weights)
            _log(f"cold boot: published weights to {args.weights}")
    else:
        params = init

    def make(p=None, weights_version=None):
        # decode_k=1 so kill_replica@step=N counts one token per
        # working iteration — the drill timing contract (serve_lm.py)
        return Engine(model, params if p is None else p,
                      EngineConfig(n_slots=args.slots,
                                   capacity=args.capacity,
                                   max_new_tokens=args.max_new_tokens,
                                   prefill_cohort=1,
                                   buckets=[args.prompt_len,
                                            args.capacity],
                                   decode_k=args.decode_k,
                                   prefill_chunk=args.prefill_chunk),
                      weights_version=weights_version)

    def engine():
        return make()

    # the rollout path (SIGHUP + --rollout) needs the template params
    # and a versioned-engine constructor alongside the plain factory
    engine.make = make
    engine.params = params
    return engine


def _pending_prompts(args):
    """The deterministic request batch minus what prior incarnations
    already drained (base JSONL + any ``--hosts`` part files)."""
    import numpy as np

    done = _done_ids(args.out)
    rng = np.random.RandomState(args.seed)
    prompts = {}
    for i in range(args.requests):
        prompt = rng.randint(0, args.vocab,
                             (args.prompt_len,)).astype(np.int32)
        if i not in done:
            prompts[i] = prompt
    _log(f"queued {len(prompts)} of {args.requests} requests "
         f"({len(done)} already drained)")
    return prompts


def _drain_flag():
    """Install the SIGUSR1 graceful-drain handler (module docstring).
    The handler only flips the flag; serving loops act on it at their
    next iteration boundary, so a signal never tears engine state."""
    import signal

    drain = {"requested": False}

    def _on_drain(signum, frame):
        drain["requested"] = True

    try:
        signal.signal(signal.SIGUSR1, _on_drain)
    except ValueError:
        pass                           # not the main thread (tests)
    return drain


def _reload_flag():
    """Install the SIGHUP live-reload handler (module docstring): the
    handler only flips the flag; the router serving loop runs the
    rollout at its next iteration boundary, never mid-step."""
    import signal

    reload_ = {"requested": False}

    def _on_reload(signum, frame):
        reload_["requested"] = True

    try:
        signal.signal(signal.SIGHUP, _on_reload)
    except (ValueError, AttributeError):
        pass                           # not the main thread / no SIGHUP
    return reload_


def _run_rollout(args, router, fab):
    """One SIGHUP-triggered rolling update over the live router: load
    the ``--rollout`` candidate (manifest-verified), mint the canary
    oracle greedy off-traffic on a reference engine holding it, then
    walk the fleet CANARY → DRAIN → SWAP → READMIT. On a COMPLETED
    walk the candidate re-publishes atomically to ``--weights`` — the
    commit point a supervised restart converges from."""
    import numpy as np

    from chainermn_tpu.fleet import RolloutController
    from chainermn_tpu.serving import load_weights, publish_weights
    from chainermn_tpu.serving.weights import WeightsError

    try:
        v2, src = load_weights(args.rollout, like=fab.params)
    except WeightsError as e:
        _log(f"rollout: candidate {args.rollout} refused ({e}); "
             "fleet untouched")
        return None
    version = os.path.basename(os.path.normpath(args.rollout))
    _log(f"rollout: candidate {version} verified from {src}")

    # the pinned canary prompt set: the first requests of the
    # deterministic batch, replayed GREEDY under fixed seeds
    rng = np.random.RandomState(args.seed)
    can_p = []
    for i in range(min(2, args.requests)):
        prompt = rng.randint(0, args.vocab,
                             (args.prompt_len,)).astype(np.int32)
        can_p.append((prompt.tolist(), args.seed + i,
                      args.max_new_tokens))
    oracle_eng = fab.make(v2, version)
    oreqs = [oracle_eng.submit(np.asarray(p, np.int32),
                               max_new_tokens=n, seed=s)
             for p, s, n in can_p]
    oracle_eng.run_until_drained()
    can_o = [list(r.tokens) for r in oreqs]

    rc = RolloutController(router, fab.make, like=fab.params)
    out = rc.rollout(v2, version, canary_prompts=can_p,
                     canary_oracle=can_o, from_version="v1")
    _log("rollout: " + json.dumps(
        {k: out[k] for k in ("status", "version", "swapped", "crashed",
                             "rolled_back", "reason")}, sort_keys=True))
    if out["status"] == "completed" and args.weights:
        publish_weights(v2, args.weights, weights_version=version)
        _log(f"rollout: published {version} to {args.weights}")
    return out


def serve(args):
    from chainermn_tpu.fleet import DisaggregatedFleet, FleetReport, Router
    from chainermn_tpu.serving import DeadlineExceeded

    if args.hosts:
        return serve_hosts(args)

    engine = _engine_factory(args)
    prompts = _pending_prompts(args)
    report = FleetReport()
    drain = _drain_flag()
    reload_ = _reload_flag()
    shed = False
    rolled = False
    kw = dict(max_new_tokens=args.max_new_tokens,
              temperature=args.temperature, top_k=args.top_k)

    if args.disaggregate:
        fleet = DisaggregatedFleet(engine(), engine(),
                                   wire_format=args.wire_format,
                                   report=report,
                                   async_conveyor=args.async_conveyor,
                                   streamed=args.streamed)
        streams = {i: fleet.submit(p, seed=args.seed + i, **kw)
                   for i, p in emit_order(prompts)}
        with open(args.out, "a") as out:
            emitted = set()
            while not fleet.idle():
                if drain["requested"] and not shed:
                    shed = True
                    _log("SIGUSR1: drain — finishing in-flight sessions")
                # each engine step syncs internally (int32 token pulls)
                fleet.step()  # dlint: disable=DL104
                for i, s in streams.items():
                    if s.finished and i not in emitted:
                        emitted.add(i)
                        _emit(out, i, prompts[i], s.tokens,
                              reason=s.fallback_reason)
        fleet.close()
        summary = fleet.summary()
    else:
        # a rollout's canary traces on the serving thread; co-located
        # worker heartbeats starve under the GIL, so give health a
        # compile-sized timeout when a live reload is on the table
        with Router([engine() for _ in range(args.replicas)],
                    max_queue_depth=args.max_queue_depth,
                    health_timeout_ms=(600_000 if args.rollout
                                       else None),
                    report=report) as router:
            futs = {i: router.submit(p, seed=args.seed + i, **kw)
                    for i, p in emit_order(prompts)}
            pending = dict(futs)
            with open(args.out, "a") as out:
                while pending:
                    if reload_["requested"] and not rolled:
                        reload_["requested"] = False
                        rolled = True
                        if args.rollout:
                            _run_rollout(args, router, engine)
                        else:
                            _log("SIGHUP ignored: no --rollout "
                                 "candidate named")
                    if drain["requested"] and not shed:
                        shed = True
                        n = router.shed_pending()
                        _log(f"SIGUSR1: drain — shed {n} queued "
                             "request(s), finishing in-flight streams")
                    for i in sorted(pending):
                        fut = pending[i]
                        if fut.cancelled():
                            del pending[i]   # shed: next incarnation's
                            continue         # replay re-submits it
                        try:
                            req = router.result(fut, timeout_ms=100)
                        except DeadlineExceeded:
                            continue     # still decoding; poll the rest
                        del pending[i]
                        _emit(out, i, prompts[i], req.tokens)
            summary = router.summary()

    _log(("drained (SIGUSR1 retirement); " if shed else "drained; ")
         + f"fleet report: {json.dumps(summary, sort_keys=True)}")
    if args.report:
        with open(args.report, "w") as f:
            f.write(json.dumps(summary, sort_keys=True))
    return None


#: control channel for the dynamic-ownership protocol (``--hosts``):
#: a prefill host announces ``{"kind": "expect", "sid": i}`` to the
#: decode host it picked BEFORE shipping data frames, and sends one
#: ``{"kind": "eof"}`` per decode host when its batch is drained.
CTRL_TAG = 7003


def _parse_endpoints(spec, n):
    """``host:port,host:port,...`` — one endpoint per rank. A bare
    ``:port`` binds/dials 127.0.0.1."""
    eps = []
    for part in spec.split(","):
        host, _, port = part.strip().rpartition(":")
        try:
            eps.append((host or "127.0.0.1", int(port)))
        except ValueError:
            raise SystemExit(f"bad --endpoints entry {part!r} "
                             "(want host:port)")
    if len(eps) != n:
        raise SystemExit(f"--endpoints names {len(eps)} endpoints "
                         f"for --hosts {n}")
    return eps


def _make_plane(args, rank, n):
    """The object-plane wire for ``--hosts`` mode: file-backed (``fs``,
    restart-tolerant by construction) or real TCP (``socket``, restart
    fencing via incarnation handshake + seq HWM)."""
    if args.transport == "socket":
        if not args.endpoints:
            raise SystemExit("--transport socket needs --endpoints")
        from chainermn_tpu.comm.socket_plane import SocketObjectPlane
        return SocketObjectPlane(_parse_endpoints(args.endpoints, n),
                                 rank)
    if not args.plane_dir:
        raise SystemExit("--hosts needs --plane-dir (the shared wire)")
    from chainermn_tpu.comm.object_plane import FsObjectPlane
    return FsObjectPlane(args.plane_dir, rank, n)


def serve_hosts(args):
    """One host of a REAL cross-process disaggregated fleet (m×n).

    Ranks 0..P-1 prefill; ranks P..N-1 decode. Any prefill host can
    feed any decode host: each ready handoff goes to the decode host
    with the fewest streams shipped to it so far, skipping hosts whose
    last send failed until they deliver again (the saturated-survivor
    precheck). Ownership is announced with an ``expect`` control frame
    on :data:`CTRL_TAG` before the data frames fly, so the receiving
    host can build the stream and start its arrival deadline; an
    ``eof`` per prefill rank closes the protocol. Decode hosts adopt
    (or, past ``--handoff-deadline-s``, fence + fall back to a clean
    re-prefill from seed) and append finished streams to their own
    per-incarnation part file. With ``--streamed`` the data frames are
    format-5 per-layer chunks + a closing manifest, reassembled by
    ``StreamAssembler`` — a chunk that misses its delivery budget
    fails assembly and re-prefills cleanly.

    The ``fs`` wire survives a SIGKILLed rank by construction (the
    jax.distributed coordinator cannot re-admit one — the whole point
    of this mode is surviving exactly that under the supervisor); the
    ``socket`` wire survives it via the reborn peer's incarnation
    handshake. After a prefill restart, a re-announced stream may pick
    a DIFFERENT decode host than the dead incarnation did; with one
    decode host (the drill topology) that is moot, with several the
    seeded replay keeps every emission bitwise and ``_done_ids``'s
    merge keeps the final JSONL idempotent.
    """
    from chainermn_tpu.fleet import FleetReport
    from chainermn_tpu.fleet.handoff import (HANDOFF_FORMAT_STREAMED,
                                             HandoffError, decode_handoff,
                                             decode_handoff_streamed,
                                             encode_handoff,
                                             encode_handoff_streamed,
                                             streamed_chunk_sid,
                                             streamed_wire_bytes)
    from chainermn_tpu.fleet.pools import (DecodePool, PrefillPool,
                                           Stream, StreamAssembler)
    from chainermn_tpu.fleet.transport import ObjectPlaneTransport
    from chainermn_tpu.resilience import chaos
    from chainermn_tpu.resilience.supervisor import restart_count

    if args.hosts < 2:
        raise SystemExit("--hosts needs at least 2 (1 prefill + 1 decode)")
    if not (0 <= args.host_rank < args.hosts):
        raise SystemExit(f"--host-rank {args.host_rank} outside "
                         f"[0, {args.hosts})")
    rank, n, P = args.host_rank, args.hosts, args.prefill_hosts
    if not (1 <= P < n):
        raise SystemExit(f"--prefill-hosts {P} outside [1, {n})")
    plane = _make_plane(args, rank, n)
    engine = _engine_factory(args)()
    prompts = _pending_prompts(args)
    report = FleetReport()
    drain = _drain_flag()              # SIGUSR1: finish in flight, exit 0
    kw = dict(temperature=args.temperature, top_k=args.top_k)
    budget_s = args.handoff_deadline_s + 120.0   # hard stop for any loop
    decode_ranks = list(range(P, n))

    def _ship(transport, sid, handoff):
        """Encode + send one handoff; returns the terminal status (the
        closing frame's, in streamed mode — a chunk that exhausts its
        budget is caught by the receiver's assembly check instead)."""
        if not args.streamed:
            manifest, blob = encode_handoff(handoff, args.wire_format)
            report.record_handoff(args.wire_format, len(blob))
            return transport.send(sid, manifest, blob)
        chunks, closing, closing_blob = encode_handoff_streamed(
            handoff, args.wire_format)
        report.record_handoff(args.wire_format,
                              streamed_wire_bytes(closing))
        for ci, (man, blob) in enumerate(chunks):
            transport.send(streamed_chunk_sid(sid, ci), man, blob)
        return transport.send(sid, closing, closing_blob)

    if rank < P:
        pool = PrefillPool(engine)
        transports = {r: ObjectPlaneTransport(plane, peer=r)
                      for r in decode_ranks}
        mine = {i: p for i, p in prompts.items() if i % P == rank}
        for i, p in emit_order(mine):
            pool.submit(Stream(i, p, args.max_new_tokens,
                               dict(kw, seed=args.seed + i)))
        shipped = {r: 0 for r in decode_ranks}
        suspect = set()                # last send failed: prefer others
        deadline = time.monotonic() + budget_s
        it = 0
        while not engine.idle() or engine.held:
            if drain.pop("requested", None):
                _log("SIGUSR1: drain — finishing in-flight prefills")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"prefill host failed to drain within {budget_s}s")
            # the drill's kill@step= SIGKILL lands here — between
            # engine steps, possibly with frames already in flight
            chaos.on_step(it)
            it += 1
            # export/encode below pulls every ready slot's pages to
            # host (np.asarray) — that IS the per-iteration sync
            pool.step()  # dlint: disable=DL104
            for stream, req in pool.ready():
                sid = stream.stream_id
                dest = min(decode_ranks,
                           key=lambda r: (r in suspect, shipped[r], r))
                plane.send_obj({"kind": "expect", "sid": sid}, dest,
                               tag=CTRL_TAG)
                status = _ship(transports[dest], sid, pool.export(req))
                shipped[dest] += 1
                if status == "failed":
                    report.record_fallback()
                    suspect.add(dest)
                    why = transports[dest].last_send_defects
                    _log(f"handoff stream={sid} -> h{dest}: failed "
                         f"({'; '.join(why) or 'no defect history'})")
                else:
                    suspect.discard(dest)
                    _log(f"handoff stream={sid} -> h{dest}: {status}")
                pool.release(req, aborted=(status == "failed"))
        for r in decode_ranks:
            plane.send_obj({"kind": "eof"}, r, tag=CTRL_TAG)
        for t in transports.values():
            report.record_transport(sender_stats=t.stats)
        report.record_transport(plane_stats=getattr(plane, "stats", {}))
        summary = report.summary([engine.report])
    else:
        pool = DecodePool(engine)
        transports = {r: ObjectPlaneTransport(plane, peer=r)
                      for r in range(P)}
        asm = StreamAssembler()
        streams = {}                   # sid → Stream (built on expect)
        src_of = {}                    # sid → announcing prefill rank
        expected, placed, emitted, eofs = set(), set(), set(), set()
        backlog = []
        part = f"{args.out}.h{rank}.r{restart_count()}"
        arrive_by = time.monotonic() + args.handoff_deadline_s
        deadline = time.monotonic() + budget_s

        def _fallback(sid, reason):
            report.record_fallback()
            pool.fallback(streams[sid], reason)
            placed.add(sid)

        with open(part, "a") as out:
            while len(eofs) < P or len(emitted) < len(expected):
                if drain.pop("requested", None):
                    _log("SIGUSR1: drain — finishing in-flight decodes")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"decode host {rank} failed to drain within "
                        f"{budget_s}s ({len(emitted)}/{len(expected)} "
                        f"expected, eof {len(eofs)}/{P})")
                for pr in range(P):
                    while True:
                        try:
                            msg = plane.try_recv_obj(pr, tag=CTRL_TAG,
                                                     timeout_ms=1)
                        except TimeoutError:
                            break
                        if msg.get("kind") == "eof":
                            eofs.add(pr)
                        elif msg.get("kind") == "expect":
                            sid = int(msg["sid"])
                            if sid in expected or sid not in prompts:
                                continue   # replay of a drained stream
                            expected.add(sid)
                            src_of[sid] = pr
                            streams[sid] = Stream(
                                sid, prompts[sid], args.max_new_tokens,
                                dict(kw, seed=args.seed + sid))
                for t in transports.values():
                    backlog.extend(t.poll(timeout_ms=10))
                still = []
                for arr in backlog:
                    if arr.stream_id < 0:
                        asm.add_chunk(arr)     # format-5 chunk frame
                        continue
                    sid = arr.stream_id
                    if sid in placed:
                        continue
                    if sid not in streams:
                        # data outran its expect frame (separate
                        # channel): hold until the announcement lands
                        still.append(arr)
                        continue
                    if arr.failed:
                        _, notes = asm.take(sid)
                        why = "; ".join(arr.defects) or "delivery failed"
                        if notes:
                            why += " [" + "; ".join(notes) + "]"
                        _fallback(sid, why)
                        continue
                    if not pool.has_room():
                        still.append(arr)   # adopted frame waits for room
                        continue
                    notes = []
                    try:
                        man = arr.manifest
                        if (isinstance(man, dict) and man.get("format")
                                == HANDOFF_FORMAT_STREAMED):
                            chunks, notes = asm.take(sid)
                            handoff = decode_handoff_streamed(
                                man, arr.blob, chunks)
                        else:
                            handoff = decode_handoff(man, arr.blob)
                        pool.place(streams[sid], handoff)
                        placed.add(sid)
                    except HandoffError as e:
                        # attach the per-chunk defect history: the
                        # fallback log says WHY the wire failed
                        why = str(e)
                        if notes:
                            why += " [" + "; ".join(notes) + "]"
                        _fallback(sid, why)
                backlog = still
                if time.monotonic() > arrive_by:
                    for sid in sorted(expected - placed):
                        # never arrived: fence the stream (a late frame
                        # now acks duplicate) and re-prefill from seed
                        transports[src_of[sid]].resolve(sid)
                        _fallback(sid, "missed the handoff deadline")
                        _log(f"stream {sid} missed the handoff "
                             f"deadline; fenced + re-prefilled")
                pool.step()
                for sid, s in streams.items():
                    if s.finished and sid not in emitted:
                        emitted.add(sid)
                        _emit(out, sid, prompts[sid], s.tokens,
                              reason=s.fallback_reason)
        for t in transports.values():
            report.record_transport(receiver_stats=t.receiver_stats)
        report.record_transport(plane_stats=getattr(plane, "stats", {}))
        summary = report.summary([engine.report])

    if hasattr(plane, "close"):
        plane.close()
    _log(f"host {rank} drained; report: "
         f"{json.dumps(summary, sort_keys=True)}")
    if args.report:
        wire = {"fleet": report.to_wire(),
                "serving": [engine.report.to_wire()]}
        with open(f"{args.report}.h{rank}", "w") as f:
            f.write(json.dumps(wire, sort_keys=True))
    return None


def emit_order(prompts):
    return sorted(prompts.items())


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fleet_lm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", required=True,
                    help="JSONL of completed streams (append, idempotent)")
    ap.add_argument("--weights", default=None,
                    help="published-weights path: warm-load when present, "
                         "publish on cold boot")
    ap.add_argument("--report", default=None,
                    help="write the merged FleetReport JSON here on drain")
    ap.add_argument("--replicas", type=int, default=2,
                    help="engine replicas behind the router")
    ap.add_argument("--disaggregate", action="store_true",
                    help="prefill/decode pools + KVHandoff instead of "
                         "the replicated router")
    ap.add_argument("--wire-format", default="f32",
                    choices=["f32", "int8-block"],
                    help="KVHandoff wire format (disaggregated mode)")
    ap.add_argument("--async-conveyor", action="store_true",
                    help="overlap handoff transfer with decode steps "
                         "(disaggregated mode, bounded worker queue)")
    ap.add_argument("--streamed", action="store_true",
                    help="ship handoffs as format-5 per-layer chunk "
                         "frames + a closing manifest (per-chunk "
                         "SHA/NACK/re-send granularity)")
    ap.add_argument("--hosts", type=int, default=0,
                    help="cross-PROCESS disaggregation over N hosts "
                         "(this process is one of them; see --host-rank)")
    ap.add_argument("--host-rank", type=int, default=0,
                    help="this process's rank in --hosts mode "
                         "(0..P-1 = prefill hosts, P..N-1 = decode "
                         "hosts; see --prefill-hosts)")
    ap.add_argument("--prefill-hosts", type=int, default=1,
                    help="P prefill ranks in --hosts mode: any prefill "
                         "host feeds any decode host (least-outstanding "
                         "destination choice)")
    ap.add_argument("--transport", default="fs",
                    choices=["fs", "socket"],
                    help="--hosts wire: 'fs' = on-disk FsObjectPlane "
                         "under --plane-dir; 'socket' = TCP "
                         "SocketObjectPlane over --endpoints")
    ap.add_argument("--endpoints", default=None,
                    help="comma list of host:port, one per rank "
                         "(--transport socket)")
    ap.add_argument("--plane-dir", default=None,
                    help="shared directory backing the FsObjectPlane "
                         "wire (--hosts mode, --transport fs)")
    ap.add_argument("--handoff-deadline-s", type=float, default=30.0,
                    help="decode-host budget for a stream's handoff to "
                         "arrive before fencing it and re-prefilling "
                         "from seed (--hosts mode)")
    ap.add_argument("--rollout", default=None,
                    help="published candidate-weights path for the "
                         "SIGHUP-triggered live rolling update "
                         "(router mode; see the signal contract)")
    ap.add_argument("--max-queue-depth", type=int, default=None,
                    help="per-replica admission bound (router mode)")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--prompt-len", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--capacity", type=int, default=32)
    ap.add_argument("--decode-k", type=int, default=1,
                    help="tokens committed per decode dispatch")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked prefill width (default: monolithic)")
    ap.add_argument("--temperature", type=float, default=None,
                    help="sampling temperature (default: greedy argmax)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="top-k truncation for sampled decode")
    ap.add_argument("--vocab", type=int, default=43)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from chainermn_tpu.resilience.supervisor import main_exit_code

    return main_exit_code(lambda: serve(args))


if __name__ == "__main__":
    sys.exit(main())

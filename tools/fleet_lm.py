#!/usr/bin/env python
"""fleet_lm — a serving FLEET in one process: N engine replicas behind
the router, or a disaggregated prefill/decode pair.

Builds one seeded TransformerLM, shares its weights across every
replica (warm-loading from a published snapshot when one exists, like
serve_lm.py), queues a deterministic batch of prompts, and drains:

* default — ``fleet.Router`` over ``--replicas`` engines, each in its
  own worker thread: load-aware + session-affine placement, queue-depth
  backpressure, and heartbeat-driven replica health. With
  ``$CHAINERMN_TPU_CHAOS='kill_replica@step=N,replica=R'`` the targeted
  worker dies mid-stream and the router re-queues its slots onto
  survivors — the drill asserts every stream still completes with zero
  dropped or duplicated tokens (seeded replay, serving/sampling.py).
* ``--disaggregate`` — ``fleet.DisaggregatedFleet``: prefill engine →
  KVHandoff wire (``--wire-format`` f32 | int8-block) → decode engine,
  exposed to ``corrupt_handoff`` faults (fallback = clean re-prefill).
  Add ``--async-conveyor`` to overlap the wire with decode steps.
* ``--hosts N --host-rank R --plane-dir D`` — REAL cross-process
  disaggregation: rank 0 prefills and ships seq/SHA-framed handoffs
  over the restart-tolerant ``FsObjectPlane`` wire
  (``fleet.ObjectPlaneTransport``); ranks 1..N-1 adopt and decode.
  Wire-level chaos (``drop_handoff``/``delay_handoff``/``dup_handoff``/
  ``corrupt_handoff``) tears at the frames in flight; ``kill@step=``
  SIGKILLs the prefill process mid-transfer — under
  ``resilience.Supervisor`` the restarted incarnation re-prefills
  every unfinished stream and the receivers' fences answer already-
  adopted replays with duplicate acks (zero dropped or duplicated
  tokens).

Completed streams append to ``--out`` idempotently (request ids already
on disk are skipped), so a supervised restart heals to the same final
JSONL the unkilled run would have produced — per-request seeds are
``--seed + request_id``, making sampled streams as replayable as greedy
ones. In ``--hosts`` mode each decode host writes a per-incarnation
part file ``<out>.h<rank>.r<restart>`` instead (a restarted process
never appends to a file a SIGKILL may have torn mid-line); ``_done_ids``
merges base + parts and skips torn trailing lines. Exit status follows
the supervisor contract: 0 clean, 75 on a watchdog abort, anything else
is a crash.

Signal contract: **SIGUSR1 requests a graceful drain** (same contract
as serve_lm.py; ``classify_exit`` bills neither the exit nor an
unhandled -SIGUSR1 to the crash budget). Router mode sheds the
never-placed backlog (``Router.shed_pending``) and finishes every
in-flight stream — in-flight sessions on a replica being RETIRED move
with ``Router.drain``'s live migration, not this signal; the
disaggregated and ``--hosts`` modes finish their in-flight sessions.
Either way the process flushes its reports and exits 0, and the shed
ids are re-submitted by the next incarnation's idempotent replay.
"""

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _log(msg):
    print(f"fleet_lm: {msg}", file=sys.stderr, flush=True)


def _done_ids(path):
    """Request ids already drained to the JSONL — the base file plus any
    per-host/per-incarnation part files (``--hosts`` mode). A SIGKILLed
    incarnation can leave its newest line torn, so undecodable lines are
    skipped: the request they would have recorded re-runs, and seeded
    replay makes the re-run emit the identical stream."""
    done = set()
    for p in [path] + sorted(glob.glob(path + ".h*")):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    done.add(json.loads(line)["request_id"])
                except (ValueError, KeyError):
                    continue     # torn trailing line from a killed run
    return done


def _emit(out, i, prompt, tokens):
    out.write(json.dumps({"request_id": i, "prompt": prompt.tolist(),
                          "tokens": list(tokens)}) + "\n")
    out.flush()
    os.fsync(out.fileno())


def _engine_factory(args):
    """Shared model/params/engine construction. Params come from the
    seeded init (identical in every process — the cross-host bitwise
    contract needs no weight shipping) unless ``--weights`` names a
    published snapshot to warm-load or cold-publish."""
    import jax
    import jax.numpy as jnp

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (Engine, EngineConfig,
                                       load_weights, publish_weights)
    from chainermn_tpu.serving.weights import WeightsError

    model = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                          n_heads=args.n_heads, n_layers=args.n_layers,
                          d_ff=2 * args.d_model, max_len=args.capacity,
                          attention="reference", pos_emb="rope")
    init = model.init(jax.random.PRNGKey(args.seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
    if args.weights:
        try:
            params, src = load_weights(args.weights, like=init)
            _log(f"warm weights loaded from {src}")
        except WeightsError:
            params = init
            publish_weights(params, args.weights)
            _log(f"cold boot: published weights to {args.weights}")
    else:
        params = init

    def engine():
        # decode_k=1 so kill_replica@step=N counts one token per
        # working iteration — the drill timing contract (serve_lm.py)
        return Engine(model, params,
                      EngineConfig(n_slots=args.slots,
                                   capacity=args.capacity,
                                   max_new_tokens=args.max_new_tokens,
                                   prefill_cohort=1,
                                   buckets=[args.prompt_len,
                                            args.capacity],
                                   decode_k=args.decode_k,
                                   prefill_chunk=args.prefill_chunk))

    return engine


def _pending_prompts(args):
    """The deterministic request batch minus what prior incarnations
    already drained (base JSONL + any ``--hosts`` part files)."""
    import numpy as np

    done = _done_ids(args.out)
    rng = np.random.RandomState(args.seed)
    prompts = {}
    for i in range(args.requests):
        prompt = rng.randint(0, args.vocab,
                             (args.prompt_len,)).astype(np.int32)
        if i not in done:
            prompts[i] = prompt
    _log(f"queued {len(prompts)} of {args.requests} requests "
         f"({len(done)} already drained)")
    return prompts


def _drain_flag():
    """Install the SIGUSR1 graceful-drain handler (module docstring).
    The handler only flips the flag; serving loops act on it at their
    next iteration boundary, so a signal never tears engine state."""
    import signal

    drain = {"requested": False}

    def _on_drain(signum, frame):
        drain["requested"] = True

    try:
        signal.signal(signal.SIGUSR1, _on_drain)
    except ValueError:
        pass                           # not the main thread (tests)
    return drain


def serve(args):
    from chainermn_tpu.fleet import DisaggregatedFleet, FleetReport, Router
    from chainermn_tpu.serving import DeadlineExceeded

    if args.hosts:
        return serve_hosts(args)

    engine = _engine_factory(args)
    prompts = _pending_prompts(args)
    report = FleetReport()
    drain = _drain_flag()
    shed = False
    kw = dict(max_new_tokens=args.max_new_tokens,
              temperature=args.temperature, top_k=args.top_k)

    if args.disaggregate:
        fleet = DisaggregatedFleet(engine(), engine(),
                                   wire_format=args.wire_format,
                                   report=report,
                                   async_conveyor=args.async_conveyor)
        streams = {i: fleet.submit(p, seed=args.seed + i, **kw)
                   for i, p in emit_order(prompts)}
        with open(args.out, "a") as out:
            emitted = set()
            while not fleet.idle():
                if drain["requested"] and not shed:
                    shed = True
                    _log("SIGUSR1: drain — finishing in-flight sessions")
                # each engine step syncs internally (int32 token pulls)
                fleet.step()  # dlint: disable=DL104
                for i, s in streams.items():
                    if s.finished and i not in emitted:
                        emitted.add(i)
                        _emit(out, i, prompts[i], s.tokens)
        fleet.close()
        summary = fleet.summary()
    else:
        with Router([engine() for _ in range(args.replicas)],
                    max_queue_depth=args.max_queue_depth,
                    report=report) as router:
            futs = {i: router.submit(p, seed=args.seed + i, **kw)
                    for i, p in emit_order(prompts)}
            pending = dict(futs)
            with open(args.out, "a") as out:
                while pending:
                    if drain["requested"] and not shed:
                        shed = True
                        n = router.shed_pending()
                        _log(f"SIGUSR1: drain — shed {n} queued "
                             "request(s), finishing in-flight streams")
                    for i in sorted(pending):
                        fut = pending[i]
                        if fut.cancelled():
                            del pending[i]   # shed: next incarnation's
                            continue         # replay re-submits it
                        try:
                            req = router.result(fut, timeout_ms=100)
                        except DeadlineExceeded:
                            continue     # still decoding; poll the rest
                        del pending[i]
                        _emit(out, i, prompts[i], req.tokens)
            summary = router.summary()

    _log(("drained (SIGUSR1 retirement); " if shed else "drained; ")
         + f"fleet report: {json.dumps(summary, sort_keys=True)}")
    if args.report:
        with open(args.report, "w") as f:
            f.write(json.dumps(summary, sort_keys=True))
    return None


def serve_hosts(args):
    """One host of a REAL cross-process disaggregated fleet.

    Rank 0 prefills every pending stream and ships handoffs to their
    owner decode hosts (stream ``i`` belongs to rank ``1 + i % (N-1)``)
    over ``ObjectPlaneTransport`` frames on the ``FsObjectPlane`` wire
    — the file-backed plane, because the jax.distributed coordinator
    cannot re-admit a SIGKILLed rank and the whole point of this mode
    is surviving exactly that under the supervisor. Decode hosts adopt
    (or, past ``--handoff-deadline-s``, fence + fall back to a clean
    re-prefill from seed) and append finished streams to their own
    per-incarnation part file.
    """
    from chainermn_tpu.comm.object_plane import FsObjectPlane
    from chainermn_tpu.fleet import FleetReport
    from chainermn_tpu.fleet.handoff import (HandoffError, decode_handoff,
                                             encode_handoff)
    from chainermn_tpu.fleet.pools import DecodePool, PrefillPool, Stream
    from chainermn_tpu.fleet.transport import ObjectPlaneTransport
    from chainermn_tpu.resilience import chaos
    from chainermn_tpu.resilience.supervisor import restart_count

    if args.hosts < 2:
        raise SystemExit("--hosts needs at least 2 (1 prefill + 1 decode)")
    if not (0 <= args.host_rank < args.hosts):
        raise SystemExit(f"--host-rank {args.host_rank} outside "
                         f"[0, {args.hosts})")
    if not args.plane_dir:
        raise SystemExit("--hosts needs --plane-dir (the shared wire)")
    rank, n = args.host_rank, args.hosts
    plane = FsObjectPlane(args.plane_dir, rank, n)
    engine = _engine_factory(args)()
    prompts = _pending_prompts(args)
    report = FleetReport()
    drain = _drain_flag()              # SIGUSR1: finish in flight, exit 0
    owner = lambda i: 1 + (i % (n - 1))  # noqa: E731 — one-line mapping
    kw = dict(temperature=args.temperature, top_k=args.top_k)
    budget_s = args.handoff_deadline_s + 120.0   # hard stop for any loop

    if rank == 0:
        pool = PrefillPool(engine)
        transports = {r: ObjectPlaneTransport(plane, peer=r)
                      for r in range(1, n)}
        for i, p in emit_order(prompts):
            pool.submit(Stream(i, p, args.max_new_tokens,
                               dict(kw, seed=args.seed + i)))
        deadline = time.monotonic() + budget_s
        it = 0
        while not engine.idle() or engine.held:
            if drain.pop("requested", None):
                _log("SIGUSR1: drain — finishing in-flight prefills")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"prefill host failed to drain within {budget_s}s")
            # the drill's kill@step= SIGKILL lands here — between
            # engine steps, possibly with frames already in flight
            chaos.on_step(it)
            it += 1
            # export/encode below pulls every ready slot's pages to
            # host (np.asarray) — that IS the per-iteration sync
            pool.step()  # dlint: disable=DL104
            for stream, req in pool.ready():
                handoff = pool.export(req)
                manifest, blob = encode_handoff(handoff, args.wire_format)
                report.record_handoff(args.wire_format, len(blob))
                status = transports[owner(stream.stream_id)].send(
                    stream.stream_id, manifest, blob)
                if status == "failed":
                    report.record_fallback()
                pool.release(req, aborted=(status == "failed"))
                _log(f"handoff stream={stream.stream_id} -> "
                     f"h{owner(stream.stream_id)}: {status}")
        summary = report.summary([engine.report])
    else:
        pool = DecodePool(engine)
        transport = ObjectPlaneTransport(plane, peer=0)
        owned = {i: p for i, p in prompts.items() if owner(i) == rank}
        streams = {i: Stream(i, p, args.max_new_tokens,
                             dict(kw, seed=args.seed + i))
                   for i, p in owned.items()}
        part = f"{args.out}.h{rank}.r{restart_count()}"
        arrive_by = time.monotonic() + args.handoff_deadline_s
        deadline = time.monotonic() + budget_s
        placed, emitted, backlog = set(), set(), []
        with open(part, "a") as out:
            while len(emitted) < len(owned):
                if drain.pop("requested", None):
                    _log("SIGUSR1: drain — finishing in-flight decodes")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"decode host {rank} failed to drain within "
                        f"{budget_s}s ({len(emitted)}/{len(owned)})")
                backlog.extend(transport.poll(timeout_ms=20))
                still = []
                for arr in backlog:
                    s = streams.get(arr.stream_id)
                    if s is None or arr.stream_id in placed:
                        continue
                    if arr.failed:
                        report.record_fallback()
                        pool.fallback(s)
                    elif pool.has_room():
                        try:
                            pool.place(s, decode_handoff(arr.manifest,
                                                         arr.blob))
                        except HandoffError:
                            report.record_fallback()
                            pool.fallback(s)
                    else:
                        still.append(arr)   # adopted frame waits for room
                        continue
                    placed.add(arr.stream_id)
                backlog = still
                if time.monotonic() > arrive_by:
                    for i in sorted(set(owned) - placed):
                        # never arrived: fence the stream (a late frame
                        # now acks duplicate) and re-prefill from seed
                        transport.resolve(i)
                        report.record_fallback()
                        pool.fallback(streams[i])
                        placed.add(i)
                        _log(f"stream {i} missed the handoff deadline; "
                             f"fenced + re-prefilled")
                # each engine step syncs internally (int32 token pulls)
                pool.step()  # dlint: disable=DL104
                for i, s in streams.items():
                    if s.finished and i not in emitted:
                        emitted.add(i)
                        _emit(out, i, owned[i], s.tokens)
        summary = report.summary([engine.report])

    _log(f"host {rank} drained; report: "
         f"{json.dumps(summary, sort_keys=True)}")
    if args.report:
        wire = {"fleet": report.to_wire(),
                "serving": [engine.report.to_wire()]}
        with open(f"{args.report}.h{rank}", "w") as f:
            f.write(json.dumps(wire, sort_keys=True))
    return None


def emit_order(prompts):
    return sorted(prompts.items())


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fleet_lm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", required=True,
                    help="JSONL of completed streams (append, idempotent)")
    ap.add_argument("--weights", default=None,
                    help="published-weights path: warm-load when present, "
                         "publish on cold boot")
    ap.add_argument("--report", default=None,
                    help="write the merged FleetReport JSON here on drain")
    ap.add_argument("--replicas", type=int, default=2,
                    help="engine replicas behind the router")
    ap.add_argument("--disaggregate", action="store_true",
                    help="prefill/decode pools + KVHandoff instead of "
                         "the replicated router")
    ap.add_argument("--wire-format", default="f32",
                    choices=["f32", "int8-block"],
                    help="KVHandoff wire format (disaggregated mode)")
    ap.add_argument("--async-conveyor", action="store_true",
                    help="overlap handoff transfer with decode steps "
                         "(disaggregated mode, bounded worker queue)")
    ap.add_argument("--hosts", type=int, default=0,
                    help="cross-PROCESS disaggregation over N hosts "
                         "(this process is one of them; see --host-rank)")
    ap.add_argument("--host-rank", type=int, default=0,
                    help="this process's rank in --hosts mode "
                         "(0 = prefill host, 1..N-1 = decode hosts)")
    ap.add_argument("--plane-dir", default=None,
                    help="shared directory backing the FsObjectPlane "
                         "wire (--hosts mode)")
    ap.add_argument("--handoff-deadline-s", type=float, default=30.0,
                    help="decode-host budget for a stream's handoff to "
                         "arrive before fencing it and re-prefilling "
                         "from seed (--hosts mode)")
    ap.add_argument("--max-queue-depth", type=int, default=None,
                    help="per-replica admission bound (router mode)")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--prompt-len", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--capacity", type=int, default=32)
    ap.add_argument("--decode-k", type=int, default=1,
                    help="tokens committed per decode dispatch")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked prefill width (default: monolithic)")
    ap.add_argument("--temperature", type=float, default=None,
                    help="sampling temperature (default: greedy argmax)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="top-k truncation for sampled decode")
    ap.add_argument("--vocab", type=int, default=43)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from chainermn_tpu.resilience.supervisor import main_exit_code

    return main_exit_code(lambda: serve(args))


if __name__ == "__main__":
    sys.exit(main())

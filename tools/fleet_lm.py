#!/usr/bin/env python
"""fleet_lm — a serving FLEET in one process: N engine replicas behind
the router, or a disaggregated prefill/decode pair.

Builds one seeded TransformerLM, shares its weights across every
replica (warm-loading from a published snapshot when one exists, like
serve_lm.py), queues a deterministic batch of prompts, and drains:

* default — ``fleet.Router`` over ``--replicas`` engines, each in its
  own worker thread: load-aware + session-affine placement, queue-depth
  backpressure, and heartbeat-driven replica health. With
  ``$CHAINERMN_TPU_CHAOS='kill_replica@step=N,replica=R'`` the targeted
  worker dies mid-stream and the router re-queues its slots onto
  survivors — the drill asserts every stream still completes with zero
  dropped or duplicated tokens (seeded replay, serving/sampling.py).
* ``--disaggregate`` — ``fleet.DisaggregatedFleet``: prefill engine →
  KVHandoff wire (``--wire-format`` f32 | int8-block) → decode engine,
  exposed to ``corrupt_handoff`` faults (fallback = clean re-prefill).

Completed streams append to ``--out`` idempotently (request ids already
on disk are skipped), so a supervised restart heals to the same final
JSONL the unkilled run would have produced — per-request seeds are
``--seed + request_id``, making sampled streams as replayable as greedy
ones. Exit status follows the supervisor contract: 0 clean, 75 on a
watchdog abort, anything else is a crash.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _log(msg):
    print(f"fleet_lm: {msg}", file=sys.stderr, flush=True)


def _done_ids(path):
    """Request ids already drained to the JSONL (prior incarnations)."""
    done = set()
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    done.add(json.loads(line)["request_id"])
    return done


def _emit(out, i, prompt, tokens):
    out.write(json.dumps({"request_id": i, "prompt": prompt.tolist(),
                          "tokens": list(tokens)}) + "\n")
    out.flush()
    os.fsync(out.fileno())


def serve(args):
    import numpy as np

    import jax
    import jax.numpy as jnp

    from chainermn_tpu.fleet import DisaggregatedFleet, FleetReport, Router
    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import (Engine, EngineConfig,
                                       load_weights, publish_weights)
    from chainermn_tpu.serving.weights import WeightsError

    model = TransformerLM(vocab=args.vocab, d_model=args.d_model,
                          n_heads=args.n_heads, n_layers=args.n_layers,
                          d_ff=2 * args.d_model, max_len=args.capacity,
                          attention="reference", pos_emb="rope")
    init = model.init(jax.random.PRNGKey(args.seed),
                      jnp.zeros((1, 4), jnp.int32))["params"]
    if args.weights:
        try:
            params, src = load_weights(args.weights, like=init)
            _log(f"warm weights loaded from {src}")
        except WeightsError:
            params = init
            publish_weights(params, args.weights)
            _log(f"cold boot: published weights to {args.weights}")
    else:
        params = init

    def engine():
        # decode_k=1 so kill_replica@step=N counts one token per
        # working iteration — the drill timing contract (serve_lm.py)
        return Engine(model, params,
                      EngineConfig(n_slots=args.slots,
                                   capacity=args.capacity,
                                   max_new_tokens=args.max_new_tokens,
                                   prefill_cohort=1,
                                   buckets=[args.prompt_len,
                                            args.capacity],
                                   decode_k=args.decode_k,
                                   prefill_chunk=args.prefill_chunk))

    done = _done_ids(args.out)
    rng = np.random.RandomState(args.seed)
    prompts = {}
    for i in range(args.requests):
        prompt = rng.randint(0, args.vocab,
                             (args.prompt_len,)).astype(np.int32)
        if i not in done:
            prompts[i] = prompt
    _log(f"queued {len(prompts)} of {args.requests} requests "
         f"({len(done)} already drained)")

    report = FleetReport()
    kw = dict(max_new_tokens=args.max_new_tokens,
              temperature=args.temperature, top_k=args.top_k)

    if args.disaggregate:
        fleet = DisaggregatedFleet(engine(), engine(),
                                   wire_format=args.wire_format,
                                   report=report)
        streams = {i: fleet.submit(p, seed=args.seed + i, **kw)
                   for i, p in emit_order(prompts)}
        with open(args.out, "a") as out:
            emitted = set()
            while not fleet.idle():
                # each engine step syncs internally (int32 token pulls)
                fleet.step()  # dlint: disable=DL104
                for i, s in streams.items():
                    if s.finished and i not in emitted:
                        emitted.add(i)
                        _emit(out, i, prompts[i], s.tokens)
        summary = fleet.summary()
    else:
        with Router([engine() for _ in range(args.replicas)],
                    max_queue_depth=args.max_queue_depth,
                    report=report) as router:
            futs = {i: router.submit(p, seed=args.seed + i, **kw)
                    for i, p in emit_order(prompts)}
            with open(args.out, "a") as out:
                for i, fut in futs.items():
                    req = router.result(fut)
                    _emit(out, i, prompts[i], req.tokens)
            summary = router.summary()

    _log(f"drained; fleet report: {json.dumps(summary, sort_keys=True)}")
    if args.report:
        with open(args.report, "w") as f:
            f.write(json.dumps(summary, sort_keys=True))
    return None


def emit_order(prompts):
    return sorted(prompts.items())


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fleet_lm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--out", required=True,
                    help="JSONL of completed streams (append, idempotent)")
    ap.add_argument("--weights", default=None,
                    help="published-weights path: warm-load when present, "
                         "publish on cold boot")
    ap.add_argument("--report", default=None,
                    help="write the merged FleetReport JSON here on drain")
    ap.add_argument("--replicas", type=int, default=2,
                    help="engine replicas behind the router")
    ap.add_argument("--disaggregate", action="store_true",
                    help="prefill/decode pools + KVHandoff instead of "
                         "the replicated router")
    ap.add_argument("--wire-format", default="f32",
                    choices=["f32", "int8-block"],
                    help="KVHandoff wire format (disaggregated mode)")
    ap.add_argument("--max-queue-depth", type=int, default=None,
                    help="per-replica admission bound (router mode)")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--prompt-len", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--capacity", type=int, default=32)
    ap.add_argument("--decode-k", type=int, default=1,
                    help="tokens committed per decode dispatch")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked prefill width (default: monolithic)")
    ap.add_argument("--temperature", type=float, default=None,
                    help="sampling temperature (default: greedy argmax)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="top-k truncation for sampled decode")
    ap.add_argument("--vocab", type=int, default=43)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from chainermn_tpu.resilience.supervisor import main_exit_code

    return main_exit_code(lambda: serve(args))


if __name__ == "__main__":
    sys.exit(main())

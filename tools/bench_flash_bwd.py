#!/usr/bin/env python
"""Flash-attention BACKWARD block sweep at the LM bench shape.

The (1024, 1024) defaults were tuned on the FORWARD kernel (BASELINE.md
§flash); the backward kernels hold 4 live [bq, bk] f32 intermediates
(s, p, dp, ds) instead of 2 and may prefer different tiles. Times the
full vjp (fwd+bwd) AND fwd-only per config, scan-amortized inside one
jit (memory: ~7.5 ms per async dispatch, ~100 ms per sync — see
BASELINE.md methodology), warm 3 executions.

Usage: python tools/bench_flash_bwd.py [B H L D [K]]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from chainermn_tpu.ops.flash_attention import flash_attention

    B = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    H = int(sys.argv[2]) if len(sys.argv) > 2 else 12
    L = int(sys.argv[3]) if len(sys.argv) > 3 else 2048
    D = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    K = int(sys.argv[5]) if len(sys.argv) > 5 else 20

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, L, H, D) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, L, H, D) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, L, H, D) * 0.3, jnp.bfloat16)

    def timed(fn):
        """K carry-dependent iterations inside one jit; report s/iter."""
        def loop(q, k, v):
            def body(c, _):
                qq, kk, vv = c
                o = fn(qq, kk, vv)
                # carry dependence without changing magnitudes
                return (qq + 0.0 * o[0], kk, vv), ()
            (qq, _, _), _ = lax.scan(body, (q, k, v), None, length=K)
            return qq
        j = jax.jit(loop)
        for _ in range(3):
            r = j(q, k, v)
            float(jnp.sum(r[0, 0].astype(jnp.float32)))
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            r = j(q, k, v)
            float(jnp.sum(r[0, 0].astype(jnp.float32)))
        return (time.perf_counter() - t0) / (reps * K)

    def grad_fn(fwd_blocks, bwd_blocks):
        def f(q, k, v):
            def loss(q, k, v):
                o = flash_attention(
                    q, k, v, True, None, fwd_blocks[0], fwd_blocks[1],
                    None, None, None, bwd_blocks)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return g[0]
        return f

    def fwd_fn(blocks):
        return lambda q, k, v: flash_attention(
            q, k, v, True, None, blocks[0], blocks[1])

    results = []
    fwd_grid = [(1024, 1024), (512, 1024), (512, 512), (256, 1024)]
    for fb in fwd_grid:
        s = timed(fwd_fn(fb))
        results.append({"kind": "fwd", "blocks": fb, "ms": s * 1e3})
        print(json.dumps(results[-1]), flush=True)

    bwd_grid = [(1024, 1024), (512, 1024), (1024, 512), (512, 512),
                (256, 1024), (256, 512), (128, 1024), (2048, 512),
                (512, 2048), (256, 256)]
    best_fwd = min((r for r in results if r["kind"] == "fwd"),
                   key=lambda r: r["ms"])["blocks"]
    for bb in bwd_grid:
        s = timed(grad_fn(tuple(best_fwd), bb))
        results.append({"kind": "fwd+bwd", "fwd_blocks": best_fwd,
                        "bwd_blocks": bb, "ms": s * 1e3})
        print(json.dumps(results[-1]), flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""chaos — launch (or validate) fault-injected training runs.

The chaos harness (:mod:`chainermn_tpu.resilience.chaos`) activates when
``$CHAINERMN_TPU_CHAOS`` holds a fault spec; workers read it at hook
sites inside the trainer loop, the object plane's KV RPCs, and the
checkpoint publish path — so the SAME binary runs clean or faulted,
deterministically per (spec, seed, rank). This tool is the front door:
validate a spec, print the fault catalogue, or exec a training command
with the spec injected into its environment.

Usage::

    python tools/chaos.py --dry-run --spec 'kill@step=3,rank=1'
    python tools/chaos.py --list-faults
    python tools/chaos.py --spec 'delay_rpc@ms=500,op=kv_get' -- \\
        python examples/train_mnist.py

Spec grammar: ``;``-separated clauses, each ``kind@key=value,...``.
Exit status: 0 valid/clean, 2 usage or spec error.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--spec", default=None,
                    help="fault spec to validate/inject "
                         "(grammar: 'kind@k=v,...;kind@k=v,...')")
    ap.add_argument("--dry-run", action="store_true",
                    help="parse and print the faults, run nothing")
    ap.add_argument("--list-faults", action="store_true",
                    help="print the fault-kind catalogue and exit")
    ap.add_argument("--seed", type=int, default=None,
                    help="seed appended to probabilistic faults that "
                         "carry none (deterministic replay)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="training command to exec with the spec in "
                         "$CHAINERMN_TPU_CHAOS (prefix with --)")
    args = ap.parse_args(argv)

    from chainermn_tpu.resilience import chaos

    if args.list_faults:
        for kind, usage in sorted(chaos.FAULT_KINDS.items()):
            print(f"{kind:15s} {usage}")
        return 0

    if args.spec is None:
        ap.print_usage(sys.stderr)
        print("chaos: give --spec (or --list-faults)", file=sys.stderr)
        return 2

    try:
        faults = chaos.parse_spec(args.spec)
    except ValueError as e:
        print(f"chaos: bad spec: {e}", file=sys.stderr)
        return 2

    if args.seed is not None:
        for f in faults:
            if f.seed is None:
                f.seed = args.seed

    if args.dry_run or not args.command:
        print(f"chaos: spec ok — {len(faults)} fault(s):")
        for f in faults:
            rank = "*" if f.rank is None else f.rank
            print(f"  {f.kind}@rank={rank} {f.describe()}")
        if not args.dry_run and not args.command:
            print("chaos: no command given (append '-- CMD...' to run)",
                  file=sys.stderr)
        return 0

    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("chaos: empty command after '--'", file=sys.stderr)
        return 2
    env = dict(os.environ)
    env[chaos.ENV_VAR] = args.spec
    os.execvpe(cmd[0], cmd, env)  # no return


if __name__ == "__main__":
    sys.exit(main())

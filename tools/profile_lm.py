#!/usr/bin/env python
"""LM train-step profile + cost analysis on the real chip.

Builds the EXACT tools/bench_lm.py program (GPT-small-ish, d=768, 12L,
L=2048, b=8, bf16, flash attention, adamw, scan_steps=4), then:

1. `cost_analysis()` on the compiled step → FLOPs + HBM bytes → roofline.
2. A jax.profiler trace around one warmed dispatch → per-kernel device
   time, bucketed by kernel family.

Methodology follows docs/resnet50_roofline.md (warm ≥3 executions for the
tunneled chip's deferred second-execution cost; device pid from the trace;
leaf events only, jit_*/numeric containers excluded).

Usage: python tools/profile_lm.py [trace_dir]
"""

import collections
import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

D_MODEL, N_LAYERS, SEQ_LEN = 768, 12, 2048
BATCH = int(os.environ.get("PROFILE_BATCH", "8"))
SCAN_K = 4
QKV_LAYOUT = os.environ.get("PROFILE_QKV_LAYOUT", "blhd")
LOSS = os.environ.get("PROFILE_LOSS", "unfused")  # 'fused' → ops.fused_ce


def build_step():
    import jax
    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu.models.transformer import (
        TransformerLM, lm_loss_with_aux)
    from chainermn_tpu.training.step import make_data_parallel_train_step

    comm = chainermn_tpu.create_communicator("xla")
    model = TransformerLM(
        vocab=32768, d_model=D_MODEL, n_heads=D_MODEL // 64,
        n_layers=N_LAYERS, d_ff=4 * D_MODEL, max_len=SEQ_LEN,
        pos_emb="rope", attention="flash", dtype=jnp.bfloat16,
        qkv_layout=QKV_LAYOUT)
    toks = np.random.RandomState(0).randint(
        0, 32768, size=(BATCH * comm.size, SEQ_LEN + 1)).astype(np.int32)
    params = comm.bcast_data(
        model.init(jax.random.PRNGKey(0), toks[:1, :-1])["params"])
    opt = chainermn_tpu.create_multi_node_optimizer(optax.adamw(3e-4), comm)
    if LOSS == "fused":
        from chainermn_tpu.ops import fused_lm_loss

        lf = fused_lm_loss
    else:
        lf = lm_loss_with_aux
    step = make_data_parallel_train_step(
        model, opt, comm, loss_fn=lf, scan_steps=SCAN_K)
    state = (params, opt.init(params))

    from jax.sharding import NamedSharding, PartitionSpec as P
    dsh = NamedSharding(comm.mesh, P(None, comm.axis_names[0]))
    xs = jax.device_put(np.broadcast_to(
        toks[None, :, :-1], (SCAN_K,) + toks[:, :-1].shape).copy(), dsh)
    ys = jax.device_put(np.broadcast_to(
        toks[None, :, 1:], (SCAN_K,) + toks[:, 1:].shape).copy(), dsh)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    return step, state, xs, ys, n_params


def parse_trace(trace_dir):
    """Sum leaf device-kernel durations from the newest vm.trace.json.gz,
    bucketed by kernel-name family (docs/resnet50_roofline.md §1)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        return None
    with gzip.open(paths[-1], "rt") as f:
        tr = json.load(f)
    events = tr.get("traceEvents", [])
    # device pid: the process whose name mentions the device (pid 3 on
    # this plugin); fall back to the pid with the most X events
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name" and "args" in e}
    dev_pids = [p for p, n in pid_names.items()
                if "TPU" in n or "Device" in n or "/device" in n.lower()]
    xs = [e for e in events if e.get("ph") == "X"]
    if not dev_pids:
        counts = collections.Counter(e["pid"] for e in xs)
        dev_pids = [counts.most_common(1)[0][0]] if counts else []
    fams = collections.Counter()
    total = 0.0
    for e in xs:
        if e["pid"] not in dev_pids:
            continue
        name = e.get("name", "")
        # containers, not kernels
        if name.startswith("jit_") or name.isdigit():
            continue
        if name.startswith("while"):
            continue  # container: its leaves are counted individually
        dur = e.get("dur", 0) / 1e6  # us → s
        base = name.split(".")[0].split("(")[0]
        # strip trailing instance numbers: fusion.123 → fusion
        base = base.rstrip("0123456789").rstrip("._-") or name
        fams[base] += dur
        total += dur
    return {"total_s": total, "families": dict(fams.most_common(25))}


def main():
    import jax

    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/lm_trace"
    step, state, xs, ys, n_params = build_step()

    # warm: compile + the chip's deferred second-execution cost
    for _ in range(3):
        state, m = step(state, xs, ys)
        float(m["main/loss"][-1])

    # ---- cost analysis on the compiled executable --------------------
    ca = {}
    try:
        compiled = step.lower(state, xs, ys).compile()
        raw = compiled.cost_analysis()
        raw = raw[0] if isinstance(raw, (list, tuple)) else raw
        ca = {k: float(v) for k, v in raw.items()
              if isinstance(v, (int, float)) and (
                  "flops" in k or "bytes" in k or "time" in k)}
    except Exception as e:  # noqa: BLE001 — report, don't die
        ca = {"error": repr(e)}

    # ---- timed steady state ------------------------------------------
    # bench_lm methodology: sync ONCE at the end — dispatches queue
    # asynchronously so the ~100 ms tunnel round-trip overlaps and the
    # figure is DEVICE throughput. (A per-iteration sync adds the full
    # tunnel latency to every dispatch: measured +23 ms/step on the same
    # program, r5 — that discrepancy was methodology, not the program.)
    n_iters = 6
    t0 = time.perf_counter()
    for _ in range(n_iters):
        state, m = step(state, xs, ys)  # dlint: disable=DL104 — see above
    float(m["main/loss"][-1])
    dt = time.perf_counter() - t0
    step_s = dt / (n_iters * SCAN_K)
    tok_s = BATCH * SEQ_LEN / step_s

    # ---- trace one dispatch ------------------------------------------
    jax.profiler.start_trace(trace_dir)
    state, m = step(state, xs, ys)
    float(m["main/loss"][-1])
    jax.profiler.stop_trace()
    prof = parse_trace(trace_dir)

    flops = ca.get("flops", 0.0) * 1  # per dispatch (SCAN_K steps)
    bytes_ = ca.get("bytes accessed", 0.0)
    out = {
        "config": {"d_model": D_MODEL, "n_layers": N_LAYERS,
                   "seq_len": SEQ_LEN, "batch": BATCH, "scan_k": SCAN_K,
                   "n_params": n_params},
        "measured_step_s": step_s,
        "tokens_per_sec": tok_s,
        "cost_analysis_per_dispatch": ca,
        "flops_per_step": flops / SCAN_K if flops else None,
        "bytes_per_step": bytes_ / SCAN_K if bytes_ else None,
        "roofline_hbm_ms": (bytes_ / SCAN_K) / 819e9 * 1e3 if bytes_
        else None,
        "roofline_mxu_ms": (flops / SCAN_K) / 197e12 * 1e3 if flops
        else None,
        "profile": prof,
    }
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()

// chainermn_native — host-side runtime primitives.
//
// TPU-native counterpart of the reference's native surface (SURVEY.md §2.2):
// where the reference ships a Cython NCCL binding plus CuPy pack/unpack
// kernels (chainermn/nccl/nccl.pyx, communicators/_memory_utility.py), the
// TPU collectives live in XLA — so the native layer here serves the part XLA
// does not cover: the host data path. Provides
//
//   * flat-buffer pack/unpack (the _memory_utility.pack_params analog) with
//     a std::thread fan-out — used for checkpoint serialization and
//     host-staged transports;
//   * threaded strided row-gather (the hot inner loop of batch assembly:
//     out[i] = base[indices[i]]) — the data-loader core;
//   * a double-buffered prefetching batch loader: a worker thread assembles
//     the next batch into a reusable buffer while the device runs the
//     current step.
//
// Exposed as a plain C ABI for ctypes (pybind11 is not available in this
// toolchain); see chainermn_tpu/ops/native.py for the Python side.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

void parallel_for(int64_t n, int n_threads, void (*fn)(int64_t, int64_t, void*),
                  void* ctx) {
  if (n_threads <= 1 || n < 2) {
    fn(0, n, ctx);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    ts.emplace_back([=] { fn(lo, hi, ctx); });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// flat buffer pack / unpack
// ---------------------------------------------------------------------------

struct PackCtx {
  const void** srcs;
  void** dsts;
  const int64_t* sizes;    // bytes per leaf
  const int64_t* offsets;  // byte offsets into the flat buffer
  char* flat;
  const char* cflat;
};

static void pack_range(int64_t lo, int64_t hi, void* vctx) {
  auto* c = static_cast<PackCtx*>(vctx);
  for (int64_t i = lo; i < hi; ++i)
    std::memcpy(c->flat + c->offsets[i], c->srcs[i],
                static_cast<size_t>(c->sizes[i]));
}

static void unpack_range(int64_t lo, int64_t hi, void* vctx) {
  auto* c = static_cast<PackCtx*>(vctx);
  for (int64_t i = lo; i < hi; ++i)
    std::memcpy(c->dsts[i], c->cflat + c->offsets[i],
                static_cast<size_t>(c->sizes[i]));
}

// Pack n buffers into `flat` at `offsets`. Threaded over leaves.
void cmn_pack(const void** srcs, const int64_t* sizes, const int64_t* offsets,
              int64_t n, void* flat, int n_threads) {
  PackCtx c{srcs, nullptr, sizes, offsets, static_cast<char*>(flat), nullptr};
  parallel_for(n, n_threads, pack_range, &c);
}

void cmn_unpack(const void* flat, void** dsts, const int64_t* sizes,
                const int64_t* offsets, int64_t n, int n_threads) {
  PackCtx c{nullptr, dsts, sizes, offsets, nullptr,
            static_cast<const char*>(flat)};
  parallel_for(n, n_threads, unpack_range, &c);
}

// ---------------------------------------------------------------------------
// threaded row gather: out[i, :] = base[indices[i], :]
// ---------------------------------------------------------------------------

struct GatherCtx {
  const char* base;
  int64_t row_bytes;
  const int64_t* indices;
  char* out;
};

static void gather_range(int64_t lo, int64_t hi, void* vctx) {
  auto* c = static_cast<GatherCtx*>(vctx);
  for (int64_t i = lo; i < hi; ++i)
    std::memcpy(c->out + i * c->row_bytes,
                c->base + c->indices[i] * c->row_bytes,
                static_cast<size_t>(c->row_bytes));
}

void cmn_gather_rows(const void* base, int64_t row_bytes,
                     const int64_t* indices, int64_t n, void* out,
                     int n_threads) {
  GatherCtx c{static_cast<const char*>(base), row_bytes, indices,
              static_cast<char*>(out)};
  parallel_for(n, n_threads, gather_range, &c);
}

// ---------------------------------------------------------------------------
// double-buffered prefetching loader
// ---------------------------------------------------------------------------
//
// The loader owns `depth` reusable buffers per stream (x and y). submit()
// enqueues an index set; a worker thread gathers rows into the next free
// buffer; next() blocks until the oldest submitted batch is ready and
// returns its buffer id. The Python side wraps buffer ids as numpy views.

struct Loader {
  const char* xbase;
  const char* ybase;
  int64_t xrow, yrow;  // bytes per row
  int64_t batch;       // rows per batch
  int depth;
  int n_threads;
  std::vector<std::vector<char>> xbuf, ybuf;

  std::mutex mu;
  std::condition_variable cv;
  std::queue<std::vector<int64_t>> pending;  // submitted index sets
  std::queue<int> ready;                     // finished buffer ids
  std::queue<int> freebufs;
  std::atomic<bool> stop{false};
  std::thread worker;

  Loader(const void* xb, const void* yb, int64_t xr, int64_t yr, int64_t b,
         int d, int nt)
      : xbase(static_cast<const char*>(xb)),
        ybase(static_cast<const char*>(yb)),
        xrow(xr), yrow(yr), batch(b), depth(d), n_threads(nt) {
    xbuf.resize(depth);
    ybuf.resize(depth);
    for (int i = 0; i < depth; ++i) {
      xbuf[i].resize(static_cast<size_t>(xrow * batch));
      ybuf[i].resize(static_cast<size_t>(yrow * batch));
      freebufs.push(i);
    }
    worker = std::thread([this] { run(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop = true;
    }
    cv.notify_all();
    worker.join();
  }

  void run() {
    for (;;) {
      std::vector<int64_t> idx;
      int buf;
      {
        std::unique_lock<std::mutex> l(mu);
        cv.wait(l, [this] {
          return stop || (!pending.empty() && !freebufs.empty());
        });
        if (stop) return;
        idx = std::move(pending.front());
        pending.pop();
        buf = freebufs.front();
        freebufs.pop();
      }
      GatherCtx cx{xbase, xrow, idx.data(), xbuf[buf].data()};
      parallel_for(static_cast<int64_t>(idx.size()), n_threads, gather_range,
                   &cx);
      GatherCtx cy{ybase, yrow, idx.data(), ybuf[buf].data()};
      parallel_for(static_cast<int64_t>(idx.size()), n_threads, gather_range,
                   &cy);
      {
        std::lock_guard<std::mutex> l(mu);
        ready.push(buf);
      }
      cv.notify_all();
    }
  }
};

void* cmn_loader_create(const void* xbase, const void* ybase, int64_t xrow,
                        int64_t yrow, int64_t batch, int depth,
                        int n_threads) {
  return new Loader(xbase, ybase, xrow, yrow, batch, depth, n_threads);
}

void cmn_loader_submit(void* h, const int64_t* indices, int64_t n) {
  auto* l = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->pending.emplace(indices, indices + n);
  }
  l->cv.notify_all();
}

// Blocks until a batch is ready; returns buffer id and writes x/y pointers.
int cmn_loader_next(void* h, void** xout, void** yout) {
  auto* l = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(l->mu);
  l->cv.wait(lk, [l] { return !l->ready.empty(); });
  int buf = l->ready.front();
  l->ready.pop();
  *xout = l->xbuf[buf].data();
  *yout = l->ybuf[buf].data();
  return buf;
}

// Return a buffer to the free pool once the device owns a copy.
void cmn_loader_release(void* h, int buf) {
  auto* l = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->freebufs.push(buf);
  }
  l->cv.notify_all();
}

void cmn_loader_destroy(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"

"""Drop-in import alias: ``import chainermn`` → ``chainermn_tpu``.

The reference's user-facing promise is a ~3-line diff to any training script
(create a communicator, wrap the optimizer, scatter the dataset — SURVEY.md
§0). This package keeps those scripts' *import lines* working against the
TPU-native rebuild: every top-level factory, plus the documented submodules
(``chainermn.functions``, ``chainermn.links``, ``chainermn.communicators``,
``chainermn.datasets``, ``chainermn.iterators``, ``chainermn.extensions``,
``chainermn.optimizers``, mirroring the reference package layout per
SURVEY.md §1), resolves to the ``chainermn_tpu`` implementation.

What cannot carry over: Chainer itself. Models are flax modules and
optimizers are optax transformations here, so a real migration still touches
model code — see MIGRATION.md for the mapping. This shim makes the
*distributed* surface (the part ChainerMN owned) line-compatible.
"""

import importlib as _importlib
import pkgutil as _pkgutil
import sys as _sys

from chainermn_tpu import *  # noqa: F401,F403 — re-export the public API
from chainermn_tpu import __all__ as _all
from chainermn_tpu import __version__  # noqa: F401

# Reference submodule layout → rebuild modules. `chainermn.communicators`
# maps to the comm package (communicator classes + factory live there).
_SUBMODULES = {
    "communicators": "chainermn_tpu.comm",
    "functions": "chainermn_tpu.functions",
    "links": "chainermn_tpu.links",
    "datasets": "chainermn_tpu.datasets",
    "iterators": "chainermn_tpu.iterators",
    "extensions": "chainermn_tpu.extensions",
    "optimizers": "chainermn_tpu.optimizers",
}


def _alias_tree(alias_name: str, target_name: str) -> None:
    """Alias the WHOLE subtree, not just the top module: a plain top-level
    sys.modules entry would let `import chainermn.communicators.base`
    re-execute base.py under the alias name — a duplicate module with
    distinct class objects (isinstance across the two copies fails)."""
    mod = _importlib.import_module(target_name)
    _sys.modules[alias_name] = mod
    for info in _pkgutil.iter_modules(getattr(mod, "__path__", [])):
        _alias_tree(f"{alias_name}.{info.name}",
                    f"{target_name}.{info.name}")


for _name, _target in _SUBMODULES.items():
    _alias_tree(f"{__name__}.{_name}", _target)
    globals()[_name] = _sys.modules[f"{__name__}.{_name}"]

__all__ = list(_all) + list(_SUBMODULES)

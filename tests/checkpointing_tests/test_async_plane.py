"""AsyncSnapshotPlane: bitwise parity with the sync save, donation
safety, backpressure (block vs skip), drain deadlines, emergency-save
grace accounting, deferred-error surfacing, and the SIGKILL crash
window between offload and publish (subprocess)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.checkpointing import AsyncSnapshotPlane
from chainermn_tpu.extensions.checkpoint import MultiNodeCheckpointer
from chainermn_tpu.resilience import chaos
from chainermn_tpu.resilience.preemption import reserve_grace


@pytest.fixture()
def comm():
    return chainermn_tpu.create_communicator("xla")


def _sharded(comm, shape, offset=0.0):
    x = jnp.arange(float(np.prod(shape)), dtype=jnp.float32)
    x = x.reshape(shape) + offset
    return jax.device_put(
        x, NamedSharding(comm.mesh, P(comm.mesh.axis_names[0])))


def _state(comm):
    return {"w": _sharded(comm, (8, 4)),
            "b": jnp.arange(3.0, dtype=jnp.float32),
            "h": np.arange(5, dtype=np.int32)}


# -- construction contract ----------------------------------------------


def test_rejects_async_write_checkpointer(comm, tmp_path):
    ck = MultiNodeCheckpointer("job", comm, path=str(tmp_path),
                               async_write=True)
    with pytest.raises(ValueError, match="async_write"):
        AsyncSnapshotPlane(ck)


def test_rejects_bad_backpressure_and_pending(comm, tmp_path):
    ck = MultiNodeCheckpointer("job", comm, path=str(tmp_path))
    with pytest.raises(ValueError, match="backpressure"):
        AsyncSnapshotPlane(ck, backpressure="drop")
    with pytest.raises(ValueError, match="max_pending"):
        AsyncSnapshotPlane(ck, max_pending=0)


# -- bitwise parity with the sync path ----------------------------------


def test_async_save_bitwise_equals_sync(comm, tmp_path):
    state = _state(comm)
    ck_sync = MultiNodeCheckpointer("sync", comm, path=str(tmp_path))
    ck_sync.save(state, iteration=3, host_state={"pos": 7})

    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("async", comm, path=str(tmp_path)))
    plane.save(state, iteration=3, host_state={"pos": 7})
    plane.flush()

    a = np.load(tmp_path / "sync" / "snapshot_iter_3.0",
                allow_pickle=False)
    b = np.load(tmp_path / "async" / "snapshot_iter_3.0",
                allow_pickle=False)
    assert set(a.files) == set(b.files)
    for k in a.files:
        assert np.array_equal(a[k], b[k]), k
    plane.close()


def test_round_trip_through_the_plane(comm, tmp_path):
    state = _state(comm)
    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)))
    plane.save(state, iteration=2, host_state={"rng": 11})
    # read-side drains first — no explicit flush needed
    assert plane.latest_common_iteration() == 2
    template = {"w": jnp.zeros_like(state["w"]),
                "b": jnp.zeros(3, jnp.float32),
                "h": np.zeros(5, np.int32)}
    loaded, it = plane.maybe_load(template)
    assert it == 2
    assert np.array_equal(np.asarray(loaded["w"]),
                          np.asarray(state["w"]))
    assert plane.load_host_state(2) == {"rng": 11}
    plane.close()


def test_resume_bit_for_bit_vs_uninterrupted(comm, tmp_path):
    """Losses after resuming from the async snapshot must be bit-for-bit
    identical to the uninterrupted run — including with a DONATING step
    that deletes the saved buffers right after save() returns."""
    sharding = NamedSharding(comm.mesh, P(comm.mesh.axis_names[0]))

    @jax.jit
    def loss_of(w):
        return jnp.float32(jnp.mean(w * w))

    step = jax.jit(lambda w: w * 1.0001 + 0.01, donate_argnums=0)

    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)))
    w = _sharded(comm, (8, 4))
    ref_losses = []
    for i in range(1, 11):
        w = step(w)
        ref_losses.append(float(loss_of(w)))  # per-iter sync (1-core rule)
        if i == 5:
            plane.save({"w": w}, iteration=5)
    plane.flush()

    template = {"w": jax.device_put(jnp.zeros((8, 4), jnp.float32),
                                    sharding)}
    loaded, it = plane.maybe_load(template, iteration=5)
    assert it == 5
    w2 = loaded["w"]
    resumed = []
    for _ in range(6, 11):
        w2 = step(w2)
        resumed.append(float(loss_of(w2)))
    assert resumed == ref_losses[5:]
    plane.close()


# -- backpressure -------------------------------------------------------


def test_backpressure_skip_drops_and_counts(comm, tmp_path, monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR,
                       "stall_writer@ms=500,match=snapshot_iter")
    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)),
        max_pending=1, backpressure="skip")
    state = _state(comm)
    assert plane.save(state, iteration=1) is True
    time.sleep(0.15)  # writer picked item 1, now inside the stall
    assert plane.save(state, iteration=2) is True   # fills the slot
    assert plane.save(state, iteration=3) is False  # queue full: dropped
    assert plane.skipped == 1
    monkeypatch.delenv(chaos.ENV_VAR)
    plane.flush()
    assert plane.published == 2
    assert plane.latest_common_iteration() == 2  # iter 3 never existed
    plane.close()


def test_backpressure_block_stalls_until_slot_frees(comm, tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR,
                       "stall_writer@ms=300,match=snapshot_iter")
    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)),
        max_pending=1, backpressure="block")
    state = _state(comm)
    plane.save(state, iteration=1)
    plane.save(state, iteration=2)  # blocks until the writer takes #1
    t0 = time.monotonic()
    plane.save(state, iteration=3)  # blocks through #1's 300 ms stall
    blocked = time.monotonic() - t0
    assert blocked > 0.05  # the stall IS the backpressure signal
    assert plane.skipped == 0
    monkeypatch.delenv(chaos.ENV_VAR)
    plane.flush()
    assert plane.published == 3
    plane.close()


# -- drain / deadline / errors ------------------------------------------


def test_drain_deadline_false_then_flush_completes(comm, tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR,
                       "stall_writer@ms=400,match=snapshot_iter")
    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)))
    plane.save(_state(comm), iteration=1)
    assert plane.drain(time.monotonic() + 0.05) is False  # budget passed
    assert plane.pending == 1
    monkeypatch.delenv(chaos.ENV_VAR)
    plane.flush()  # unbounded drain finishes the publish
    assert plane.published == 1
    assert plane.pending == 0
    plane.close()


def test_writer_error_surfaces_on_flush(comm, tmp_path, monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR, "enospc@match=snapshot_iter_7")
    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)))
    plane.save(_state(comm), iteration=7)
    with pytest.raises(RuntimeError,
                       match="async snapshot publish failed"):
        plane.flush()
    monkeypatch.delenv(chaos.ENV_VAR)
    # nothing partial was published — the failed iteration is invisible
    assert plane.latest_common_iteration() is None
    plane.close()


# -- emergency-save grace accounting ------------------------------------


def test_reserve_grace_subtracts_from_the_window():
    assert reserve_grace(None) is None
    now = time.monotonic()
    d = reserve_grace(now + 10.0, fraction=0.5)
    assert now + 4.5 < d < now + 5.5  # half reserved for the sync save
    d = reserve_grace(now + 10.0, fraction=0.5, floor_s=8.0)
    assert d <= now + 2.1  # floor wins: 8 s kept for the sync save
    # an already-passed deadline never goes further into the past
    assert reserve_grace(now - 5.0) >= now - 1e-3


def test_emergency_save_drains_inside_the_same_window(comm, tmp_path):
    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)))
    seen = {}
    plane.drain = lambda deadline_s=None: seen.update(drain=deadline_s)
    plane.ck.emergency_save = \
        lambda trainer, deadline_s=None: seen.update(sync=deadline_s)
    deadline = time.monotonic() + 10.0
    plane.emergency_save(trainer=None, deadline_s=deadline)
    # drain gets a RESERVED slice of the window; the sync last-chance
    # save still sees the ORIGINAL deadline — one window, never doubled
    assert seen["sync"] == deadline
    assert seen["drain"] is not None
    assert seen["drain"] < deadline
    assert seen["drain"] >= time.monotonic() - 1.0


# -- trainer protocol ----------------------------------------------------


class _FakeUpdater:
    def __init__(self, comm):
        self.state = {"w": _sharded(comm, (8, 4))}
        self.iteration = 9

    def host_state_dict(self):
        return {"epoch": 2}


class _FakeTrainer:
    def __init__(self, comm):
        self.updater = _FakeUpdater(comm)
        self.observation = {}


def test_extension_protocol_and_report(comm, tmp_path, capsys):
    from chainermn_tpu.training.reports import CheckpointReport

    plane = AsyncSnapshotPlane(
        MultiNodeCheckpointer("job", comm, path=str(tmp_path)))
    trainer = _FakeTrainer(comm)
    plane(trainer)  # extension __call__ = save off the step path
    plane.flush()
    assert plane.latest_common_iteration() == 9
    assert plane.load_host_state(9) == {"epoch": 2}

    report = CheckpointReport(plane)
    report(trainer)
    out = capsys.readouterr().out
    assert "ckpt plane: backpressure=block" in out
    obs = trainer.observation
    assert obs["ckpt/published"] == 1
    assert obs["ckpt/skipped"] == 0
    assert obs["ckpt/cadence"] == 0  # single save — no cadence yet
    assert obs["ckpt/bytes"] > 0
    assert obs["ckpt/stall_ms"] >= 0.0
    plane.close()


# -- the SIGKILL window --------------------------------------------------

_CHILD = """
import os, signal, sys, time
import numpy as np
import jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.checkpointing import AsyncSnapshotPlane
from chainermn_tpu.extensions.checkpoint import MultiNodeCheckpointer
from chainermn_tpu.resilience import chaos

comm = chainermn_tpu.create_communicator("xla")
state = {"w": jax.device_put(
    jnp.arange(32.0).reshape(8, 4),
    NamedSharding(comm.mesh, P(comm.mesh.axis_names[0])))}
plane = AsyncSnapshotPlane(
    MultiNodeCheckpointer("job", comm, path=sys.argv[1]))
plane.save(state, iteration=1)
plane.flush()
# widen the offload->publish window, then die inside it
os.environ[chaos.ENV_VAR] = "stall_writer@ms=30000,match=snapshot_iter_2"
plane.save(state, iteration=2)
time.sleep(0.5)  # the writer is now stalled BEFORE the publish
os.kill(os.getpid(), signal.SIGKILL)
"""


@pytest.mark.slow
def test_sigkill_between_offload_and_publish_falls_back(comm, tmp_path):
    """A SIGKILL while iteration 2 sits between offload and publish must
    lose ONLY that snapshot: nothing partial is visible, and the
    election falls back to the fully-published iteration 1."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL, proc.stderr

    job = tmp_path / "job"
    assert (job / "snapshot_iter_1.0").exists()
    # iteration 2 never published: no data file (a tmp may linger — the
    # atomic rename is the publish, and it never ran)
    assert not (job / "snapshot_iter_2.0").exists()
    ck = MultiNodeCheckpointer("job", comm, path=str(tmp_path))
    assert ck.latest_common_iteration() == 1
    template = {"w": jax.device_put(
        jnp.zeros((8, 4), jnp.float32),
        NamedSharding(comm.mesh, P(comm.mesh.axis_names[0])))}
    loaded, it = ck.maybe_load(template)
    assert it == 1
    assert np.array_equal(np.asarray(loaded["w"]),
                          np.arange(32.0, dtype=np.float32).reshape(8, 4))

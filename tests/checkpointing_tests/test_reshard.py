"""Manifest-driven multi-axis resharding: EF-frame regroup math, the
2x4 -> 2x2 mesh reshape parity run, tile-layout-only bitwise splices,
the elastic planner's reshard decision on a REAL two-axis comm, layout
manifests, and the offline coverage helpers tools/ckpt.py builds on."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.checkpointing.reshard import (
    default_leaf_resharder,
    ef_frame_regroup,
    leaf_coverage,
    manifest_info,
    mesh_axes,
    reshard_state,
    saved_axes,
    scan_snapshot_dir,
)
from chainermn_tpu.extensions.checkpoint import MultiNodeCheckpointer
from chainermn_tpu.optimizers.zero import (
    _padded_size,
    zero_layout_manifest,
    fsdp_layout_manifest,
)
from chainermn_tpu.resilience.elastic import (
    elastic_resume,
    plan_elastic_resume,
)


def _mesh(shape, axes):
    devs = np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


@pytest.fixture()
def comm24():
    return chainermn_tpu.create_communicator(
        "xla", mesh=_mesh((2, 4), ("data", "model")))


@pytest.fixture()
def comm22():
    return chainermn_tpu.create_communicator(
        "xla", mesh=_mesh((2, 2), ("data", "model")))


@pytest.fixture()
def comm8():
    return chainermn_tpu.create_communicator("xla")


def _put(x, mesh, spec):
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))


# -- EF regroup math -----------------------------------------------------


def test_ef_regroup_shrink_is_group_mean():
    full = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    out = ef_frame_regroup(full, 4)
    assert out.shape == (4, 16)
    np.testing.assert_array_equal(
        out, full.reshape(4, 2, 16).sum(axis=1) / 2)


def test_ef_regroup_grow_is_bitwise_repeat():
    full = np.random.default_rng(0).normal(
        size=(4, 16)).astype(np.float32)
    out = ef_frame_regroup(full, 8)
    np.testing.assert_array_equal(out, np.repeat(full, 2, axis=0))


def test_ef_regroup_preserves_cross_rank_mean_both_ways():
    """The invariant that makes the regroup CORRECT: the reducers
    average residuals over ranks (op='mean'), and both directions keep
    that mean bit-exact for power-of-two worlds."""
    rng = np.random.default_rng(7)
    full = rng.normal(size=(8, 64)).astype(np.float32)
    mean = full.mean(axis=0, dtype=np.float64)
    for n_new in (4, 2, 16):
        out = ef_frame_regroup(full, n_new)
        # the group sums round once in float32, so the float64
        # reference mean is matched to f32 precision, not bit-exactly
        np.testing.assert_allclose(
            out.mean(axis=0, dtype=np.float64), mean,
            rtol=1e-5, atol=1e-7)
    # shrink-then-grow round trip: exact (each row is its group's mean)
    back = ef_frame_regroup(ef_frame_regroup(full, 4), 8)
    np.testing.assert_array_equal(
        back, np.repeat(full.reshape(4, 2, 64).sum(1) / 2, 2, axis=0))


def test_ef_regroup_rejects_non_divisible_and_non_2d():
    with pytest.raises(ValueError, match="divide"):
        ef_frame_regroup(np.zeros((3, 8), np.float32), 2)
    with pytest.raises(ValueError, match="2-D"):
        ef_frame_regroup(np.zeros(8, np.float32), 2)


def test_default_resharder_only_touches_world_stacked_frames():
    fetch = lambda: np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    ref = jnp.zeros((4, 16), jnp.float32)
    out = default_leaf_resharder(0, ref, (8, 16), fetch)
    assert out.shape == (4, 16)
    # different trailing dim = a genuinely different model: refused
    assert default_leaf_resharder(
        0, jnp.zeros((4, 32)), (8, 16), fetch) is None
    # same leading dim: splice territory, not regroup territory
    assert default_leaf_resharder(
        0, jnp.zeros((8, 16)), (8, 16), fetch) is None
    # non-2-D: refused
    assert default_leaf_resharder(
        0, jnp.zeros((4, 2, 16)), (8, 2, 16), fetch) is None


# -- multi-axis mesh reshape (the previously-impossible resume) ----------


def test_reshard_2x4_to_2x2_parity(comm24, comm22, tmp_path):
    """Save on a 2x4 TP x DP mesh, resume on 2x2: same-shape leaves
    restore bitwise through the splice, the world-stacked EF frame
    regroups to the oracle, and a step on the new mesh runs finite."""
    m24, m22 = comm24.mesh, comm22.mesh
    w = _put(jnp.arange(64.0).reshape(8, 8), m24, P("data", "model"))
    ef_full = np.random.default_rng(3).normal(
        size=(8, 256)).astype(np.float32)
    ef = _put(ef_full, m24, P("model"))
    ck24 = MultiNodeCheckpointer("job", comm24, path=str(tmp_path))
    ck24.save({"w": w, "ef": ef}, iteration=7)

    ck22 = MultiNodeCheckpointer("job", comm22, path=str(tmp_path))
    template = {
        "w": _put(jnp.zeros((8, 8)), m22, P("data", "model")),
        "ef": _put(jnp.zeros((4, 256)), m22, P("model")),
    }
    loaded, it = reshard_state(ck22, template)
    assert it == 7
    np.testing.assert_array_equal(np.asarray(loaded["w"]),
                                  np.arange(64.0).reshape(8, 8))
    # EF oracle: the from-scratch regroup of the full saved frame
    np.testing.assert_array_equal(
        np.asarray(loaded["ef"]),
        ef_full.reshape(4, 2, 256).sum(axis=1) / 2)
    # the restored leaves live on the NEW mesh and step finite
    assert loaded["w"].sharding.mesh.shape == {"data": 2, "model": 2}
    loss = float(jax.jit(
        lambda s: jnp.mean(s["w"]) + jnp.mean(s["ef"]))(loaded))
    assert np.isfinite(loss)


def test_tile_layout_only_change_is_bitwise_splice(comm8, comm24,
                                                   tmp_path):
    """Same global shapes, different tiling (1-D 'r' x8 -> 2x4): pure
    interval splice, bit-for-bit — including the EF frame, whose world
    count (8 devices) did not change."""
    m8, m24 = comm8.mesh, comm24.mesh
    w_full = np.random.default_rng(1).normal(size=(8, 8)) \
        .astype(np.float32)
    ef_full = np.random.default_rng(2).normal(size=(8, 256)) \
        .astype(np.float32)
    ck8 = MultiNodeCheckpointer("job", comm8, path=str(tmp_path))
    ck8.save({"w": _put(w_full, m8, P("r")),
              "ef": _put(ef_full, m8, P("r"))}, iteration=4)

    ck24 = MultiNodeCheckpointer("job", comm24, path=str(tmp_path))
    template = {"w": _put(jnp.zeros((8, 8)), m24, P("data", "model")),
                "ef": _put(jnp.zeros((8, 256)), m24, P("model"))}
    loaded, it = reshard_state(ck24, template)
    assert it == 4
    np.testing.assert_array_equal(np.asarray(loaded["w"]), w_full)
    np.testing.assert_array_equal(np.asarray(loaded["ef"]), ef_full)


def test_plan_and_elastic_resume_across_axes_change(comm24, comm22,
                                                    tmp_path):
    """End-to-end through resilience/elastic.py on REAL comms: the
    2-axis mesh change that historically raised ElasticTopologyError
    plans as 'reshard' (axes read from the coverage manifest) and
    elastic_resume restores the updater exactly — same process count,
    so the host side is the exact-restore path."""
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.training import StandardUpdater

    m24, m22 = comm24.mesh, comm22.mesh
    w = _put(jnp.arange(64.0).reshape(8, 8), m24, P("data", "model"))
    ck24 = MultiNodeCheckpointer("job", comm24, path=str(tmp_path))
    ck24.save({"w": w}, iteration=5, host_state={"pos": 40})

    ck22 = MultiNodeCheckpointer("job", comm22, path=str(tmp_path))
    plan = plan_elastic_resume(ck22)
    assert plan.action == "reshard"
    assert plan.iteration == 5
    assert plan.saved_axes == {"data": 2, "model": 4}
    assert plan.new_axes == {"data": 2, "model": 2}
    assert plan.averaging_rescale == pytest.approx(2.0)  # 8 -> 4 devices

    data = [(np.zeros(2, np.float32), np.int32(0))] * 8
    it = SerialIterator(data, 2)

    def step(state, x, y):
        return state, {"loss": float(jnp.mean(state["w"]))}

    u = StandardUpdater(
        it, step,
        {"w": _put(jnp.zeros((8, 8)), m22, P("data", "model"))}, comm22)
    u.shard_batch = lambda arrays: arrays
    host = {}
    u.load_host_state = host.update
    executed = elastic_resume(ck22, u)
    assert executed.action == "reshard"
    assert u.iteration == 5
    np.testing.assert_array_equal(np.asarray(u.state["w"]),
                                  np.arange(64.0).reshape(8, 8))
    assert host.get("pos") == 40  # exact host restore: same world size
    u.update()
    assert np.isfinite(u.last_metrics["loss"])


# -- layout manifests ----------------------------------------------------


def test_zero_layout_manifest_is_device_count_independent(comm8,
                                                          tmp_path):
    params = {"a": jnp.zeros(1000, jnp.float32),
              "b": jnp.zeros((10, 30), jnp.float32)}
    m8 = zero_layout_manifest(params, comm8)
    assert m8["kind"] == "zero-flat"
    assert m8["n"] == 8
    assert m8["total"] == 1300
    assert m8["padded"] == _padded_size(1300, 8)
    assert m8["ef_frames"] == [[8, m8["padded"]]]
    # quantum padding: the TRAILING dim matches what a 4-device world
    # would write — the reshard regroup only ever changes the leading dim
    comm4 = chainermn_tpu.create_communicator(
        "xla", mesh=Mesh(np.array(jax.devices()[:4]), ("r",)))
    assert zero_layout_manifest(params, comm4)["padded"] == m8["padded"]

    ck = MultiNodeCheckpointer("job", comm8, path=str(tmp_path))
    ck.set_layout(m8)
    ck.save({"w": jnp.zeros(4, jnp.float32)}, iteration=1)
    info = manifest_info(ck, 1)
    assert info["layout"]["kind"] == "zero-flat"
    assert info["world"] == 1  # single process
    assert saved_axes(ck, 1) == {"r": 8}


def test_zero_layout_manifest_bucketed(comm8):
    params = {"a": jnp.zeros(100_000, jnp.float32),
              "b": jnp.zeros(60_000, jnp.float32)}
    m = zero_layout_manifest(params, comm8, bucket_bytes=1 << 18)
    assert m["kind"] == "zero-bucketed"
    assert m["bucket_bytes"] == 1 << 18
    assert len(m["ef_frames"]) == len(m["padded"]) >= 2
    for (rows, cols), padded in zip(m["ef_frames"], m["padded"]):
        assert rows == 8 and cols == padded


def test_fsdp_layout_manifest_rows(comm8):
    params = {"layer": {"w": jnp.zeros((8, 4), jnp.float32)},
              "bias": jnp.zeros(4, jnp.float32)}
    m = fsdp_layout_manifest(params, comm8)
    assert m["kind"] == "fsdp"
    assert m["n"] == 8
    paths = {r["path"] for r in m["leaves"]}
    assert any("w" in p for p in paths)
    assert all("shape" in r and "spec" in r for r in m["leaves"])


# -- offline helpers (the tools/ckpt.py substrate) -----------------------


def test_mesh_axes_and_manifest_axes_agree(comm24, tmp_path):
    assert mesh_axes(comm24) == {"data": 2, "model": 4}
    ck = MultiNodeCheckpointer("job", comm24, path=str(tmp_path))
    ck.save({"w": _put(jnp.zeros((8, 8)), comm24.mesh,
                       P("data", "model"))}, iteration=3)
    assert saved_axes(ck, 3) == {"data": 2, "model": 4}


def test_scan_and_coverage_complete(comm8, tmp_path):
    ck = MultiNodeCheckpointer("job", comm8, path=str(tmp_path))
    ck.save({"w": _put(jnp.zeros((8, 4)), comm8.mesh, P("r"))},
            iteration=2)
    job = str(tmp_path / "job")
    snaps = scan_snapshot_dir(job)
    assert list(snaps) == [2]
    cov = leaf_coverage(snaps[2])
    (rec,) = cov.values()
    assert rec["gshape"] == (8, 4)
    assert rec["covered"] is True
    assert rec["volume"] == 32


def test_coverage_reports_missing_shards(tmp_path):
    """A file set holding only half the shard intervals is INCOMPLETE —
    the accounting tools/ckpt.py and the dry-run planner rely on."""
    fn = str(tmp_path / "snapshot_iter_1.0")
    np.savez(fn + ".npz",
             leaf_0_nshards=np.int64(1),
             leaf_0_gshape=np.asarray((8, 4), np.int64),
             leaf_0_s0=np.zeros((4, 4), np.float32),
             leaf_0_idx0=np.asarray([[0, 4], [0, -1]], np.int64))
    import os

    os.replace(fn + ".npz", fn)
    cov = leaf_coverage([fn])
    assert cov[0]["covered"] is False
    assert cov[0]["volume"] == 16

"""Async snapshot plane + manifest-driven resharding tests (ISSUE 9).

Runs on the conftest's 8-virtual-CPU-device mesh; the multi-axis
reshard tests carve 2x4 and 2x2 meshes out of the same 8 devices."""

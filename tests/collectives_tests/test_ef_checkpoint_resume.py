"""Error-feedback residuals must survive a checkpoint/resume cycle.

The EF residual is OPTIMIZER STATE in every sense that matters: it
carries the gradient signal the int8 wire dropped, to be replayed into
later steps. A checkpointer that silently loses it resumes a *different*
optimization trajectory. The contract pinned here:

* a run checkpointed mid-flight and resumed into a FRESH process-state
  template reproduces the uninterrupted run's losses exactly;
* the negative control — same resume with the residuals zeroed — visibly
  diverges, proving the equality above actually flows through the
  residuals and the test has teeth.

Inputs are scaled (* 1e-2) into the regime where the int8 quantization
floor makes residuals large (see test_reducers.py), so the control
cannot pass by accident.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu
from chainermn_tpu.collectives import QuantizedReducer
from chainermn_tpu.datasets.toy import synthetic_mnist
from chainermn_tpu.extensions import create_multi_node_checkpointer
from chainermn_tpu.models import MLP
from chainermn_tpu.training.step import make_data_parallel_train_step

STEPS, SPLIT, BS, N = 8, 4, 32, 256


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("xla")


@pytest.fixture(scope="module")
def setup(comm):
    model = MLP(n_units=16, n_out=10)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    params = comm.bcast_data(params)
    train = synthetic_mnist(N, seed=0)
    xs = np.stack([train[i][0] for i in range(N)]).astype(np.float32) * 1e-2
    ys = np.array([train[i][1] for i in range(N)], np.int32)
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(1e-2), comm,
        grad_reducer=QuantizedReducer(comm, mode="int8", ef=True))
    step = make_data_parallel_train_step(model, opt, comm, donate=False)
    return params, opt, step, xs, ys


def _fresh_state(opt, params):
    p0 = jax.tree_util.tree_map(jnp.array, params)
    return (p0, jax.jit(opt.init)(p0))


def _run(step, state, xs, ys, lo_step, hi_step):
    losses = []
    for i in range(lo_step, hi_step):
        lo = (i * BS) % N
        state, m = step(state, xs[lo:lo + BS], ys[lo:lo + BS])
        losses.append(float(m["main/loss"]))  # per-iteration sync
    return state, losses


def _residuals(state):
    # (params, _ReducerWrappedState(inner=..., reducer=residuals))
    return state[1].reducer


def test_ef_residuals_roundtrip_through_checkpoint(comm, setup, tmp_path):
    params, opt, step, xs, ys = setup

    # uninterrupted reference
    state, ref = _run(step, _fresh_state(opt, params), xs, ys, 0, STEPS)

    # checkpointed run: stop at SPLIT, save, resume into a FRESH template
    mid, head = _run(step, _fresh_state(opt, params), xs, ys, 0, SPLIT)
    np.testing.assert_allclose(head, ref[:SPLIT], rtol=1e-6)
    res_norm = sum(float(jnp.abs(l).sum())
                   for l in jax.tree_util.tree_leaves(_residuals(mid)))
    assert res_norm > 0, "no residual signal at the checkpoint — " \
        "the roundtrip claim would be vacuous"
    cp = create_multi_node_checkpointer("ef", comm, path=str(tmp_path))
    cp.save(mid, iteration=SPLIT)

    cp2 = create_multi_node_checkpointer("ef", comm, path=str(tmp_path))
    restored, it = cp2.maybe_load(_fresh_state(opt, params))
    assert it == SPLIT
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        _residuals(mid), _residuals(restored))

    _, tail = _run(step, restored, xs, ys, SPLIT, STEPS)
    np.testing.assert_allclose(tail, ref[SPLIT:], rtol=1e-6)


def test_zeroed_residuals_diverge(comm, setup, tmp_path):
    """Negative control: drop the residuals on resume and the trajectory
    must visibly leave the reference — the roundtrip equality above is
    carried BY the residuals, not by coincidence."""
    params, opt, step, xs, ys = setup
    _, ref = _run(step, _fresh_state(opt, params), xs, ys, 0, STEPS)
    mid, _ = _run(step, _fresh_state(opt, params), xs, ys, 0, SPLIT)
    lopped = (mid[0], mid[1]._replace(
        reducer=jax.tree_util.tree_map(jnp.zeros_like, _residuals(mid))))
    _, tail = _run(step, lopped, xs, ys, SPLIT, STEPS)
    assert max(abs(a - b) for a, b in zip(tail, ref[SPLIT:])) > 1e-6, (
        tail, ref[SPLIT:])

"""GradReducer strategy tests.

Oracle: ``flat`` IS the reference (bit-identical to not passing a
reducer at all); every other strategy is measured against it.

* hierarchical — BITWISE parity with flat on sum-reducible payloads
  (integer-valued floats: reassociation cannot change the sum), allclose
  on real training floats.
* quantized — with error feedback the MNIST MLP converges like flat;
  without it the quantization floor (amax/254 per int8 bucket) eats the
  small weight gradients and the tail loss is demonstrably worse. The
  input scaling below (x * 1e-2) is calibrated so the separation is wide
  (measured: flat 1.4e-3 / ef 1.8e-3 / no-ef 9.7e-3 at 120 steps).
* auto — cost-model crossover structure + measured-table override; off
  TPU the measurement sweep is an honest null.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.collectives import (
    AutoReducer,
    CostModel,
    FlatReducer,
    GradReducer,
    HierarchicalReducer,
    HierTopology,
    QuantizedReducer,
    REDUCERS,
    make_grad_reducer,
    measure_strategies,
)
from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers import make_zero1_train_step, zero1_params
from chainermn_tpu.optimizers.zero import make_fsdp_train_step
from chainermn_tpu.training.step import make_data_parallel_train_step


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("xla")


def _mlp_params(comm, n_units=32):
    model = MLP(n_units=n_units, n_out=10)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    return model, comm.bcast_data(params)


def _data(comm, batch_per=4, seed=0, scale=1.0):
    n = comm.size * batch_per
    rs = np.random.RandomState(seed)
    x = (rs.rand(n, 28, 28) * scale).astype(np.float32)
    y = rs.randint(0, 10, size=(n,)).astype(np.int32)
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    return jax.device_put(x, dsh), jax.device_put(y, dsh)


def _shard_reduce(comm, kernel):
    """jit a per-shard flat-vector kernel over the (8,) mesh axis."""
    ax = comm.axis_names[0]

    def f(v):
        return kernel(v[0])[None]

    return jax.jit(shard_map(
        f, mesh=comm.mesh, in_specs=P(ax), out_specs=P(ax)))


def _train(comm, model, params, grad_reducer, steps, data, lr=1e-2,
           opt=None):
    """DP training run; returns (losses, final params)."""
    o = chainermn_tpu.create_multi_node_optimizer(
        opt or optax.adam(lr), comm, grad_reducer=grad_reducer)
    p0 = jax.tree_util.tree_map(jnp.array, params)
    state = (p0, jax.jit(o.init)(p0))
    step = make_data_parallel_train_step(model, o, comm, donate=False)
    xs, ys = data
    losses = []
    n = xs.shape[0]
    bs = comm.size * 4
    for i in range(steps):
        lo = (i * bs) % n
        state, m = step(state, xs[lo:lo + bs], ys[lo:lo + bs])
        losses.append(float(m["main/loss"]))  # per-iteration sync
    return losses, state[0]


# ---------------------------------------------------------------------------
# flat: the reference
# ---------------------------------------------------------------------------

def test_flat_reducer_bit_identical_to_default(comm):
    """grad_reducer='flat' must be byte-for-byte the legacy psum path."""
    model, params = _mlp_params(comm)
    data = _data(comm)
    _, p_default = _train(comm, model, params, None, 3, data)
    _, p_flat = _train(comm, model, params, "flat", 3, data)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        p_default, p_flat)


# ---------------------------------------------------------------------------
# hierarchical: two-level parity
# ---------------------------------------------------------------------------

def test_hierarchical_allreduce_bitwise_on_integer_floats(comm):
    """rs(intra) -> psum(inter) -> ag(intra) must equal one flat psum
    BITWISE on integer-valued floats (sums are exactly representable, so
    any disagreement is a logic bug, not reassociation)."""
    n = comm.size
    topo = HierTopology(comm, intra=4)
    assert topo.intra == 4 and topo.inter == n // 4
    rs = np.random.RandomState(0)
    x = rs.randint(-8, 8, size=(n, 4097)).astype(np.float32)  # odd: pads
    ax = comm.axis_names[0]
    flat = _shard_reduce(comm, lambda v: lax.psum(v, ax))(x)
    hier = _shard_reduce(comm, topo.allreduce)(x)
    np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))
    np.testing.assert_array_equal(np.asarray(flat)[0], x.sum(axis=0))


def test_hierarchical_reduce_scatter_layout_matches_flat(comm):
    """Two-stage reduce-scatter must land tile r on rank r — the exact
    layout of one flat psum_scatter (ZeRO state depends on it)."""
    n = comm.size
    topo = HierTopology(comm, intra=4)
    ax = comm.axis_names[0]
    L = n * 640
    rs = np.random.RandomState(1)
    x = rs.randint(-8, 8, size=(n, L)).astype(np.float32)
    ref = _shard_reduce(
        comm, lambda v: lax.psum_scatter(v, ax, tiled=True))(x)
    got = _shard_reduce(comm, lambda v: topo.reduce_scatter(v, ax))(x)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_hierarchical_dp_step_matches_flat(comm):
    model, params = _mlp_params(comm)
    data = _data(comm)
    red = HierarchicalReducer(comm, intra=4)
    l_flat, p_flat = _train(comm, model, params, None, 3, data)
    l_hier, p_hier = _train(comm, model, params, red, 3, data)
    np.testing.assert_allclose(l_flat, l_hier, rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        p_flat, p_hier)


def test_hierarchical_bad_intra_rejected(comm):
    with pytest.raises(ValueError, match="divide"):
        HierarchicalReducer(comm, intra=3)


# ---------------------------------------------------------------------------
# quantized: error feedback
# ---------------------------------------------------------------------------

def test_quantized_ef_convergence_vs_no_ef(comm):
    """The satellite-3 claim, in one calibrated regime (inputs * 1e-2,
    Adam): the int8 quantization floor (amax/254, pinned by the O(1)
    head-bias gradients) rounds the small weight gradients to zero, so

    * WITHOUT error feedback the tail loss is demonstrably worse;
    * WITH error feedback residuals accumulate past the floor and the
      run converges like flat.
    """
    from chainermn_tpu.datasets.toy import synthetic_mnist

    model, params = _mlp_params(comm)
    N, bs, steps = 2048, 128, 120
    train = synthetic_mnist(N, seed=0)
    xs = np.stack([train[i][0] for i in range(N)]).astype(np.float32) * 1e-2
    ys = np.array([train[i][1] for i in range(N)], np.int32)

    def run(gr):
        o = chainermn_tpu.create_multi_node_optimizer(
            optax.adam(1e-2), comm, grad_reducer=gr)
        p0 = jax.tree_util.tree_map(jnp.array, params)
        state = (p0, jax.jit(o.init)(p0))
        step = make_data_parallel_train_step(model, o, comm, donate=False)
        losses = []
        for i in range(steps):
            lo = (i * bs) % N
            state, m = step(state, xs[lo:lo + bs], ys[lo:lo + bs])
            losses.append(float(m["main/loss"]))  # per-iteration sync
        return losses

    flat = run(None)
    ef = run(QuantizedReducer(comm, mode="int8", ef=True))
    noef = run(QuantizedReducer(comm, mode="int8", ef=False))

    def tail(l):
        return float(np.mean(l[-10:]))

    assert all(np.isfinite(l).all() for l in (flat, ef, noef))
    # measured: flat 1.4e-3, ef 1.8e-3, noef 9.7e-3 — wide margins
    assert tail(flat) < 5e-3, tail(flat)
    assert tail(ef) < 5e-3, tail(ef)              # with-EF ~ flat
    assert tail(noef) > 3 * tail(ef), (tail(noef), tail(ef))


def test_quantized_bf16_stateless_tracks_flat(comm):
    model, params = _mlp_params(comm)
    data = _data(comm)
    l_flat, _ = _train(comm, model, params, None, 5, data)
    l_q, _ = _train(comm, model, params,
                    QuantizedReducer(comm, mode="bf16", ef=False), 5, data)
    np.testing.assert_allclose(l_flat, l_q, rtol=0.05, atol=0.02)


def test_quantized_ef_reduce_scatter_flat_ef_accounting(comm):
    """The lifted ZeRO hook: ``reduce_scatter_flat_ef`` returns the tile
    mean AND the residual (what the wire dropped, in the flat-bucket
    frame), with the conservation identity

        mean_r(g_r) == concat(tile_means) + mean_r(residual_r)

    and — on exactly int8-representable data — zero residual bitwise."""
    n = comm.size
    ax = comm.axis_names[0]
    red = QuantizedReducer(comm, mode="int8-block", ef=True)
    L = n * 512  # multiple of both n and QUANT_BLOCK

    def kernel(v):
        t, e = red.reduce_scatter_flat_ef(
            v[0], jnp.zeros_like(v[0]), ax, n)
        return t[None], e[None]

    f = jax.jit(shard_map(kernel, mesh=comm.mesh, in_specs=P(ax),
                          out_specs=(P(ax), P(ax))))

    rs = np.random.RandomState(0)
    g = rs.randn(n, L).astype(np.float32)
    tiles, res = f(g)
    np.testing.assert_allclose(
        np.asarray(tiles).reshape(-1) + np.asarray(res).mean(axis=0),
        g.mean(axis=0), rtol=1e-5, atol=1e-6)

    # exactly representable: integer values, block amax pinned to 127 ->
    # scale 1.0, quantization is lossless, residual is EXACTLY zero
    gi = rs.randint(-127, 128, size=(n, L)).astype(np.float32)
    gi[0, ::256] = 127.0
    tiles, res = f(gi)
    np.testing.assert_array_equal(np.asarray(res),
                                  np.zeros_like(np.asarray(res)))
    np.testing.assert_array_equal(np.asarray(tiles).reshape(-1),
                                  gi.mean(axis=0))


def test_quantized_ef_plain_reduce_scatter_still_refused(comm):
    """The STATELESS entry point must keep refusing an ef=True reducer —
    silently dropping the residual is the bug class the EF tests above
    exist for; the error directs to reduce_scatter_flat_ef."""
    red = QuantizedReducer(comm, mode="int8", ef=True)
    L = comm.size * 16
    ax = comm.axis_names[0]
    with pytest.raises(RuntimeError, match="reduce_scatter_flat_ef"):
        _shard_reduce(
            comm,
            lambda v: red.reduce_scatter_flat(v, ax, comm.size),
        )(np.ones((comm.size, L), np.float32))


# ---------------------------------------------------------------------------
# auto: cost model + measured override
# ---------------------------------------------------------------------------

def test_auto_choose_crossover(comm):
    red = AutoReducer(comm, intra=4)
    # tiny buckets are launch-latency bound -> flat; huge buckets want
    # the inter tier to carry 1/intra of the bytes -> hierarchical
    assert red.choose(1 << 10) == "flat"
    assert red.choose(32 << 20) == "hierarchical"
    # the crossover is monotone: once hierarchical wins it keeps winning
    strategies = [red.choose(1 << p) for p in range(8, 27)]
    flip = strategies.index("hierarchical")
    assert all(s == "hierarchical" for s in strategies[flip:])


def test_auto_measured_table_overrides_model(comm):
    measured = {("flat", 1 << 10): 50.0, ("hierarchical", 1 << 10): 1.0}
    red = AutoReducer(comm, intra=4, measured=measured)
    assert red.choose(1 << 10) == "hierarchical"


def test_auto_lossy_gate(comm):
    measured = {("flat", 1 << 20): 10.0,
                ("hierarchical", 1 << 20): 10.0,
                ("quantized", 1 << 20): 1.0}
    # quantized is never a candidate unless lossy=True is explicit
    assert AutoReducer(comm, intra=4,
                       measured=measured).choose(1 << 20) != "quantized"
    assert AutoReducer(comm, intra=4, measured=measured,
                       lossy=True).choose(1 << 20) == "quantized"


def test_measure_strategies_off_tpu_is_honest_null(comm):
    assert jax.devices()[0].platform != "tpu"
    assert measure_strategies(comm) == {}


def test_auto_dp_step_matches_flat(comm):
    model, params = _mlp_params(comm)
    data = _data(comm)
    l_flat, p_flat = _train(comm, model, params, None, 3, data)
    l_auto, p_auto = _train(comm, model, params, "auto", 3, data)
    np.testing.assert_allclose(l_flat, l_auto, rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        p_flat, p_auto)


# ---------------------------------------------------------------------------
# registry / plan / wire bytes
# ---------------------------------------------------------------------------

def test_registry_and_factory(comm):
    assert set(REDUCERS) >= {"flat", "hierarchical", "quantized", "auto"}
    assert make_grad_reducer(None, comm) is None
    inst = FlatReducer(comm)
    assert make_grad_reducer(inst, comm) is inst
    assert isinstance(make_grad_reducer("flat", comm), FlatReducer)
    with pytest.raises(ValueError, match="hierarchical.*quantized"):
        make_grad_reducer("pure_nccl", comm)
    with pytest.raises(ValueError, match="op"):
        FlatReducer(comm, op="max")


def test_plan_accounts_every_byte(comm):
    _, params = _mlp_params(comm)
    total = sum(l.size * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(params))
    for name in ("flat", "hierarchical"):
        rows = make_grad_reducer(name, comm).plan(params)
        assert sum(r["bytes"] for r in rows) == total
        assert all(r["wire_bytes"] == r["bytes"] for r in rows)
        assert all(r["algorithm"] == name for r in rows)


def test_quantized_plan_compresses_wire(comm):
    _, params = _mlp_params(comm)
    for mode, ratio in (("bf16", 2), ("int8", 4)):
        red = QuantizedReducer(comm, mode=mode)
        for r in red.plan(params):
            assert r["wire_bytes"] < r["bytes"]
            # per-bucket scale word aside, compression ~= dtype ratio
            assert r["wire_bytes"] <= r["bytes"] // ratio + 8


def test_auto_plan_carries_estimates_and_choice(comm):
    _, params = _mlp_params(comm)
    rows = AutoReducer(comm, intra=4).plan(params)
    assert rows
    for r in rows:
        assert r["algorithm"].startswith("auto:")
        assert r["est_us"] > 0


def test_describe_is_one_line_per_bucket(comm):
    _, params = _mlp_params(comm)
    red = make_grad_reducer("flat", comm)
    text = red.describe(params)
    assert len(text.splitlines()) == len(red.plan(params))
    assert "flat" in text and "bucket" in text


# ---------------------------------------------------------------------------
# ZeRO / FSDP wiring
# ---------------------------------------------------------------------------

def test_zero1_hierarchical_matches_default(comm):
    model, params = _mlp_params(comm)
    x, y = _data(comm)
    red = HierarchicalReducer(comm, intra=4)
    s0, st0 = make_zero1_train_step(model, optax.adam(1e-2), comm, params,
                                    donate=False)
    s1, st1 = make_zero1_train_step(model, optax.adam(1e-2), comm, params,
                                    donate=False, grad_reducer=red)
    for _ in range(3):
        st0, m0 = s0(st0, x, y)
        st1, m1 = s1(st1, x, y)
        np.testing.assert_allclose(float(m0["main/loss"]),
                                   float(m1["main/loss"]), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        zero1_params(st0, params), zero1_params(st1, params))


def test_zero1_stateful_reducer_accepted_and_trains(comm):
    """ZeRO-1 now ACCEPTS a stateful quantized reducer (PR 8): the
    per-rank EF residual rides _ReducerWrappedState in the flat-bucket
    frame. Short smoke here; the calibrated EF-vs-no-EF separation
    lives in test_quantized_wire.py."""
    model, params = _mlp_params(comm)
    x, y = _data(comm)
    step, state = make_zero1_train_step(
        model, optax.adam(1e-2), comm, params, donate=False,
        grad_reducer=QuantizedReducer(comm, mode="int8-block", ef=True))
    losses = []
    for _ in range(4):
        state, m = step(state, x, y)
        losses.append(float(m["main/loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # the residual state is there, in the flat-bucket frame: each rank
    # holds the FULL padded flat vector (that is the layout contract)
    assert state[1].reducer[0].shape == (comm.size, state[0].shape[0])


def test_fsdp_stateful_reducer_rejected(comm):
    model, params = _mlp_params(comm)
    with pytest.raises(ValueError, match="ef=False"):
        make_fsdp_train_step(
            model, optax.adam(1e-2), comm, params,
            grad_reducer=QuantizedReducer(comm, mode="int8", ef=True))


def test_fsdp_quantized_wire_roundtrip_converges(comm):
    model, params = _mlp_params(comm)
    x, y = _data(comm)
    step, state = make_fsdp_train_step(
        model, optax.adam(1e-2), comm, params, donate=False,
        grad_reducer=QuantizedReducer(comm, mode="bf16", ef=False))
    losses = []
    for _ in range(4):
        state, m = step(state, x, y)
        losses.append(float(m["main/loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

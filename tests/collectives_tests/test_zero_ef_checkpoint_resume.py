"""ZeRO EF residuals must survive a checkpoint/resume cycle.

The DP contract (test_ef_checkpoint_resume.py) applied to the ZeRO-1
flat path, where the residual lives in the FLAT-BUCKET frame inside
``_ReducerWrappedState`` — the layout PR 8 chose precisely so the state
is a plain leaf of the optimizer pytree and checkpoints with zero
special cases:

* a run checkpointed mid-flight and resumed into a FRESH state template
  reproduces the uninterrupted run's losses exactly, and the restored
  residuals are BITWISE the saved ones;
* the negative control — residuals zeroed on resume — visibly diverges.

Both the unbucketed (one full-vector residual) and bucketed (one
residual per bucket) layouts are covered.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from jax.sharding import NamedSharding, PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.collectives import QuantizedReducer
from chainermn_tpu.datasets.toy import synthetic_mnist
from chainermn_tpu.extensions import create_multi_node_checkpointer
from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers import make_zero1_train_step

STEPS, SPLIT, BS, N = 8, 4, 32, 256


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("xla")


@pytest.fixture(scope="module")
def data(comm):
    train = synthetic_mnist(N, seed=0)
    xs = np.stack([train[i][0] for i in range(N)]).astype(np.float32) * 1e-2
    ys = np.array([train[i][1] for i in range(N)], np.int32)
    return xs, ys


def _build(comm, bucket_bytes):
    model = MLP(n_units=16, n_out=10)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    params = comm.bcast_data(params)
    red = QuantizedReducer(comm, mode="int8", ef=True)
    return make_zero1_train_step(
        model, optax.adam(1e-2), comm, params, donate=False,
        bucket_bytes=bucket_bytes, grad_reducer=red)


def _run(comm, step, state, xs, ys, lo_step, hi_step):
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    losses = []
    for i in range(lo_step, hi_step):
        lo = (i * BS) % N
        state, m = step(state, jax.device_put(xs[lo:lo + BS], dsh),
                        jax.device_put(ys[lo:lo + BS], dsh))
        losses.append(float(m["main/loss"]))  # per-iteration sync
    return state, losses


def _residuals(state):
    # (p_shard(s), _ReducerWrappedState(inner=..., reducer=residuals))
    return state[1].reducer


@pytest.mark.parametrize("bucket_bytes", [None, 1 << 10],
                         ids=["flat", "bucketed"])
def test_zero1_ef_residuals_roundtrip_through_checkpoint(
        comm, data, tmp_path, bucket_bytes):
    xs, ys = data
    step, state0 = _build(comm, bucket_bytes)

    # uninterrupted reference
    _, ref = _run(comm, step, state0, xs, ys, 0, STEPS)

    # checkpointed run: fresh factory state, stop at SPLIT, save,
    # resume into ANOTHER fresh template
    _, fresh = _build(comm, bucket_bytes)
    mid, head = _run(comm, step, fresh, xs, ys, 0, SPLIT)
    np.testing.assert_allclose(head, ref[:SPLIT], rtol=1e-6)
    res_norm = sum(float(jnp.abs(l).sum())
                   for l in jax.tree_util.tree_leaves(_residuals(mid)))
    assert res_norm > 0, "no residual signal at the checkpoint — " \
        "the roundtrip claim would be vacuous"
    cp = create_multi_node_checkpointer("zero_ef", comm,
                                        path=str(tmp_path))
    cp.save(mid, iteration=SPLIT)

    cp2 = create_multi_node_checkpointer("zero_ef", comm,
                                         path=str(tmp_path))
    _, template = _build(comm, bucket_bytes)
    restored, it = cp2.maybe_load(template)
    assert it == SPLIT
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        _residuals(mid), _residuals(restored))

    _, tail = _run(comm, step, restored, xs, ys, SPLIT, STEPS)
    np.testing.assert_allclose(tail, ref[SPLIT:], rtol=1e-6)


def test_zero1_zeroed_residuals_diverge(comm, data):
    """Negative control: zero the residuals at SPLIT and the tail must
    leave the reference — the equality above is carried BY the
    residuals."""
    xs, ys = data
    step, state0 = _build(comm, None)
    _, ref = _run(comm, step, state0, xs, ys, 0, STEPS)
    _, fresh = _build(comm, None)
    mid, _ = _run(comm, step, fresh, xs, ys, 0, SPLIT)
    lopped = (mid[0], mid[1]._replace(
        reducer=jax.tree_util.tree_map(jnp.zeros_like, _residuals(mid))))
    _, tail = _run(comm, step, lopped, xs, ys, SPLIT, STEPS)
    assert max(abs(a - b) for a, b in zip(tail, ref[SPLIT:])) > 1e-6, (
        tail, ref[SPLIT:])

"""Hierarchical reduction over a REAL process boundary.

Two `jax.distributed` processes x four virtual CPU devices = one global
8-device mesh where `comm.intra_size == 4` / `inter_size == 2` — so
`HierarchicalReducer`'s DEFAULT topology (intra = comm.intra_size)
factors exactly along the process boundary: the reduce-scatter and
all-gather stay intra-process, only the shrunk inter all-reduce crosses
gloo (the CPU stand-in for DCN). Parity vs flat psum and a short
converging DP run, both over the real multi-process mesh.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mp_harness import assert_all_ok, run_workers

_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)
assert jax.process_count() == 2 and len(jax.devices()) == 8

sys.path.insert(0, os.environ["REPO_ROOT"])
import numpy as np
import jax.numpy as jnp
import optax

import chainermn_tpu  # installs the jax.shard_map shim (_compat)
from chainermn_tpu.collectives import HierarchicalReducer, HierTopology
from chainermn_tpu.models import MLP
from chainermn_tpu.training.step import make_data_parallel_train_step

from jax import lax, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

comm = chainermn_tpu.create_communicator("xla")
assert comm.size == 8 and comm.intra_size == 4, (comm.size, comm.intra_size)
ax = comm.axis_names[0]

# -- the default topology factors along the process boundary --------------
topo = HierTopology(comm)
assert (topo.intra, topo.inter) == (4, 2), (topo.intra, topo.inter)

# -- bitwise parity vs flat psum on integer-valued floats -----------------
rs = np.random.RandomState(0)
x = rs.randint(-8, 8, size=(8, 513)).astype(np.float32)  # odd: pads
sh = NamedSharding(comm.mesh, P(ax))
xg = jax.make_array_from_process_local_data(sh, x[proc_id * 4:(proc_id + 1) * 4])

def reduce_with(kernel):
    f = jax.jit(shard_map(lambda v: kernel(v[0])[None], mesh=comm.mesh,
                          in_specs=P(ax), out_specs=P(ax)))
    out = f(xg)
    return np.stack([np.asarray(s.data) for s in out.addressable_shards])

flat = reduce_with(lambda v: lax.psum(v, ax))
hier = reduce_with(topo.allreduce)
np.testing.assert_array_equal(flat, hier)
np.testing.assert_array_equal(flat[0, 0], x.sum(axis=0))

# -- short DP training run with grad_reducer='hierarchical' ---------------
model = MLP(n_units=16, n_out=10)
params = model.init(jax.random.PRNGKey(0),
                    np.zeros((2, 28, 28), np.float32))["params"]
params = comm.bcast_data(params)
opt = chainermn_tpu.create_multi_node_optimizer(
    optax.adam(1e-2), comm, grad_reducer=HierarchicalReducer(comm))
state = (params, jax.jit(opt.init)(params))
step = make_data_parallel_train_step(model, opt, comm, donate=False)

drs = np.random.RandomState(1)
n = 16
bx = drs.rand(n, 28, 28).astype(np.float32)
by = drs.randint(0, 10, size=(n,)).astype(np.int32)
bxg = jax.make_array_from_process_local_data(
    sh, bx[proc_id * 8:(proc_id + 1) * 8])
byg = jax.make_array_from_process_local_data(
    sh, by[proc_id * 8:(proc_id + 1) * 8])

losses = []
for _ in range(5):
    state, m = step(state, bxg, byg)
    losses.append(float(m["main/loss"]))  # per-iteration sync
assert np.isfinite(losses).all(), losses
assert losses[-1] < losses[0], losses

print(f"WORKER{proc_id} OK", flush=True)
"""


@pytest.mark.timeout(180)
def test_hierarchical_reduction_across_processes(tmp_path):
    procs, outs = run_workers(_WORKER, tmp_path)
    assert_all_ok(procs, outs)

"""The blockwise wire formats (PR 8): codec exactness, knob plumbing,
and the calibrated EF separation mirrored into the ZeRO/FSDP paths.

* codec — ``pack_int4``/``unpack_int4`` round-trip bitwise; the
  blockwise quantize/dequantize is EXACT on exactly-representable data
  (integer values with the block amax pinned to qmax) and bounded by
  one quantization step otherwise, including non-multiple-of-block
  tails.
* plumbing — ``wire_format=`` flows through ``make_grad_reducer`` and
  ``create_multi_node_optimizer``; narrow formats on non-compressing
  strategies are refused loudly.
* ZeRO — the test_reducers.py calibration (inputs * 1e-2, Adam 1e-2,
  120 steps: the int8 floor rounds the small weight gradients to zero)
  applied to ZeRO-1 and ZeRO-2: WITHOUT error feedback the tail loss
  stalls; WITH the flat-bucket-frame residual it converges like flat.
* FSDP — ``param_wire='int8-block'`` still converges, and the COMPILED
  program carries s8 all-gathers (DL205 confirms on the real HLO, not
  the host-side byte accounting).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from jax.sharding import NamedSharding, PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.analysis import check_quantized_wire_dtype
from chainermn_tpu.collectives import (
    QuantizedReducer,
    WIRE_FORMATS,
    block_dequantize,
    block_quantize,
    make_grad_reducer,
    pack_int4,
    quantized_wire_bytes,
    unpack_int4,
    wire_ratio,
)
from chainermn_tpu.collectives.quantized import QUANT_BLOCK
from chainermn_tpu.datasets.toy import synthetic_mnist
from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers.zero import (
    make_fsdp_train_step,
    make_zero1_train_step,
    make_zero2_train_step,
)


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("xla")


# ---------------------------------------------------------------------------
# codec exactness
# ---------------------------------------------------------------------------

def test_pack_int4_roundtrip_exact():
    """Every nibble value in every position: two codes per byte, and
    unpack(pack(q)) == q bitwise, odd lengths included."""
    for length in (2, 7, 16, 255, 256, 1000):
        rs = np.random.RandomState(length)
        q = rs.randint(-7, 8, size=(length,)).astype(np.int8)
        packed = np.asarray(pack_int4(jnp.asarray(q)))
        assert packed.dtype == np.uint8
        assert packed.size == (length + 1) // 2
        out = np.asarray(unpack_int4(jnp.asarray(packed), length))
        np.testing.assert_array_equal(out.astype(np.int8), q)


@pytest.mark.parametrize("mode,qmax", [("int8-block", 127),
                                       ("int4-block", 7)])
def test_block_codec_exact_on_representable(mode, qmax):
    """Integer values with each block's amax == qmax give scale 1.0:
    the round trip must be BITWISE (this is the property the EF
    zero-residual tests lean on)."""
    for length in (QUANT_BLOCK, 4 * QUANT_BLOCK, 4 * QUANT_BLOCK + 19):
        rs = np.random.RandomState(length)
        v = rs.randint(-qmax, qmax + 1, size=(length,)).astype(np.float32)
        v[::QUANT_BLOCK] = qmax  # pin every block's amax
        q, s = block_quantize(jnp.asarray(v), mode)
        out = np.asarray(block_dequantize(q, s, length, mode))
        np.testing.assert_array_equal(out, v)


@pytest.mark.parametrize("mode", ["int8-block", "int4-block"])
def test_block_codec_error_bounded_by_one_step(mode):
    """Arbitrary floats: |x - deq(q(x))| <= scale/2 per element, with
    the PER-BLOCK scale (this is what blockwise buys over one global
    amax — an outlier only poisons its own 256 elements)."""
    qmax = 127.0 if mode == "int8-block" else 7.0
    rs = np.random.RandomState(0)
    v = rs.randn(8 * QUANT_BLOCK).astype(np.float32)
    v[0] = 1e3  # outlier: global-amax would flatten everything else
    q, s = block_quantize(jnp.asarray(v), mode)
    out = np.asarray(block_dequantize(q, s, v.size, mode))
    step = np.repeat(np.asarray(s), QUANT_BLOCK)
    assert (np.abs(out - v) <= step / 2 + 1e-7).all()
    # the outlier block's step is huge; the others stay fine-grained
    assert np.asarray(s)[0] > 10 * np.asarray(s)[1:].max()
    assert np.abs(out[QUANT_BLOCK:] - v[QUANT_BLOCK:]).max() < 3.0 / qmax


def test_wire_bytes_accounting():
    """wire_ratio is the dtype width PLUS the block formats' f32-scale
    sidecar (1/256 extra); quantized_wire_bytes is the exact-integer
    form of the same accounting."""
    assert [wire_ratio(f) for f in WIRE_FORMATS] == [
        1.0, 0.5, 0.25, 0.25 + 1 / 256, 0.125 + 1 / 256]
    payload = 1 << 20  # f32 bytes -> 262144 elements -> 1024 blocks
    elems = payload // 4
    sidecar = 4 * (elems // QUANT_BLOCK)
    assert quantized_wire_bytes(payload, "bf16") == payload // 2
    assert quantized_wire_bytes(payload, "int8-block") == elems + sidecar
    assert (quantized_wire_bytes(payload, "int4-block")
            == elems // 2 + sidecar)
    # the headline gates: <= 0.27x / <= 0.14x of the flat f32 wire
    assert quantized_wire_bytes(payload, "int8-block") <= 0.27 * payload
    assert quantized_wire_bytes(payload, "int4-block") <= 0.14 * payload


# ---------------------------------------------------------------------------
# knob plumbing
# ---------------------------------------------------------------------------

def test_make_grad_reducer_wire_format(comm):
    red = make_grad_reducer("quantized", comm, wire_format="int4-block")
    assert red.mode == "int4-block"
    auto = make_grad_reducer("auto", comm, wire_format="int8-block")
    assert auto.wire_format == "int8-block"
    for strategy in ("flat", "hierarchical"):
        with pytest.raises(ValueError, match="wire_format"):
            make_grad_reducer(strategy, comm, wire_format="int8-block")
    with pytest.raises(ValueError, match="wire_format"):
        make_grad_reducer("quantized", comm, wire_format="int3")


def test_create_optimizer_wire_format(comm):
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(1e-2), comm, grad_reducer="quantized",
        wire_format="int8-block")
    assert opt.grad_reducer.mode == "int8-block"
    with pytest.raises(ValueError, match="compressing"):
        chainermn_tpu.create_multi_node_optimizer(
            optax.adam(1e-2), comm, wire_format="int8-block")


# ---------------------------------------------------------------------------
# the calibrated EF separation, mirrored into ZeRO
# ---------------------------------------------------------------------------

def _mlp_params(comm):
    model = MLP(n_units=32, n_out=10)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    return model, comm.bcast_data(params)


def _calib_data(comm):
    N = 2048
    train = synthetic_mnist(N, seed=0)
    xs = np.stack([train[i][0] for i in range(N)]).astype(np.float32) * 1e-2
    ys = np.array([train[i][1] for i in range(N)], np.int32)
    return xs, ys, N


def _run_steps(comm, step, state, xs, ys, n_elems, steps=120, bs=128):
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    losses = []
    for i in range(steps):
        lo = (i * bs) % n_elems
        state, m = step(state, jax.device_put(xs[lo:lo + bs], dsh),
                        jax.device_put(ys[lo:lo + bs], dsh))
        losses.append(float(m["main/loss"]))  # per-iteration sync
    return losses, state


def _tail(losses):
    return float(np.mean(losses[-10:]))


@pytest.mark.parametrize("zero", [1, 2])
def test_zero_ef_converges_no_ef_stalls(comm, zero):
    """test_reducers.py's calibrated regime run through the ZeRO flat
    paths. The separation control uses the GLOBAL-scale int8 wire (one
    amax per bucket, pinned by the O(1) head-bias gradients — exactly
    the DP calibration): without error feedback it stalls; with the
    flat-bucket-frame residual (threaded per scatter — per MICROBATCH
    in ZeRO-2) it converges like flat. The blockwise formats are then
    checked to TRACK flat: their per-256-element scales adapt to the
    small weight gradients, which is the point of blockwise — they must
    not need the calibrated stall to be usable."""
    model, params = _mlp_params(comm)
    xs, ys, N = _calib_data(comm)

    def build(grad_reducer):
        if zero == 1:
            return make_zero1_train_step(
                model, optax.adam(1e-2), comm, params, donate=False,
                grad_reducer=grad_reducer)
        return make_zero2_train_step(
            model, optax.adam(1e-2), comm, params, 2, donate=False,
            grad_reducer=grad_reducer)

    tails = {}
    for name, gr in (
            ("flat", None),
            ("ef", QuantizedReducer(comm, mode="int8", ef=True)),
            ("noef", QuantizedReducer(comm, mode="int8", ef=False)),
            ("blk8", QuantizedReducer(comm, mode="int8-block", ef=True)),
            ("blk4", QuantizedReducer(comm, mode="int4-block", ef=True))):
        step, state = build(gr)
        losses, _ = _run_steps(comm, step, state, xs, ys, N)
        assert np.isfinite(losses).all(), name
        tails[name] = _tail(losses)

    # measured (zero1): flat 1.4e-3, ef 1.8e-3, noef 9.7e-3,
    # blk8 1.8e-3, blk4 1.7e-3 — wide margins. ZeRO-2 quantizes each
    # MICROBATCH's (noisier) gradient with its own scale, which dithers
    # the rounding floor: no-EF lags (3.5e-3 vs ef 2.2e-3 at 120 steps)
    # instead of stalling outright, so its separation bar is lower.
    sep = 3.0 if zero == 1 else 1.4
    assert tails["flat"] < 5e-3, tails
    assert tails["ef"] < 5e-3, tails              # with-EF ~ flat
    assert tails["noef"] > sep * tails["ef"], tails
    assert tails["blk8"] < 5e-3, tails            # blockwise tracks flat
    assert tails["blk4"] < 5e-3, tails


# ---------------------------------------------------------------------------
# FSDP param_wire: converges AND the compiled wire is narrow
# ---------------------------------------------------------------------------

def test_fsdp_param_wire_converges_and_compiles_narrow(comm):
    model, params = _mlp_params(comm)
    xs, ys, N = _calib_data(comm)
    bs = 128

    ref_step, ref_state = make_fsdp_train_step(
        model, optax.adam(1e-2), comm, params, donate=False)
    ref, _ = _run_steps(comm, ref_step, ref_state, xs, ys, N,
                        steps=30, bs=bs)

    step, state = make_fsdp_train_step(
        model, optax.adam(1e-2), comm, params, donate=False,
        param_wire="int8-block")
    q, _ = _run_steps(comm, step, state, xs, ys, N, steps=30, bs=bs)
    assert np.isfinite(q).all()
    assert q[-1] < q[0]
    # int8-block params are a mild perturbation: the curve tracks the
    # f32-gather reference, it does not stall
    assert _tail(q[-10:]) < 2 * _tail(ref[-10:]) + 0.05, (q[-1], ref[-1])

    # the program, not the accounting: s8 codes cross the gather wire
    from jax.sharding import NamedSharding as _NS
    dsh = _NS(comm.mesh, P(comm.axis_names[0]))
    text = step.lower(state, jax.device_put(xs[:bs], dsh),
                      jax.device_put(ys[:bs], dsh)).compile().as_text()
    assert re.search(r"= s8\[[\d,]*\][^\n]* all-gather\(", text), (
        "no s8 all-gather in the compiled param_wire program")
    out = check_quantized_wire_dtype(text, expect_quantized=True)
    assert out["ok"] is True, out


def test_fsdp_param_wire_unknown_format_rejected(comm):
    model, params = _mlp_params(comm)
    with pytest.raises(ValueError, match="param_wire"):
        make_fsdp_train_step(model, optax.adam(1e-2), comm, params,
                             param_wire="int3")

"""Compiled-HLO structure of the reduction strategies.

The acceptance bar for ``hierarchical`` is not a loss curve — it is the
*program*: the compiled step must contain a reduce-scatter over the
intra tier, an all-reduce over the inter tier carrying ``1/intra`` of
the payload, and an all-gather back over the intra tier, chained in
that dataflow order — NOT one flat all-reduce. Verified with the same
HLO parse machinery the DL2xx passes use
(``chainermn_tpu.analysis.hlo_passes``).
"""

import re

import jax
import numpy as np
import pytest

from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.analysis.hlo_passes import parse_computations
from chainermn_tpu.collectives import HierTopology, QuantizedReducer

NELEM = 4096
INTRA = 4


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("xla")


def _compiled_text(comm, kernel):
    ax = comm.axis_names[0]
    x = np.ones((comm.size, NELEM), np.float32)
    f = jax.jit(shard_map(lambda v: kernel(v[0])[None], mesh=comm.mesh,
                          in_specs=P(ax), out_specs=P(ax)))
    return f.lower(x).compile().as_text()


def _collectives(text):
    """Ordered [(kind, result, operands)] per computation, collectives
    only."""
    out = {}
    for cname, ops in parse_computations(text).items():
        hits = [(k, res, operands) for k, res, operands in ops
                if k.split("-start")[0] in
                ("reduce-scatter", "all-reduce", "all-gather")]
        if hits:
            out[cname] = hits
    return out


def test_hierarchical_emits_rs_ar_ag_chain(comm):
    topo = HierTopology(comm, intra=INTRA)
    text = _compiled_text(comm, topo.allreduce)
    colls = _collectives(text)
    assert len(colls) == 1, colls
    (ops,) = colls.values()
    kinds = [k.split("-start")[0] for k, _, _ in ops]
    assert kinds == ["reduce-scatter", "all-reduce", "all-gather"], kinds
    # dataflow chain: ar consumes the rs result, ag consumes the ar
    rs, ar, ag = ops
    assert rs[1] in ar[2], (rs, ar)
    assert ar[1] in ag[2], (ar, ag)
    # the inter all-reduce carries 1/intra of the payload...
    ar_line = next(l for l in text.splitlines()
                   if re.search(r"= f32\[\d+\]\S* all-reduce\(", l))
    assert f"f32[{NELEM // INTRA}]" in ar_line, ar_line
    # ...across the inter groups (rank d = g*intra + j; inter walks g)
    inter = "{" + "},{".join(
        ",".join(str(j + g * INTRA) for g in range(comm.size // INTRA))
        for j in range(INTRA)) + "}"
    assert f"replica_groups={{{inter}}}" in ar_line, ar_line


def test_flat_emits_single_full_allreduce(comm):
    ax = comm.axis_names[0]
    text = _compiled_text(comm, lambda v: lax.psum(v, ax))
    colls = _collectives(text)
    assert len(colls) == 1, colls
    (ops,) = colls.values()
    kinds = [k.split("-start")[0] for k, _, _ in ops]
    assert kinds == ["all-reduce"], kinds
    assert "reduce-scatter" not in text and "all-gather" not in text
    ar_line = next(l for l in text.splitlines() if " all-reduce(" in l)
    assert f"f32[{NELEM}]" in ar_line, ar_line  # full payload, one hop


def test_quantized_int8_reduces_in_integers(comm):
    """The int8 wire format must be visible in the program: the gradient
    all-reduce accumulates s32 words, not f32."""
    red = QuantizedReducer(comm, mode="int8", ef=False)
    axes = comm.axis_names

    def kernel(v):
        from chainermn_tpu.collectives.quantized import quantize_allreduce
        return quantize_allreduce(v, axes, "int8")[0]

    text = _compiled_text(comm, kernel)
    int_ars = [l for l in text.splitlines()
               if re.search(r"= s32\[\d+\]\S* all-reduce\(", l)]
    assert int_ars, "no s32 all-reduce in the int8 quantized program"
    assert not re.search(r"= f32\[%d\]\S* all-reduce\(" % NELEM, text), (
        "quantized program still all-reduces the full f32 payload")


def test_quantized_blockwise_program_passes_dl205(comm):
    """The blockwise wire on REAL compiled HLO: codes all-reduce in s32
    (the f32 scale sidecar is the smaller collective), and the DL205
    pass — the same one dlint --hlo runs — confirms the dominant
    reduce is narrow."""
    from chainermn_tpu.analysis import check_quantized_wire_dtype
    from chainermn_tpu.collectives.quantized import quantize_allreduce

    axes = comm.axis_names
    for mode in ("int8-block", "int4-block"):
        text = _compiled_text(
            comm, lambda v: quantize_allreduce(v, axes, mode)[0])
        int_ars = [l for l in text.splitlines()
                   if re.search(r"= s32\[[\d,]+\]\S* all-reduce\(", l)]
        assert int_ars, f"no s32 all-reduce in the {mode} program"
        out = check_quantized_wire_dtype(text, expect_quantized=True)
        assert out["ok"] is True, (mode, out)
        assert out["dominant"]["reduce"]["dtype"] == "s32", (mode, out)

"""Test bootstrap: 8 virtual CPU devices with REAL XLA collectives.

The reference tests distributed behavior by running the whole pytest suite
under ``mpiexec -n 2`` on one host — real MPI/NCCL, tiny world, no mocks
(SURVEY.md §4). The TPU-native analog: force 8 host-platform devices so a
single process gets a real 8-device mesh whose collectives are real XLA
collectives, then run everything SPMD under jit/shard_map.

1-CORE SYNC RULE: this host has one CPU core. A test loop that dispatches
collective-bearing steps WITHOUT syncing each iteration (pull a scalar,
e.g. ``float(metrics["main/loss"])``, or ``jax.block_until_ready``) piles
up async executions until the XLA CPU collective rendezvous aborts the
process ("Fatal Python error: Aborted", load-dependent). FIXED r5:
every multi-iteration step loop in the suite (and in the embedded
multi-process worker scripts) now syncs per iteration — the r4 full-suite
abort came from test_multi_node_optimizer.py's 300-step loop, audited
along with every other loop via an AST scan for step-calling loops with
no sync marker in the body. New tests MUST keep the rule: sync (scalar
pull or block_until_ready) inside every step loop.
"""

import os

# Must run before jax initializes its backends. The environment may pin
# JAX_PLATFORMS to a TPU plugin (axon); tests always run on the virtual CPU
# mesh, so force it both via env and via jax.config (the latter wins even if
# a site hook rewrites the env var on import).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# jax version shims (top-level shard_map on older jaxlibs) — test modules
# import `from jax import shard_map` before importing chainermn_tpu, so
# apply the shim here, before collection.
from chainermn_tpu import _compat  # noqa: E402,F401

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def n_devices():
    return jax.device_count()


@pytest.fixture()
def comm():
    import chainermn_tpu

    return chainermn_tpu.create_communicator("xla")

"""Fail-fast job abort across REAL processes.

The reference's global_except_hook exists so one crashing rank kills the
job instead of leaving the others deadlocked inside a collective
(SURVEY.md §5). Here: process 0 (the jax.distributed coordinator host)
installs the hook and raises; the hook must hard-exit it with code 13
(NOT block in a graceful coordinator shutdown — the original failure mode
this test caught), and the surviving process must terminate promptly
rather than hang: either jax's coordination agent kills it on coordinator
loss, or the object plane's liveness/abort probes raise."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mp_harness import run_workers

_WORKER = r"""
import os, sys, time
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)

sys.path.insert(0, os.environ["REPO_ROOT"])
import chainermn_tpu
from chainermn_tpu.comm.object_plane import ObjectPlane

op = ObjectPlane()
# sync point: both processes alive
assert op.allgather_obj(proc_id) == [0, 1]

if proc_id == 0:
    chainermn_tpu.install_global_except_hook()
    raise RuntimeError("simulated rank crash")   # -> hook -> os._exit(13)

# survivor: give the crash a moment, then hit the object plane. The
# coordinator died with process 0, so this must not deadlock: either the
# jax coordination agent terminates this process first, or the collective
# raises through the object plane's fail-fast probes.
time.sleep(3)
try:
    op.allgather_obj("after-crash")
    print("WORKER1 COLLECTIVE SUCCEEDED UNEXPECTEDLY", flush=True)
    sys.exit(1)
except BaseException as e:
    print(f"WORKER1 SAW ABORT: {type(e).__name__}", flush=True)
    os._exit(0)
"""


@pytest.mark.timeout(180)
def test_crash_aborts_instead_of_deadlocking(tmp_path):
    procs, outs = run_workers(_WORKER, tmp_path, timeout=150)
    assert procs[0].returncode == 13, (
        f"crasher should hard-exit 13:\n{outs[0][-2000:]}")
    # the survivor must TERMINATE promptly, by either fail-fast path:
    # our probes raising (exit 0 + marker) or jax's coordination agent
    # terminating the process on coordinator loss (fatal nonzero exit)
    saw_probe_abort = ("WORKER1 SAW ABORT" in outs[1]
                       and procs[1].returncode == 0)
    saw_agent_kill = (procs[1].returncode not in (None, 0)
                      and ("Terminating process" in outs[1]
                           or "coordination" in outs[1]))
    assert saw_probe_abort or saw_agent_kill, (
        f"survivor neither raised nor was terminated "
        f"(rc={procs[1].returncode}):\n{outs[1][-2000:]}")
    assert "SUCCEEDED UNEXPECTEDLY" not in outs[1]

"""Checkpoint hardening: atomic publish + SHA-256 manifests + the
election's corrupt-snapshot fallback (single process; the cross-process
matrix lives in test_multiprocess_chaos.py)."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu
from chainermn_tpu.resilience import chaos


@pytest.fixture
def comm():
    return chainermn_tpu.create_communicator("xla")


def _ck(comm, tmp_path, **kw):
    kw.setdefault("cp_interval", 5)
    return chainermn_tpu.create_multi_node_checkpointer(
        "hard", comm, path=str(tmp_path), **kw)


def _state(it):
    return {"w": jnp.full((8,), float(it), jnp.float32)}


def _snap(tmp_path, it, rank=0):
    return os.path.join(str(tmp_path), "hard", f"snapshot_iter_{it}.{rank}")


def test_save_publishes_manifest_with_matching_sha(tmp_path, comm):
    ck = _ck(comm, tmp_path)
    fn = ck.save(_state(10), 10)
    manifest = json.load(open(fn + ".json"))
    assert manifest["format"] == 1
    assert manifest["bytes"] == os.path.getsize(fn)
    import hashlib

    assert manifest["sha256"] == hashlib.sha256(
        open(fn, "rb").read()).hexdigest()
    assert not os.path.exists(fn + ".npz")  # tmp name gone after publish
    assert ck._verify_snapshot_file(fn)


def test_corrupt_snapshot_excluded_from_election(tmp_path, comm):
    ck = _ck(comm, tmp_path)
    ck.save(_state(10), 10)
    ck.save(_state(20), 20)
    # flip bytes in the newest file (what a bad disk would do)
    fn = _snap(tmp_path, 20)
    with open(fn, "rb+") as fh:
        fh.seek(30)
        fh.write(b"\xff" * 16)
    assert not ck._verify_snapshot_file(fn)
    assert ck.latest_common_iteration() == 10  # falls back
    restored, it = ck.maybe_load(_state(0))
    assert it == 10
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((8,), 10.0, np.float32))


def test_truncated_snapshot_fails_size_fast_path(tmp_path, comm):
    ck = _ck(comm, tmp_path)
    ck.save(_state(10), 10)
    fn = _snap(tmp_path, 10)
    with open(fn, "rb+") as fh:
        fh.truncate(os.path.getsize(fn) // 2)
    assert not ck._verify_snapshot_file(fn)
    assert ck.latest_common_iteration() is None


def test_explicit_load_of_corrupt_snapshot_raises(tmp_path, comm):
    ck = _ck(comm, tmp_path)
    ck.save(_state(10), 10)
    fn = _snap(tmp_path, 10)
    with open(fn, "rb+") as fh:
        fh.seek(0)
        fh.write(b"\x00" * 8)
    with pytest.raises(ValueError, match="SHA-256"):
        ck.maybe_load(_state(0), iteration=10)


def test_legacy_snapshot_without_manifest_still_elects(tmp_path, comm):
    ck = _ck(comm, tmp_path)
    ck.save(_state(10), 10)
    os.remove(_snap(tmp_path, 10) + ".json")  # pre-hardening snapshot
    assert ck._verify_snapshot_file(_snap(tmp_path, 10))
    assert ck.latest_common_iteration() == 10


def test_torn_manifest_marks_snapshot_suspect(tmp_path, comm):
    ck = _ck(comm, tmp_path)
    ck.save(_state(10), 10)
    with open(_snap(tmp_path, 10) + ".json", "w") as fh:
        fh.write('{"format": 1, "sha')  # torn mid-write
    assert not ck._verify_snapshot_file(_snap(tmp_path, 10))


def test_gc_removes_manifest_with_snapshot(tmp_path, comm):
    ck = _ck(comm, tmp_path, cp_interval=2)
    for it in (10, 20, 30):
        ck.save(_state(it), it)
    assert not os.path.exists(_snap(tmp_path, 10))
    assert not os.path.exists(_snap(tmp_path, 10) + ".json")
    assert os.path.exists(_snap(tmp_path, 30) + ".json")


def test_host_state_rides_snapshot_and_sha(tmp_path, comm):
    ck = _ck(comm, tmp_path)
    host = {"iteration": 10, "np_random": np.random.get_state(),
            "note": "host side"}
    ck.save(_state(10), 10, host_state=host)
    got = ck.load_host_state(10)
    assert got["iteration"] == 10
    assert got["note"] == "host side"
    assert got["np_random"][0] == host["np_random"][0]
    np.testing.assert_array_equal(got["np_random"][1],
                                  host["np_random"][1])
    # snapshots without host state read back as None
    ck.save(_state(20), 20)
    assert ck.load_host_state(20) is None


def test_chaos_corrupt_hook_fires_on_publish(tmp_path, comm, monkeypatch):
    """End-to-end: $CHAINERMN_TPU_CHAOS damages the file right after a
    fully valid publish, and the manifest proves it."""
    ck = _ck(comm, tmp_path)
    ck.save(_state(10), 10)
    monkeypatch.setenv(chaos.ENV_VAR, "corrupt@match=snapshot_iter_20")
    ck.save(_state(20), 20)
    monkeypatch.delenv(chaos.ENV_VAR)
    assert not ck._verify_snapshot_file(_snap(tmp_path, 20))
    assert ck.latest_common_iteration() == 10


def test_emergency_save_publishes_synchronously(tmp_path, comm):
    ck = _ck(comm, tmp_path, async_write=True)

    class FakeUpdater:
        state = _state(7)
        iteration = 7

        def host_state_dict(self):
            return {"iteration": 7}

    class FakeTrainer:
        updater = FakeUpdater()

    fn = ck.emergency_save(FakeTrainer())
    assert fn and os.path.exists(fn) and os.path.exists(fn + ".json")
    assert ck._verify_snapshot_file(fn)
    assert ck.load_host_state(7) == {"iteration": 7}
    ck.close()


def test_emergency_save_respects_expired_deadline(tmp_path, comm):
    import time

    ck = _ck(comm, tmp_path)

    class FakeUpdater:
        state = _state(7)
        iteration = 7

    class FakeTrainer:
        updater = FakeUpdater()

    assert ck.emergency_save(
        FakeTrainer(), deadline_s=time.monotonic() - 1) is None
    assert not os.path.exists(_snap(tmp_path, 7))

"""Cross-process checkpoint consensus: the reference's "newest iteration
present on ALL ranks" election with REAL processes.

Two `jax.distributed` processes share a snapshot directory; process 1
"crashes" before writing the newest snapshot, so the election must settle
on the last iteration both processes hold, and each restores its own file
(reference semantics: per-rank snapshots, allgather inventory, SURVEY.md
§3.5)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mp_harness import assert_all_ok, run_workers

_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)

sys.path.insert(0, os.environ["REPO_ROOT"])
import numpy as np
import jax.numpy as jnp
import chainermn_tpu

comm = chainermn_tpu.create_communicator("xla")
ck = chainermn_tpu.create_multi_node_checkpointer(
    "consensus", comm, path=os.environ["CKPT_DIR"], cp_interval=5)

def state_at(it):
    # per-process content so restore provably reads THIS process's file
    return {"w": jnp.full((4,), it * 100 + proc_id, jnp.float32),
            "it": jnp.asarray(it, jnp.int32)}

# both processes snapshot 10 and 20; only process 0 reaches 30
ck.save(state_at(10), 10)
ck.save(state_at(20), 20)
if proc_id == 0:
    ck.save(state_at(30), 30)

elected = ck.latest_common_iteration()
assert elected == 20, f"proc{proc_id}: elected {elected}"

restored, it = ck.maybe_load(state_at(0))
assert it == 20, it
np.testing.assert_array_equal(
    np.asarray(restored["w"]), np.full((4,), 2000 + proc_id, np.float32))
assert int(restored["it"]) == 20

# explicit-iteration load still works for the iteration only proc 0 has?
# No — maybe_load(iteration=30) on proc 1 must fail to find its file;
# consensus exists precisely to prevent that. Verify the guard holds:
if proc_id == 1:
    try:
        ck.maybe_load(state_at(0), iteration=30)
        raise SystemExit("proc1 loaded a snapshot it never wrote")
    except FileNotFoundError:
        pass

print(f"WORKER{proc_id} OK", flush=True)
"""


@pytest.mark.timeout(120)
def test_two_process_checkpoint_consensus(tmp_path):
    procs, outs = run_workers(
        _WORKER, tmp_path, timeout=110,
        env_extra={"CKPT_DIR": str(tmp_path / "snaps")})
    assert_all_ok(procs, outs)

"""Elastic matrix with REAL jax.distributed processes (ISSUE 4
acceptance): the supervisor + shrink-to-fit + replication layers under
actual process death.

* supervisor smoke — both ranks run under in-worker Supervisors on a
  per-incarnation coordinator port; chaos SIGKILLs BOTH first
  incarnations at step 7 (``run=0`` pins the fault to incarnation 0,
  so the restart heals), the supervisors relaunch, and the second
  incarnations elect the last common snapshot and finish with losses
  matching an uninterrupted run. The kill is symmetric on purpose:
  every rank crashes exactly once, so the incarnation counters (and
  with them the per-incarnation coordinator port) stay aligned without
  cross-host agreement — the asymmetric death → watchdog-abort → 75
  leg is covered by tests/resilience_tests/test_supervisor.py and the
  watchdog case in test_multiprocess_chaos.py;
* shrink-to-fit — a 2-rank run snapshots to completion, then rank 1's
  host (and every one of its files) is permanently gone: a world-1
  resume re-splices rank 0's shard, re-scatters the dataset, and keeps
  training with finite losses;
* replica recovery (slow) — ring replication during training leaves
  each rank's shard on its neighbor; with ALL of rank 1's primaries
  deleted, a same-world restart still elects the NEWEST iteration and
  rank 1 restores from the pushed-back replica.

Workers self-inject faults from $CHAINERMN_TPU_CHAOS — the training
code never knows it is under test."""

import glob
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mp_harness import assert_all_ok, run_workers

_NO_MP_CPU = "Multiprocess computations aren't implemented on the CPU backend"

# deterministic host-only training job, identical to the chaos matrix:
# identically seeded iterators on every rank make loss sequences exactly
# comparable without cross-process device support
TOTAL = 12
BS = 8


def _dataset():
    return [(np.full((2,), float(i), np.float32), np.asarray(i, np.int32))
            for i in range(40)]


def _expected_losses():
    from chainermn_tpu.iterators import SerialIterator

    exp, s = [], np.float32(0.0)
    it = SerialIterator(_dataset(), BS, shuffle=True, seed=3)
    for _ in range(TOTAL):
        batch = next(it)
        s = s + np.float32(np.stack([b[0] for b in batch]).mean())
        exp.append(float(s))
    return exp


_TRAIN_COMMON = r"""
import numpy as np
import chainermn_tpu
from chainermn_tpu.iterators import SerialIterator
from chainermn_tpu.training import StandardUpdater, Trainer

comm = chainermn_tpu.create_communicator("xla")
TOTAL = 12

def dataset():
    return [(np.full((2,), float(i), np.float32), np.asarray(i, np.int32))
            for i in range(40)]

def step(state, x, y):
    new = state + np.float32(np.asarray(x).mean())
    return new, {"loss": float(new)}

def make_updater():
    it = SerialIterator(dataset(), 8, shuffle=True, seed=3)
    u = StandardUpdater(it, step, np.float32(0.0), comm)
    u.shard_batch = lambda arrays: arrays
    return u

def make_ck():
    return chainermn_tpu.create_multi_node_checkpointer(
        "elastic", comm, path=os.environ["CKPT_DIR"], cp_interval=5)

exp = []
_s, _it = np.float32(0.0), SerialIterator(dataset(), 8, shuffle=True, seed=3)
for _ in range(TOTAL):
    batch = next(_it)
    _s = _s + np.float32(np.stack([b[0] for b in batch]).mean())
    exp.append(float(_s))
"""


# -- supervisor smoke ---------------------------------------------------

_SUPERVISED_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
base_port = int(sys.argv[2])
mode = sys.argv[3] if len(sys.argv) > 3 else "supervise"
sys.path.insert(0, os.environ["REPO_ROOT"])

if mode == "supervise":
    # the per-host parent: wraps THIS script in inner mode and restarts
    # it per the exit-status contract
    from chainermn_tpu.resilience.supervisor import Supervisor

    sup = Supervisor([sys.executable, sys.argv[0], sys.argv[1],
                      sys.argv[2], "inner"],
                     max_restarts=3, window_s=120.0)
    sys.exit(sup.run())

# ---- one training incarnation ----
# each incarnation gets its own coordinator port: the previous
# incarnation's coordinator (hosted by rank 0's dead process) must not
# be confused with the new job
incarnation = int(os.environ.get("CHAINERMN_TPU_RESTART_COUNT", "0"))
port = base_port + incarnation
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["CHAINERMN_TPU_CHAOS_RANK"] = str(proc_id)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id, initialization_timeout=60)

""" + _TRAIN_COMMON + r"""
from chainermn_tpu.resilience.supervisor import main_exit_code

def main():
    ck = make_ck()
    u = make_updater()
    if incarnation > 0:
        # restarted job: consensus resume — both ranks died at step 7,
        # so the last common snapshot is 6
        elected = ck.resume(u)
        assert elected == 6, f"rank{proc_id} inc{incarnation}: {elected}"
        assert float(u.state) == float(np.float32(exp[5])), float(u.state)
    losses = []
    t = Trainer(u, stop_trigger=(TOTAL, "iteration"))
    t.extend(lambda tr: losses.append(tr.updater.last_metrics["loss"]),
             trigger=(1, "iteration"))
    t.extend(ck, trigger=(3, "iteration"))
    t.run()
    if incarnation > 0:
        assert losses == exp[6:], f"rank{proc_id}: {losses} vs {exp[6:]}"
    # all-rank fence: both second incarnations must be alive and agree
    comm.allgather_obj(("done", proc_id))
    print(f"WORKER{proc_id} OK incarnation {incarnation}", flush=True)
    return t

code = main_exit_code(main)
if code == 0:
    # clean finish means every peer is alive: deregister through the
    # coordination shutdown barrier, so the leader's exit cannot be
    # mistaken for a death and SIGABRT a peer that is still deregistering
    jax.distributed.shutdown()
os._exit(code)  # crashed/aborted: skip teardown, the peer may be gone
"""


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_supervised_kill_restart_resumes_and_finishes(tmp_path):
    procs, outs = run_workers(
        _SUPERVISED_WORKER, tmp_path, timeout=150,
        env_extra={
            "CKPT_DIR": str(tmp_path / "snaps"),
            "CHAINERMN_TPU_CHAOS": "kill@step=7,run=0",
        })
    if any(_NO_MP_CPU in o for o in outs):
        pytest.skip("jaxlib CPU backend lacks cross-process computations")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"supervisor {i} failed:\n{out[-4000:]}"
        assert f"WORKER{i} OK incarnation 1" in out
        # the supervisor observed the SIGKILL, then the healed rerun
        assert "(crash)" in out, out[-2000:]
        assert "(clean)" in out, out[-2000:]


# -- shrink-to-fit: world 2 -> world 1 ----------------------------------

_SHRINK_PHASE1 = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)
sys.path.insert(0, os.environ["REPO_ROOT"])
""" + _TRAIN_COMMON + r"""
ck = make_ck()
u = make_updater()
t = Trainer(u, stop_trigger=(TOTAL, "iteration"))
t.extend(ck, trigger=(3, "iteration"))
t.run()
assert u.iteration == TOTAL
print(f"WORKER{proc_id} OK", flush=True)
jax.distributed.shutdown()  # barrier: no rank dies while a peer works
os._exit(0)
"""


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_shrink_to_fit_resumes_at_world_one(tmp_path):
    ckpt = str(tmp_path / "snaps")
    procs, outs = run_workers(
        _SHRINK_PHASE1, tmp_path, timeout=110,
        env_extra={"CKPT_DIR": ckpt})
    assert_all_ok(procs, outs)

    # rank 1's host is permanently gone: every file it ever wrote too
    job = os.path.join(ckpt, "elastic")
    gone = glob.glob(os.path.join(job, "snapshot_iter_*.1*"))
    assert gone, "phase 1 produced no rank-1 snapshots"
    for f in gone:
        os.remove(f)

    # world-1 resume IN-PROCESS (a single survivor needs no coordinator)
    import chainermn_tpu
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.training import StandardUpdater
    from chainermn_tpu.resilience.elastic import elastic_resume

    exp = _expected_losses()
    comm = chainermn_tpu.create_communicator("xla")
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "elastic", comm, path=ckpt, cp_interval=5)
    data = _dataset()

    def step(state, x, y):
        new = state + np.float32(np.asarray(x).mean())
        return new, {"loss": float(new)}

    it = SerialIterator(data, BS, shuffle=True, seed=3)
    u = StandardUpdater(it, step, np.float32(0.0), comm)
    u.shard_batch = lambda arrays: arrays

    plan = elastic_resume(ck, u, global_dataset=data)
    assert plan.action == "shrink"
    assert plan.saved_world == 2 and plan.new_world == 1
    assert u.iteration == TOTAL
    # the state is replicated in this job shape: rank 0's shard is the
    # whole state, restored exactly
    assert float(u.state) == float(np.float32(exp[-1])), float(u.state)
    # training continues on the rebalanced world with finite losses
    for _ in range(4):
        u.update()
        assert np.isfinite(u.last_metrics["loss"])
    assert u.iteration == TOTAL + 4


# -- replica recovery: newest iteration survives its host ---------------

_REPLICA_PHASE1 = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)
sys.path.insert(0, os.environ["REPO_ROOT"])
""" + _TRAIN_COMMON + r"""
from chainermn_tpu.resilience import PeerReplicator

ck = make_ck()
u = make_updater()
t = Trainer(u, stop_trigger=(TOTAL, "iteration"))
t.extend(ck, trigger=(3, "iteration"))
t.extend(PeerReplicator(ck), trigger=(3, "iteration"))  # after the save
t.run()
print(f"WORKER{proc_id} OK", flush=True)
jax.distributed.shutdown()  # barrier: no rank dies while a peer works
os._exit(0)
"""


_REPLICA_PHASE2 = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)
sys.path.insert(0, os.environ["REPO_ROOT"])
""" + _TRAIN_COMMON + r"""
# rank 1's primaries are gone, but the ring pushed its shards back:
# the election must still find the NEWEST iteration, and rank 1 must
# restore from the replica
ck = make_ck()
u = make_updater()
elected = ck.resume(u)
assert elected == TOTAL, f"rank{proc_id}: elected {elected}"
assert u.iteration == TOTAL
assert float(u.state) == float(np.float32(exp[-1])), float(u.state)
print(f"WORKER{proc_id} OK", flush=True)
jax.distributed.shutdown()  # barrier: no rank dies while a peer works
os._exit(0)
"""


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_replica_recovery_elects_newest_iteration(tmp_path):
    ckpt = str(tmp_path / "snaps")
    procs, outs = run_workers(
        _REPLICA_PHASE1, tmp_path, timeout=110,
        env_extra={"CKPT_DIR": ckpt})
    assert_all_ok(procs, outs)

    job = os.path.join(ckpt, "elastic")
    replicas = os.path.join(job, "replicas")
    # the ring left each rank's newest shard on its neighbor (shared
    # tmpdir in this harness, so both land in the same replicas/)
    assert os.path.exists(os.path.join(replicas, "snapshot_iter_12.0"))
    assert os.path.exists(os.path.join(replicas, "snapshot_iter_12.1"))

    # rank 1's host dies and is replaced: ALL its primaries are gone
    gone = [f for f in glob.glob(os.path.join(job, "snapshot_iter_*.1*"))
            if os.path.dirname(f) == job]
    assert gone
    for f in gone:
        os.remove(f)

    procs, outs = run_workers(
        _REPLICA_PHASE2, tmp_path, timeout=110,
        env_extra={"CKPT_DIR": ckpt})
    assert_all_ok(procs, outs)

"""Chaos matrix with REAL jax.distributed processes: the acceptance
criteria of the resilience layer.

* kill/resume — SIGKILL one rank mid-run (chaos harness, env-injected),
  restart the job, and the consensus election resumes from the last
  snapshot BOTH ranks hold, with the resumed loss sequence matching an
  uninterrupted run exactly (full-state resume: iterator position +
  shuffle RNG ride the snapshot);
* corruption fallback — one rank's newest snapshot is damaged right
  after publish; the SHA-256 manifest catches it and the election falls
  back to the previous window entry;
* SIGTERM preemption — both ranks get SIGTERM mid-step; the preemption
  guard fires an emergency all-rank checkpoint and exits cleanly;
* watchdog (slow) — a rank dies while its peer waits in an object-plane
  collective; the heartbeat watchdog converts the infinite wait into a
  bounded JobAbortedError.

Workers self-inject faults from $CHAINERMN_TPU_CHAOS — the training code
never knows it is under test."""

import os
import signal
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mp_harness import assert_all_ok, run_workers

# common prelude: a deterministic host-only training job (no device
# collectives — every rank computes identical arithmetic from identically
# seeded iterators, so cross-process device support is not required and
# loss sequences are exactly comparable)
_TRAIN_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["CHAINERMN_TPU_CHAOS_RANK"] = str(proc_id)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)

sys.path.insert(0, os.environ["REPO_ROOT"])
import numpy as np
import chainermn_tpu
from chainermn_tpu.iterators import SerialIterator
from chainermn_tpu.training import StandardUpdater, Trainer

comm = chainermn_tpu.create_communicator("xla")
TOTAL = 12

def dataset():
    return [(np.full((2,), float(i), np.float32), np.asarray(i, np.int32))
            for i in range(40)]

def step(state, x, y):
    new = state + np.float32(np.asarray(x).mean())
    return new, {"loss": float(new)}

def make_updater():
    it = SerialIterator(dataset(), 8, shuffle=True, seed=3)
    u = StandardUpdater(it, step, np.float32(0.0), comm)
    u.shard_batch = lambda arrays: arrays
    return u

def make_ck():
    return chainermn_tpu.create_multi_node_checkpointer(
        "chaos", comm, path=os.environ["CKPT_DIR"], cp_interval=5)

# the expected uninterrupted loss sequence, replayed locally
exp = []
_s, _it = np.float32(0.0), SerialIterator(dataset(), 8, shuffle=True, seed=3)
for _ in range(TOTAL):
    batch = next(_it)
    _s = _s + np.float32(np.stack([b[0] for b in batch]).mean())
    exp.append(float(_s))

phase = os.environ["CHAOS_PHASE"]
"""


_KILL_PHASE = _TRAIN_WORKER + r"""
# phase 1: rank 1 is SIGKILLed at step 7 (chaos env); rank 0 finishes
ck = make_ck()
u = make_updater()
t = Trainer(u, stop_trigger=(TOTAL, "iteration"))
losses = []
t.extend(lambda tr: losses.append(tr.updater.last_metrics["loss"]),
         trigger=(1, "iteration"))
t.extend(ck, trigger=(3, "iteration"))
t.run()
assert proc_id == 0, "rank 1 should have been killed before finishing"
assert losses == exp, f"rank0 losses diverged: {losses}"
print(f"WORKER{proc_id} OK", flush=True)
os._exit(0)  # peer is dead: the shutdown barrier would hang, skip it
"""


_RESUME_PHASE = _TRAIN_WORKER + r"""
# phase 2: restart — both ranks elect the last COMMON snapshot (6: rank 1
# died at 7, so its window holds 3 and 6) and continue to completion
ck = make_ck()
u = make_updater()
elected = ck.resume(u)
assert elected == 6, f"rank{proc_id}: elected {elected}"
assert u.iteration == 6
assert float(u.state) == float(np.float32(exp[5])), (
    f"rank{proc_id}: resumed state {float(u.state)} != {exp[5]}")
losses = []
t = Trainer(u, stop_trigger=(TOTAL, "iteration"))
t.extend(lambda tr: losses.append(tr.updater.last_metrics["loss"]),
         trigger=(1, "iteration"))
t.run()
assert losses == exp[6:], (
    f"rank{proc_id}: resumed losses diverged: {losses} vs {exp[6:]}")
print(f"WORKER{proc_id} OK", flush=True)
jax.distributed.shutdown()  # barrier: no rank dies while a peer works
os._exit(0)
"""


@pytest.mark.timeout(240)
def test_kill_one_rank_then_resume_matches_uninterrupted(tmp_path):
    ckpt = str(tmp_path / "snaps")
    # phase 1: chaos kills rank 1 at step 7 (snapshots at 3 and 6 exist)
    procs, outs = run_workers(
        _KILL_PHASE, tmp_path, timeout=110,
        env_extra={"CKPT_DIR": ckpt, "CHAOS_PHASE": "kill",
                   "CHAINERMN_TPU_CHAOS": "kill@step=7,rank=1"})
    if any("aren't implemented on the CPU backend" in o for o in outs):
        pytest.skip("jaxlib CPU backend lacks cross-process computations")
    assert procs[0].returncode == 0, f"rank0 failed:\n{outs[0][-3000:]}"
    assert "WORKER0 OK" in outs[0]
    assert procs[1].returncode == -signal.SIGKILL, (
        f"rank1 should die by SIGKILL, got {procs[1].returncode}:"
        f"\n{outs[1][-3000:]}")
    # rank 1's window stops at 6; rank 0 kept snapshotting to 12
    assert os.path.exists(os.path.join(ckpt, "chaos", "snapshot_iter_6.1"))
    assert not os.path.exists(
        os.path.join(ckpt, "chaos", "snapshot_iter_9.1"))

    # phase 2: restart the job — consensus resume from 6, losses must
    # match the uninterrupted run exactly
    procs, outs = run_workers(
        _RESUME_PHASE, tmp_path, timeout=110,
        env_extra={"CKPT_DIR": ckpt, "CHAOS_PHASE": "resume"})
    assert_all_ok(procs, outs)


_CORRUPT_PHASE = _TRAIN_WORKER + r"""
# rank 1's newest snapshot (iter 6) is corrupted right after publish by
# the chaos harness; the election must fall back to 3
ck = make_ck()
u = make_updater()
t = Trainer(u, stop_trigger=(6, "iteration"))
t.extend(ck, trigger=(3, "iteration"))
t.run()
elected = ck.latest_common_iteration()
assert elected == 3, f"rank{proc_id}: elected {elected}, wanted 3"
state, it = ck.maybe_load(np.float32(0.0))
assert it == 3
assert float(state) == float(np.float32(exp[2])), float(state)
print(f"WORKER{proc_id} OK", flush=True)
jax.distributed.shutdown()  # barrier: no rank dies while a peer works
os._exit(0)
"""


@pytest.mark.timeout(240)
def test_corrupt_newest_snapshot_falls_back_to_previous(tmp_path):
    procs, outs = run_workers(
        _CORRUPT_PHASE, tmp_path, timeout=110,
        env_extra={
            "CKPT_DIR": str(tmp_path / "snaps"),
            "CHAOS_PHASE": "corrupt",
            "CHAINERMN_TPU_CHAOS": "corrupt@match=snapshot_iter_6,rank=1",
        })
    assert_all_ok(procs, outs)


_SIGTERM_PHASE = _TRAIN_WORKER + r"""
# both ranks get SIGTERM at step 5 (self-injected): the preemption guard
# fires an emergency checkpoint and the loop exits cleanly
ck = make_ck()
u = make_updater()
t = Trainer(u, stop_trigger=(TOTAL, "iteration"))
t.extend(ck, trigger=(3, "iteration"))
t.run()
assert t.preempted, "SIGTERM did not set trainer.preempted"
it5 = u.iteration
assert 5 <= it5 <= 6, it5
fn = os.path.join(os.environ["CKPT_DIR"], "chaos",
                  f"snapshot_iter_{it5}.{proc_id}")
assert os.path.exists(fn), f"no emergency snapshot {fn}"
assert os.path.exists(fn + ".json"), "no manifest for emergency snapshot"
assert ck._verify_snapshot_file(fn)
print(f"WORKER{proc_id} OK", flush=True)
jax.distributed.shutdown()  # barrier: no rank dies while a peer works
os._exit(0)
"""


@pytest.mark.timeout(240)
def test_sigterm_both_ranks_emergency_checkpoint_clean_exit(tmp_path):
    procs, outs = run_workers(
        _SIGTERM_PHASE, tmp_path, timeout=110,
        env_extra={
            "CKPT_DIR": str(tmp_path / "snaps"),
            "CHAOS_PHASE": "sigterm",
            "CHAINERMN_TPU_CHAOS": "kill@step=5,signal=SIGTERM",
        })
    assert_all_ok(procs, outs)


_WATCHDOG_WORKER = r"""
import os, sys, time
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)

sys.path.insert(0, os.environ["REPO_ROOT"])
import chainermn_tpu
from chainermn_tpu.comm.object_plane import ObjectPlane, JobAbortedError
from chainermn_tpu.resilience.watchdog import start_watchdog

op = ObjectPlane()
wd = start_watchdog(interval_ms=200, timeout_ms=1000)
assert wd is not None
assert op.allgather_obj(proc_id) == [0, 1]  # both alive, hearts beating

if proc_id == 1:
    time.sleep(0.5)
    os._exit(9)  # simulated SIGKILL: no hook, no goodbye

# survivor: the next collective would wait on the dead peer forever
# without the watchdog; with it, the wait must become a bounded abort
t0 = time.monotonic()
try:
    op.allgather_obj("after-death")
    print("WORKER0 COLLECTIVE SUCCEEDED UNEXPECTEDLY", flush=True)
    os._exit(1)
except JobAbortedError as e:
    took = time.monotonic() - t0
    assert took < 60, f"abort took {took:.1f}s - not bounded enough"
    print(f"WORKER0 OK abort after {took:.1f}s: {e}", flush=True)
    os._exit(0)
"""


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_watchdog_converts_dead_peer_into_bounded_abort(tmp_path):
    procs, outs = run_workers(
        _WATCHDOG_WORKER, tmp_path, timeout=150,
        env_extra={"CHAINERMN_TPU_RPC_PROBE_MS": "500"})
    if any("aren't implemented on the CPU backend" in o for o in outs):
        pytest.skip("jaxlib CPU backend lacks cross-process computations")
    assert procs[1].returncode == 9
    assert procs[0].returncode == 0, f"survivor:\n{outs[0][-3000:]}"
    assert "WORKER0 OK" in outs[0]

"""Distributed checkpointer tests (reference: extensions_tests/test_checkpoint.py):
save/restore round-trip, rolling-window GC, consensus election."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu
from chainermn_tpu.extensions import create_multi_node_checkpointer


@pytest.fixture()
def comm():
    return chainermn_tpu.create_communicator("xla")


def _state(v):
    return {"params": {"w": jnp.full((3, 2), float(v))},
            "step": jnp.asarray(v)}


def test_save_load_roundtrip(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(7), iteration=100)
    restored, it = cp.maybe_load(_state(0))
    assert it == 100
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 7.0)
    assert int(restored["step"]) == 7


def test_gc_window(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=3)
    for i in range(6):
        cp.save(_state(i), iteration=i * 10)
    kept = cp._iters_on_disk()
    assert kept == [30, 40, 50]  # only the newest 3 survive


def test_resume_elects_latest(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    restored, it = cp.maybe_load(_state(0))
    assert it == 20
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 2.0)


def test_no_snapshot_returns_none(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    state, it = cp.maybe_load(_state(5))
    assert it is None
    np.testing.assert_allclose(np.asarray(state["params"]["w"]), 5.0)


def test_explicit_iteration_load(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    restored, it = cp.maybe_load(_state(0), iteration=10)
    assert it == 10
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 1.0)


def test_async_roundtrip(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        async_write=True)
    cp.save(_state(7), iteration=100)
    # maybe_load flushes the writer queue before the election
    restored, it = cp.maybe_load(_state(0))
    assert it == 100
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 7.0)
    cp.close()


def test_async_stress_interleaved(comm, tmp_path):
    """SURVEY §5: the remaining host-side concurrency hazard is the
    checkpoint I/O thread — hammer it. Rapid saves racing against
    read-side elections must only ever observe fully published snapshots,
    and the final state must be the last save."""
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=3, async_write=True)
    n = 40
    for i in range(n):
        cp.save(_state(i), iteration=i)
        if i % 7 == 3:
            it = cp.latest_common_iteration()
            assert it == i  # flush-then-elect sees everything queued so far
    restored, it = cp.maybe_load(_state(-1))
    assert it == n - 1
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]),
                               float(n - 1))
    assert int(restored["step"]) == n - 1
    kept = cp._iters_on_disk()
    assert kept == [n - 3, n - 2, n - 1]  # GC window held under stress
    cp.close()
    cp.close()  # idempotent (trainer finalization may fire after a manual close)


def test_async_write_error_surfaces(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        async_write=True)
    cp.save(_state(1), iteration=1)
    cp.flush()
    # break the target directory so the next publish fails
    import shutil

    shutil.rmtree(cp.path)
    cp.save(_state(2), iteration=2)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        cp.flush()


def test_async_write_error_does_not_break_election(comm, tmp_path):
    """A failed write must not make the collective read path raise (that
    would desynchronize ranks mid-allgather) — the election just skips the
    never-published snapshot and warns."""
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        async_write=True)
    cp.save(_state(1), iteration=1)
    cp.flush()
    import shutil

    shutil.rmtree(cp.path)
    cp.save(_state(2), iteration=2)
    with pytest.warns(UserWarning, match="async checkpoint write failed"):
        it = cp.latest_common_iteration()
    assert it is None  # rmtree removed snapshot 1 too; nothing published


def test_multi_node_evaluator_passthrough(comm):
    ev = chainermn_tpu.create_multi_node_evaluator(
        lambda: {"validation/acc": 0.5}, comm
    )
    out = ev()
    assert out == {"validation/acc": 0.5}

"""Distributed checkpointer tests (reference: extensions_tests/test_checkpoint.py):
save/restore round-trip, rolling-window GC, consensus election."""

import os

import numpy as np

import jax
import pytest

import jax.numpy as jnp

import chainermn_tpu
from chainermn_tpu.extensions import create_multi_node_checkpointer


@pytest.fixture()
def comm():
    return chainermn_tpu.create_communicator("xla")


def _state(v):
    return {"params": {"w": jnp.full((3, 2), float(v))},
            "step": jnp.asarray(v)}


def test_save_load_roundtrip(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(7), iteration=100)
    restored, it = cp.maybe_load(_state(0))
    assert it == 100
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 7.0)
    assert int(restored["step"]) == 7


def test_gc_window(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=3)
    for i in range(6):
        cp.save(_state(i), iteration=i * 10)
    kept = cp._iters_on_disk()
    assert kept == [30, 40, 50]  # only the newest 3 survive


def test_resume_elects_latest(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    restored, it = cp.maybe_load(_state(0))
    assert it == 20
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 2.0)


def test_no_snapshot_returns_none(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    state, it = cp.maybe_load(_state(5))
    assert it is None
    np.testing.assert_allclose(np.asarray(state["params"]["w"]), 5.0)


def test_explicit_iteration_load(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    restored, it = cp.maybe_load(_state(0), iteration=10)
    assert it == 10
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 1.0)


def test_async_roundtrip(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        async_write=True)
    cp.save(_state(7), iteration=100)
    # maybe_load flushes the writer queue before the election
    restored, it = cp.maybe_load(_state(0))
    assert it == 100
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 7.0)
    cp.close()


def test_async_stress_interleaved(comm, tmp_path):
    """SURVEY §5: the remaining host-side concurrency hazard is the
    checkpoint I/O thread — hammer it. Rapid saves racing against
    read-side elections must only ever observe fully published snapshots,
    and the final state must be the last save."""
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=3, async_write=True)
    n = 40
    for i in range(n):
        cp.save(_state(i), iteration=i)
        if i % 7 == 3:
            it = cp.latest_common_iteration()
            assert it == i  # flush-then-elect sees everything queued so far
    restored, it = cp.maybe_load(_state(-1))
    assert it == n - 1
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]),
                               float(n - 1))
    assert int(restored["step"]) == n - 1
    kept = cp._iters_on_disk()
    assert kept == [n - 3, n - 2, n - 1]  # GC window held under stress
    cp.close()
    cp.close()  # idempotent (trainer finalization may fire after a manual close)


def test_async_write_error_surfaces(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        async_write=True)
    cp.save(_state(1), iteration=1)
    cp.flush()
    # break the target directory so the next publish fails
    import shutil

    shutil.rmtree(cp.path)
    cp.save(_state(2), iteration=2)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        cp.flush()


def test_async_write_error_does_not_break_election(comm, tmp_path):
    """A failed write must not make the collective read path raise (that
    would desynchronize ranks mid-allgather) — the election just skips the
    never-published snapshot and warns."""
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        async_write=True)
    cp.save(_state(1), iteration=1)
    cp.flush()
    import shutil

    shutil.rmtree(cp.path)
    cp.save(_state(2), iteration=2)
    with pytest.warns(UserWarning, match="async checkpoint write failed"):
        it = cp.latest_common_iteration()
    assert it is None  # rmtree removed snapshot 1 too; nothing published


def test_multi_node_evaluator_passthrough(comm):
    ev = chainermn_tpu.create_multi_node_evaluator(
        lambda: {"validation/acc": 0.5}, comm
    )
    out = ev()
    assert out == {"validation/acc": 0.5}


def test_trainer_snapshot_and_resume(comm, tmp_path):
    """End-to-end restart-based recovery: train 8 iterations snapshotting
    each; separately train 4, 'crash', resume from the snapshot with a
    fresh Trainer, continue to 8 — final params must match exactly
    (deterministic data: no shuffle, full-batch)."""
    import optax

    import chainermn_tpu
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.models import MLP
    from chainermn_tpu.training import StandardUpdater, Trainer
    from chainermn_tpu.training.step import make_data_parallel_train_step

    n = comm.size
    rng = np.random.RandomState(0)
    data = [(rng.rand(28, 28).astype(np.float32),
             np.int32(rng.randint(0, 4))) for _ in range(2 * n)]
    model = MLP(n_units=8, n_out=4)
    opt = chainermn_tpu.create_multi_node_optimizer(optax.adam(1e-2), comm)

    def build(state=None):
        if state is None:
            params = model.init(
                jax.random.PRNGKey(0),
                np.zeros((2, 28, 28), np.float32))["params"]
            params = comm.bcast_data(params)
            state = (params, opt.init(params))
        step = make_data_parallel_train_step(model, opt, comm)
        it = SerialIterator(data, 2 * n, shuffle=False, repeat=True)
        return StandardUpdater(it, step, state, comm)

    def leaves(state):
        return [np.asarray(l) for l in jax.tree_util.tree_leaves(state[0])]

    # uninterrupted reference run: 8 iterations
    ref = build()
    Trainer(ref, stop_trigger=(8, "iteration"),
            out=str(tmp_path / "o1")).run()

    # interrupted run: 4 iterations with per-iteration snapshots
    up = build()
    cp = create_multi_node_checkpointer("job", comm,
                                        path=str(tmp_path / "snap"))
    tr = Trainer(up, stop_trigger=(4, "iteration"),
                 out=str(tmp_path / "o2"))
    tr.extend(cp, trigger=(1, "iteration"))
    tr.run()
    del up, tr  # "crash"

    # fresh process-equivalent: rebuild everything, resume, continue
    up2 = build()
    cp2 = create_multi_node_checkpointer("job", comm,
                                         path=str(tmp_path / "snap"))
    it_resumed = cp2.resume(up2)
    assert it_resumed == 4
    Trainer(up2, stop_trigger=(8, "iteration"),
            out=str(tmp_path / "o3")).run()

    for a, b in zip(leaves(ref.state), leaves(up2.state)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_resume_fast_forwards_epoch(comm, tmp_path):
    """Epoch-based stop triggers must not re-run completed epochs after
    resume: the iterator's epoch counter is restored from the iteration."""
    import optax

    import chainermn_tpu
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.models import MLP
    from chainermn_tpu.training import StandardUpdater
    from chainermn_tpu.training.step import make_data_parallel_train_step

    n = comm.size
    rng = np.random.RandomState(0)
    data = [(rng.rand(28, 28).astype(np.float32), np.int32(0))
            for _ in range(2 * n)]
    model = MLP(n_units=8, n_out=4)
    opt = chainermn_tpu.create_multi_node_optimizer(optax.adam(1e-2), comm)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    state = (comm.bcast_data(params), opt.init(params))
    step = make_data_parallel_train_step(model, opt, comm)
    # batch == dataset: one iteration per epoch
    up = StandardUpdater(SerialIterator(data, 2 * n, shuffle=False),
                         step, state, comm)
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(up.state, iteration=3)

    up2 = StandardUpdater(SerialIterator(data, 2 * n, shuffle=False),
                          step, state, comm)
    assert cp.resume(up2) == 3
    assert up2.iteration == 3
    assert up2.epoch == 3  # 3 iterations x full-dataset batches


@pytest.mark.parametrize("async_write", [False, True])
def test_orbax_backend_round_trip(comm, tmp_path, async_write):
    """backend='orbax' (tensorstore/zarr directories): save/elect/restore
    round-trip, GC of directory snapshots, resume interop."""
    pytest.importorskip("orbax.checkpoint")
    cp = create_multi_node_checkpointer(
        "job", comm, path=str(tmp_path), cp_interval=2,
        async_write=async_write, backend="orbax")
    state = {"w": jnp.arange(8.0).reshape(2, 4), "n": jnp.int32(7)}
    for it in range(1, 5):
        cp.save(jax.tree_util.tree_map(lambda a: a + it, state), it)
    cp.flush()
    assert cp.latest_common_iteration() == 4
    restored, it = cp.maybe_load(state)
    assert it == 4
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(8.0).reshape(2, 4) + 4)
    assert int(restored["n"]) == 11
    # GC kept only the rolling window of directory snapshots
    kept = sorted(cp._iters_on_disk())
    assert kept == [3, 4]

# the <2-minute parity battery (see pyproject.toml markers)
pytestmark = pytest.mark.quick

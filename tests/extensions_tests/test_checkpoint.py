"""Distributed checkpointer tests (reference: extensions_tests/test_checkpoint.py):
save/restore round-trip, rolling-window GC, consensus election."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu
from chainermn_tpu.extensions import create_multi_node_checkpointer


@pytest.fixture()
def comm():
    return chainermn_tpu.create_communicator("xla")


def _state(v):
    return {"params": {"w": jnp.full((3, 2), float(v))},
            "step": jnp.asarray(v)}


def test_save_load_roundtrip(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(7), iteration=100)
    restored, it = cp.maybe_load(_state(0))
    assert it == 100
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 7.0)
    assert int(restored["step"]) == 7


def test_gc_window(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=3)
    for i in range(6):
        cp.save(_state(i), iteration=i * 10)
    kept = cp._iters_on_disk()
    assert kept == [30, 40, 50]  # only the newest 3 survive


def test_resume_elects_latest(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    restored, it = cp.maybe_load(_state(0))
    assert it == 20
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 2.0)


def test_no_snapshot_returns_none(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    state, it = cp.maybe_load(_state(5))
    assert it is None
    np.testing.assert_allclose(np.asarray(state["params"]["w"]), 5.0)


def test_explicit_iteration_load(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path))
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    restored, it = cp.maybe_load(_state(0), iteration=10)
    assert it == 10
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 1.0)


def test_multi_node_evaluator_passthrough(comm):
    ev = chainermn_tpu.create_multi_node_evaluator(
        lambda: {"validation/acc": 0.5}, comm
    )
    out = ev()
    assert out == {"validation/acc": 0.5}

"""Sharded-state checkpointing (VERDICT r1 #6).

FSDP/ZeRO states are device-sharded; the round-1 checkpointer pulled every
leaf to host as a GLOBAL array (an OOM at real scale, and impossible
multi-process where the leaf is not fully addressable). Now sharded leaves
are saved as per-addressable-shard arrays and restored onto the template's
sharding with make_array_from_single_device_arrays — no process ever holds
a global leaf on the host. Proven here single-process (shard keys on disk,
bitwise round-trip, training continues) and with two REAL processes whose
snapshot files each contain only that process's half.
"""

import os
import re
import sys

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.models import MLP
from chainermn_tpu.optimizers import make_fsdp_train_step

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mp_harness import assert_all_ok, run_workers


@pytest.fixture(scope="module")
def comm():
    return chainermn_tpu.create_communicator("xla")


def _fsdp_state(comm):
    model = MLP(n_units=8 * comm.size, n_out=4)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    step, state = make_fsdp_train_step(model, optax.adam(1e-3), comm,
                                       params, donate=False)
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    x = jax.device_put(
        np.random.RandomState(0).rand(comm.size * 2, 28, 28)
        .astype(np.float32), dsh)
    y = jax.device_put(np.random.RandomState(1).randint(
        0, 4, size=comm.size * 2).astype(np.int32), dsh)
    return step, state, x, y


def test_fsdp_roundtrip_shard_files(comm, tmp_path):
    step, state, x, y = _fsdp_state(comm)
    state, m = step(state, x, y)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "fsdp", comm, path=str(tmp_path))
    ck.save(state, iteration=1)

    # the snapshot stores per-shard arrays for sharded leaves — never the
    # global array
    fn = os.path.join(str(tmp_path), "fsdp", "snapshot_iter_1.0")
    with np.load(fn, allow_pickle=False) as z:
        keys = set(z.files)
        shard_keys = [k for k in keys if "_s0" in k]
        assert shard_keys, keys
        for k in keys:
            if "_nshards" in k or "_gshape" in k:
                continue
            if "_s" in k:
                i = k.split("_s")[0]
                n = int(z[i + "_nshards"])
                gshape = tuple(z[i + "_gshape"])
                # each shard is 1/n of the global leaf
                assert z[k].size * n == int(np.prod(gshape, initial=1)), k

    # restore into a template with the same shardings: bitwise equal
    template = jax.tree_util.tree_map(jnp.zeros_like, state)
    restored, it = ck.maybe_load(template)
    assert it == 1
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        restored, state)
    # restored shardings match (training continues on the same step)
    jax.tree_util.tree_map(
        lambda a, b: None if a.sharding == b.sharding else
        pytest.fail(f"sharding changed: {a.sharding} vs {b.sharding}"),
        restored, state)
    state2, m = step(restored, x, y)
    assert np.isfinite(float(m["main/loss"]))


def test_resharding_restore_8_to_4(comm, tmp_path):
    """VERDICT r2 #5: FSDP state saved on the 8-device mesh restores onto
    a 4-device mesh — template shard indices don't match the saved ones,
    so the splicing path assembles each target range from the saved index
    manifests. Values bitwise-equal, training continues on the new mesh."""
    from jax.sharding import Mesh
    from chainermn_tpu.comm.xla import XlaCommunicator

    if comm.size < 8:
        pytest.skip("needs 8 devices")
    step8, state8, x, y = _fsdp_state(comm)
    state8, _ = step8(state8, x, y)
    ck8 = chainermn_tpu.create_multi_node_checkpointer(
        "reshard", comm, path=str(tmp_path))
    ck8.save(state8, iteration=5)

    comm4 = XlaCommunicator(
        mesh=Mesh(np.asarray(jax.devices()[:4]), ("r4",)))
    # SAME model as _fsdp_state (its n_units depend on comm.size=8)
    model = MLP(n_units=8 * comm.size, n_out=4)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    step4, template4 = make_fsdp_train_step(
        model, optax.adam(1e-3), comm4, params, donate=False)
    # same model: global leaf shapes agree, only SHARD indices differ
    jax.tree_util.tree_map(
        lambda a, b: (a.shape, a.dtype) == (b.shape, b.dtype)
        or pytest.fail(f"{a.shape} vs {b.shape}"), template4, state8)

    ck4 = chainermn_tpu.create_multi_node_checkpointer(
        "reshard", comm4, path=str(tmp_path))
    restored, it = ck4.maybe_load(
        jax.tree_util.tree_map(jnp.zeros_like, template4))
    assert it == 5
    # bitwise: the spliced 4-device global equals the 8-device global
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        restored, state8)
    # and every restored leaf actually lives on the 4-device sharding
    for leaf in jax.tree_util.tree_leaves(restored):
        if hasattr(leaf, "sharding"):
            assert set(leaf.sharding.device_set) == set(jax.devices()[:4])
    # training continues on the new mesh
    dsh4 = NamedSharding(comm4.mesh, P("r4"))
    x4 = jax.device_put(np.asarray(x)[:8], dsh4)
    y4 = jax.device_put(np.asarray(y)[:8], dsh4)
    state4, m = step4(restored, x4, y4)
    assert np.isfinite(float(m["main/loss"]))


def test_reshard_wrong_model_still_raises(comm, tmp_path):
    """A genuinely different model (different global length) is NOT a
    resharding and must still fail loudly."""
    step, state, x, y = _fsdp_state(comm)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "wrongmodel", comm, path=str(tmp_path))
    ck.save(state, iteration=2)
    model2 = MLP(n_units=8 * comm.size + 8, n_out=4)
    params2 = model2.init(jax.random.PRNGKey(0),
                          np.zeros((2, 28, 28), np.float32))["params"]
    _, template2 = make_fsdp_train_step(
        model2, optax.adam(1e-3), comm, params2, donate=False)
    with pytest.raises(ValueError, match="different model|not a"):
        ck.maybe_load(jax.tree_util.tree_map(jnp.zeros_like, template2))


def test_sharded_snapshot_into_replicated_template(comm, tmp_path):
    """Sharded-saved leaves restore into a REPLICATED template too
    (sharded→replicated resharding): the caller asks for the whole leaf
    everywhere, so the global array is assembled from the pieces."""
    step, state, x, y = _fsdp_state(comm)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "fsdp2", comm, path=str(tmp_path))
    ck.save(state, iteration=3)
    repl_template = jax.tree_util.tree_map(
        lambda l: np.zeros(l.shape, l.dtype), state)
    restored, it = ck.maybe_load(repl_template)
    assert it == 3
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), restored, state)


_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import chainermn_tpu

comm = chainermn_tpu.create_communicator("xla")
mesh = comm.mesh  # (dcn, ici) over 2 processes x 1 device

# a ZeRO-style state: one leaf sharded over processes, one replicated
G = 64
sh = NamedSharding(mesh, P(("dcn", "ici")))
rep = NamedSharding(mesh, P())
full = np.arange(G, dtype=np.float32) * (1 + 0.5)
local = full[proc_id * (G // 2):(proc_id + 1) * (G // 2)]
sharded_leaf = jax.make_array_from_process_local_data(sh, local)
repl_leaf = jax.device_put(np.ones((3,), np.float32), rep)
state = {"w": sharded_leaf, "b": repl_leaf}

out = os.path.join(os.environ["SANDBOX"], "ckpt")
ck = chainermn_tpu.create_multi_node_checkpointer("zero", comm, path=out)
ck.save(state, iteration=7)
ck.flush()

# this process's snapshot holds ONLY its half of the sharded leaf
fn = os.path.join(out, "zero", f"snapshot_iter_7.{proc_id}")
with np.load(fn, allow_pickle=False) as z:
    wkey = [k for k in z.files if k.endswith("_s0") and "gshape" not in k]
    assert len(wkey) == 1, z.files
    shard = z[wkey[0]]
    assert shard.shape == (G // 2,), shard.shape        # half, not global
    np.testing.assert_array_equal(shard, local)
    total_bytes = sum(z[k].nbytes for k in z.files)
    assert total_bytes < full.nbytes + 100, total_bytes  # < global leaf

template = {"w": jax.make_array_from_process_local_data(
    sh, np.zeros_like(local)), "b": jax.device_put(
    np.zeros((3,), np.float32), rep)}
restored, it = ck.maybe_load(template)
assert it == 7
np.testing.assert_array_equal(
    np.asarray(restored["w"].addressable_shards[0].data), local)
np.testing.assert_array_equal(np.asarray(restored["b"]), np.ones(3))

# the restored array is globally consistent: the processes' local halves
# concatenate to the original full leaf
halves = comm.allgather_obj(
    np.asarray(restored["w"].addressable_shards[0].data))
np.testing.assert_array_equal(np.concatenate(halves), full)

print(f"WORKER{proc_id} OK", flush=True)
"""


@pytest.mark.timeout(150)
def test_two_process_sharded_checkpoint(tmp_path):
    procs, outs = run_workers(
        _WORKER, tmp_path, timeout=140,
        env_extra={"SANDBOX": str(tmp_path)})
    assert_all_ok(procs, outs)


def test_partial_replication_dedups_shards(comm, tmp_path):
    # P('fsdp') leaf on an (fsdp, tp) mesh: replica shards must be saved
    # once and fanned back out on restore
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("fsdp", "tp"))
    sh = NamedSharding(mesh, P("fsdp"))
    full = np.arange(32, dtype=np.float32).reshape(8, 4)
    leaf = jax.device_put(full, sh)
    assert len(leaf.addressable_shards) == 8  # 4 unique x 2 replicas

    ck = chainermn_tpu.create_multi_node_checkpointer(
        "partial", comm, path=str(tmp_path))
    ck.save({"w": leaf}, iteration=2)
    fn = os.path.join(str(tmp_path), "partial", "snapshot_iter_2.0")
    with np.load(fn, allow_pickle=False) as z:
        assert int(z["leaf_0_nshards"]) == 4  # deduplicated
        total = sum(z[k].nbytes for k in z.files
                    if re.match(r"leaf_0_s\d+$", k))
        assert total == full.nbytes  # unique data only, no 2x blowup

    template = {"w": jax.device_put(np.zeros_like(full), sh)}
    restored, it = ck.maybe_load(template)
    assert it == 2
    np.testing.assert_array_equal(np.asarray(restored["w"]), full)
    assert restored["w"].sharding == sh
    # every replica device got its copy back
    assert len(restored["w"].addressable_shards) == 8


_SAVE_ONLY_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
import chainermn_tpu

comm = chainermn_tpu.create_communicator("xla")
G = 64
sh = NamedSharding(comm.mesh, P(("dcn", "ici")))
full = np.arange(G, dtype=np.float32) * 1.5   # keep in sync with the
local = full[proc_id * (G // 2):(proc_id + 1) * (G // 2)]  # main test
state = {"w": jax.make_array_from_process_local_data(sh, local),
         "b": jax.device_put(np.ones((3,), np.float32),
                             NamedSharding(comm.mesh, P()))}
out = os.path.join(os.environ["SANDBOX"], "ckpt")
ck = chainermn_tpu.create_multi_node_checkpointer("x2p", comm, path=out)
ck.save(state, iteration=9)
ck.flush()
print(f"WORKER{proc_id} OK", flush=True)
"""


@pytest.mark.timeout(150)
def test_two_process_save_single_process_reshard(tmp_path):
    """The headline cross-process resharding: a 2-process run writes two
    per-rank snapshot files; a SINGLE-process run over 8 devices restores
    them — the restoring run's inter_size gives no hint that file .1
    exists (peer files are discovered by glob), and neither file alone
    covers the 8-way template shards."""
    procs, outs = run_workers(
        _SAVE_ONLY_WORKER, tmp_path, timeout=140,
        env_extra={"SANDBOX": str(tmp_path)})
    assert_all_ok(procs, outs)

    G = 64
    full = np.arange(G, dtype=np.float32) * 1.5
    comm = chainermn_tpu.create_communicator("xla")  # 1 process, 8 devs
    sh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    template = {"w": jax.device_put(jnp.zeros((G,), jnp.float32), sh),
                "b": jnp.ones((3,), jnp.float32)}
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "x2p", comm, path=str(tmp_path / "ckpt"))
    restored, it = ck.maybe_load(template)
    assert it == 9
    np.testing.assert_array_equal(np.asarray(restored["w"]), full)
    assert len(restored["w"].sharding.device_set) == comm.size


def test_zero1_flat_state_reshards_8_to_4(comm, tmp_path):
    """ZeRO-1's flat [padded] vector (pad quantum device-count
    independent) saved on 8 devices restores onto 4 — optimizer m/v
    shards splice along with the params."""
    from jax.sharding import Mesh
    from chainermn_tpu.comm.xla import XlaCommunicator
    from chainermn_tpu.optimizers import make_zero1_train_step

    if comm.size < 8:
        pytest.skip("needs 8 devices")
    model = MLP(n_units=16, n_out=4)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    step8, state8 = make_zero1_train_step(
        model, optax.adam(1e-3), comm, params, donate=False)
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    x = jax.device_put(np.random.RandomState(0).rand(16, 28, 28)
                       .astype(np.float32), dsh)
    y = jax.device_put(np.random.RandomState(1).randint(
        0, 4, size=16).astype(np.int32), dsh)
    state8, _ = step8(state8, x, y)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "zero1rs", comm, path=str(tmp_path))
    ck.save(state8, iteration=4)

    comm4 = XlaCommunicator(
        mesh=Mesh(np.asarray(jax.devices()[:4]), ("z4",)))
    step4, template4 = make_zero1_train_step(
        model, optax.adam(1e-3), comm4, params, donate=False)
    ck4 = chainermn_tpu.create_multi_node_checkpointer(
        "zero1rs", comm4, path=str(tmp_path))
    restored, it = ck4.maybe_load(
        jax.tree_util.tree_map(jnp.zeros_like, template4))
    assert it == 4
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), restored, state8)
    dsh4 = NamedSharding(comm4.mesh, P("z4"))
    x4 = jax.device_put(np.asarray(x)[:8], dsh4)
    y4 = jax.device_put(np.asarray(y)[:8], dsh4)
    _, m = step4(restored, x4, y4)
    assert np.isfinite(float(m["main/loss"]))


def test_zero1_bucketed_state_reshards_8_to_4(comm, tmp_path):
    """The bucketed ZeRO-1 state is a tuple of independently sharded
    bucket vectors, each with a device-count-independent global layout —
    so 8-device snapshots restore bitwise onto 4 devices per bucket
    leaf, exactly like the flat vector."""
    from jax.sharding import Mesh
    from chainermn_tpu.comm.xla import XlaCommunicator
    from chainermn_tpu.optimizers import make_zero1_train_step

    if comm.size < 8:
        pytest.skip("needs 8 devices")
    bb = 32 * 1024
    model = MLP(n_units=16, n_out=4)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    step8, state8 = make_zero1_train_step(
        model, optax.adam(1e-3), comm, params, donate=False,
        bucket_bytes=bb)
    assert len(state8[0]) > 1, "config must exercise multiple buckets"
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    x = jax.device_put(np.random.RandomState(0).rand(16, 28, 28)
                       .astype(np.float32), dsh)
    y = jax.device_put(np.random.RandomState(1).randint(
        0, 4, size=16).astype(np.int32), dsh)
    state8, _ = step8(state8, x, y)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "zero1brs", comm, path=str(tmp_path))
    ck.save(state8, iteration=4)

    comm4 = XlaCommunicator(
        mesh=Mesh(np.asarray(jax.devices()[:4]), ("z4",)))
    step4, template4 = make_zero1_train_step(
        model, optax.adam(1e-3), comm4, params, donate=False,
        bucket_bytes=bb)
    ck4 = chainermn_tpu.create_multi_node_checkpointer(
        "zero1brs", comm4, path=str(tmp_path))
    restored, it = ck4.maybe_load(
        jax.tree_util.tree_map(jnp.zeros_like, template4))
    assert it == 4
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), restored, state8)
    dsh4 = NamedSharding(comm4.mesh, P("z4"))
    x4 = jax.device_put(np.asarray(x)[:8], dsh4)
    y4 = jax.device_put(np.asarray(y)[:8], dsh4)
    _, m = step4(restored, x4, y4)
    assert np.isfinite(float(m["main/loss"]))


def test_orbax_backend_resharding_8_to_4(comm, tmp_path):
    """The orbax backend reshards too: the splice path operates on the
    restored key dict the same way as npz (verified bitwise here so a
    backend change cannot silently regress it)."""
    pytest.importorskip("orbax.checkpoint")
    if comm.size < 8:
        pytest.skip("needs 8 devices")
    from jax.sharding import Mesh
    from chainermn_tpu.comm.xla import XlaCommunicator

    model = MLP(n_units=16, n_out=4)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, 28, 28), np.float32))["params"]
    _, state8 = make_fsdp_train_step(
        model, optax.adam(1e-3), comm, params, donate=False)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "obrs", comm, path=str(tmp_path), backend="orbax")
    ck.save(state8, iteration=2)
    ck.flush()

    comm4 = XlaCommunicator(
        mesh=Mesh(np.asarray(jax.devices()[:4]), ("r4",)))
    _, tmpl4 = make_fsdp_train_step(
        model, optax.adam(1e-3), comm4, params, donate=False)
    ck4 = chainermn_tpu.create_multi_node_checkpointer(
        "obrs", comm4, path=str(tmp_path), backend="orbax")
    restored, it = ck4.maybe_load(
        jax.tree_util.tree_map(jnp.zeros_like, tmpl4))
    assert it == 2
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), restored, state8)


_SCALEUP_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=3,
    process_id=proc_id)
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
import chainermn_tpu

comm = chainermn_tpu.create_communicator("xla")
G = 60
full = np.arange(G, dtype=np.float32) * 1.5  # matches the writer fixture
sh = NamedSharding(comm.mesh, P(("dcn", "ici")))
local = full[proc_id * (G // 3):(proc_id + 1) * (G // 3)]
out = os.path.join(os.environ["SANDBOX"], "ckpt")
ck = chainermn_tpu.create_multi_node_checkpointer("x2p3", comm, path=out)
template = {"w": jax.make_array_from_process_local_data(
    sh, np.zeros_like(local)),
    "b": jax.device_put(np.zeros((3,), np.float32),
                        NamedSharding(comm.mesh, P()))}
restored, it = ck.maybe_load(template)
assert it == 11, it
np.testing.assert_array_equal(
    np.asarray(restored["w"].addressable_shards[0].data), local)
np.testing.assert_array_equal(np.asarray(restored["b"]), np.ones(3))
print(f"WORKER{proc_id} OK", flush=True)
"""

_SCALEUP_SAVER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id)
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
import chainermn_tpu

comm = chainermn_tpu.create_communicator("xla")
G = 60
full = np.arange(G, dtype=np.float32) * 1.5
sh = NamedSharding(comm.mesh, P(("dcn", "ici")))
local = full[proc_id * (G // 2):(proc_id + 1) * (G // 2)]
state = {"w": jax.make_array_from_process_local_data(sh, local),
         "b": jax.device_put(np.ones((3,), np.float32),
                             NamedSharding(comm.mesh, P()))}
out = os.path.join(os.environ["SANDBOX"], "ckpt")
ck = chainermn_tpu.create_multi_node_checkpointer("x2p3", comm, path=out)
ck.save(state, iteration=11)
ck.flush()
print(f"WORKER{proc_id} OK", flush=True)
"""


@pytest.mark.timeout(300)
def test_scale_up_2_to_3_processes(tmp_path):
    """Restoring onto MORE processes than saved: process 2 has no own
    snapshot file — the glob-based completeness election still elects
    iteration 11 and every leaf loads from the peers' files."""
    procs, outs = run_workers(
        _SCALEUP_SAVER, tmp_path, timeout=140,
        env_extra={"SANDBOX": str(tmp_path)})
    assert_all_ok(procs, outs)
    procs, outs = run_workers(
        _SCALEUP_WORKER, tmp_path, n=3, timeout=140,
        env_extra={"SANDBOX": str(tmp_path)})
    assert_all_ok(procs, outs)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_reshard_fuzz_random_layouts(comm, tmp_path, seed):
    """Property check for the splicing restore: random global shapes and
    random save/restore partitionings (different axes, different device
    counts, partial replication) must round-trip bitwise."""
    from jax.sharding import Mesh
    from chainermn_tpu.comm.xla import XlaCommunicator

    if comm.size < 8:
        pytest.skip("needs 8 devices")
    rs = np.random.RandomState(seed)

    def random_comm():
        n = int(rs.choice([2, 4, 8]))
        if rs.rand() < 0.5 or n == 2:
            mesh = Mesh(np.asarray(jax.devices()[:n]), (f"a{n}",))
        else:
            mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(2, n // 2),
                        (f"x{n}", f"y{n}"))
        return XlaCommunicator(mesh=mesh)

    def random_state(c):
        mesh = c.mesh
        leaves = {}
        for k in range(3):
            # dims divisible by 8 so every partitioning is legal
            shape = tuple(int(rs.choice([8, 16, 24]))
                          for _ in range(int(rs.choice([1, 2]))))
            arr = rs.randn(*shape).astype(np.float32)
            names = list(mesh.axis_names)
            # shard dim 0 over a random subset of axes (maybe none)
            ax = tuple(a for a in names if rs.rand() < 0.7)
            spec = P(ax if len(ax) > 1 else (ax[0] if ax else None))
            leaves[f"l{k}"] = jax.device_put(
                jnp.asarray(arr), NamedSharding(mesh, spec))
        return leaves

    save_comm = random_comm()
    state = random_state(save_comm)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        f"fuzz{seed}", save_comm, path=str(tmp_path))
    ck.save(state, iteration=1)

    load_comm = random_comm()
    # template: SAME global shapes, new mesh, fresh random partitioning
    template = {}
    for k, v in state.items():
        names = list(load_comm.mesh.axis_names)
        ax = tuple(a for a in names if rs.rand() < 0.7)
        spec = P(ax if len(ax) > 1 else (ax[0] if ax else None))
        template[k] = jax.device_put(
            jnp.zeros(v.shape, v.dtype),
            NamedSharding(load_comm.mesh, spec))
    ck2 = chainermn_tpu.create_multi_node_checkpointer(
        f"fuzz{seed}", load_comm, path=str(tmp_path))
    restored, it = ck2.maybe_load(template)
    assert it == 1
    for k in state:
        np.testing.assert_array_equal(
            np.asarray(restored[k]), np.asarray(state[k]), err_msg=k)


def test_sharded_leaf_nonarray_template_raises(comm, tmp_path):
    """A non-array template leaf (e.g. a Python float) against a
    sharded-saved leaf must fail with the clear guard, not fall into the
    replicated-splice branch."""
    step, state, x, y = _fsdp_state(comm)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "nonarr", comm, path=str(tmp_path))
    ck.save(state, iteration=1)
    bad = jax.tree_util.tree_map(lambda l: 0.0, state)
    with pytest.raises(ValueError, match="not an array"):
        ck.maybe_load(bad)


def test_lm_fsdp_scan_state_reshards_8_to_4(comm, tmp_path):
    """The flagship scan-FSDP state (stacked blocks + mixed shardings)
    round-trips through the resharding checkpointer: an 8-device
    snapshot restores onto a 4-device mesh (different per-leaf shard
    layouts), training continues, and unstack_lm_blocks recovers the
    per-layer tree — the full big-model workflow loop closed."""
    from jax.sharding import Mesh

    import os as _os
    import sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    from lm_scan_helpers import lm_scan_setup, tiny_lm

    from chainermn_tpu.comm.xla import XlaCommunicator
    from chainermn_tpu.models.transformer import unstack_lm_blocks
    from chainermn_tpu.optimizers import fsdp_gather_params

    if comm.size < 8:
        pytest.skip("needs 8 devices")
    model = tiny_lm()
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 2048, size=(16, 17)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:1, :-1])["params"]

    def build(c):
        return lm_scan_setup(c, model, params, optax.adam(1e-2))

    step8, state8 = build(comm)
    dsh = NamedSharding(comm.mesh, P(comm.axis_names[0]))
    x = jax.device_put(toks[:, :-1], dsh)
    y = jax.device_put(toks[:, 1:], dsh)
    state8, _ = step8(state8, x, y)
    ck = chainermn_tpu.create_multi_node_checkpointer(
        "lmscanrs", comm, path=str(tmp_path))
    ck.save(state8, iteration=6)

    comm4 = XlaCommunicator(
        mesh=Mesh(np.asarray(jax.devices()[:4]), ("z4",)))
    step4, template4 = build(comm4)
    ck4 = chainermn_tpu.create_multi_node_checkpointer(
        "lmscanrs", comm4, path=str(tmp_path))
    restored, it = ck4.maybe_load(
        jax.tree_util.tree_map(jnp.zeros_like, template4))
    assert it == 6
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), restored, state8)
    dsh4 = NamedSharding(comm4.mesh, P("z4"))
    state4, m = step4(restored, jax.device_put(np.asarray(x)[:8], dsh4),
                      jax.device_put(np.asarray(y)[:8], dsh4))
    assert np.isfinite(float(m["main/loss"]))
    # export path from the restored-and-stepped state
    up = unstack_lm_blocks(fsdp_gather_params(state4))
    assert "block_3" in up and up["block_3"]["qkv"]["kernel"].shape[0] == 32

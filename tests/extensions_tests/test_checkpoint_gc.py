"""Consensus-aware GC (ISSUE 4 satellite): the rolling window must
never delete (1) the elected consensus winner, (2) explicitly
protected iterations, or (3) the newest iteration whose own file still
verifies — a GC racing a failed/corrupted save must not strand the
next election with only broken files."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import chainermn_tpu
from chainermn_tpu.extensions import create_multi_node_checkpointer


@pytest.fixture()
def comm():
    return chainermn_tpu.create_communicator("xla")


def _state(v):
    return {"w": jnp.full((2,), float(v))}


def _corrupt(fn):
    """Damage the published file in place, leaving the manifest: the
    SHA check must now reject it."""
    with open(fn, "rb+") as fh:
        fh.seek(0)
        chunk = fh.read(64)
        fh.seek(0)
        fh.write(bytes(b ^ 0xFF for b in chunk))


def test_gc_keeps_elected_winner_outside_window(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=2)
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    assert cp.latest_common_iteration() == 20  # pins 20
    cp.save(_state(3), iteration=30)
    cp.save(_state(4), iteration=40)
    cp.save(_state(5), iteration=50)
    # window is [40, 50]; the elected 20 survives, 10 and 30 are gone
    assert cp._iters_on_disk() == [20, 40, 50]


def test_gc_elected_pin_is_replaced_not_accumulated(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=2)
    for i in range(1, 7):
        cp.save(_state(i), iteration=i * 10)
        assert cp.latest_common_iteration() == i * 10
    # every save was immediately elected, but the pin is a single slot:
    # the window still prunes normally
    assert cp._iters_on_disk() == [50, 60]


def test_gc_protect_pins_permanently(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=1)
    cp.save(_state(1), iteration=10)
    cp.protect(10)
    cp.save(_state(2), iteration=20)
    cp.save(_state(3), iteration=30)
    assert cp._iters_on_disk() == [10, 30]


def test_gc_keeps_newest_valid_when_newer_is_corrupt(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=5)
    cp.save(_state(1), iteration=10)
    cp.save(_state(2), iteration=20)
    _corrupt(os.path.join(cp.path, "snapshot_iter_20.0"))
    # shrink the window so 10 falls outside it, then GC: 10 is the
    # newest iteration that still VERIFIES — it must survive, or the
    # next election would find only the broken 20
    cp.cp_interval = 1
    cp._gc()
    assert cp._iters_on_disk() == [10, 20]
    restored, it = cp.maybe_load(_state(0))
    assert it == 10
    np.testing.assert_allclose(np.asarray(restored["w"]), 1.0)


def test_gc_still_prunes_normally(comm, tmp_path):
    cp = create_multi_node_checkpointer("job", comm, path=str(tmp_path),
                                        cp_interval=3)
    for i in range(6):
        cp.save(_state(i), iteration=i * 10)
    assert cp._iters_on_disk() == [30, 40, 50]

"""The `import chainermn` alias keeps reference scripts' import lines alive.

Exercises the reference's documented "3-line diff" (SURVEY.md §0): create a
communicator, wrap the optimizer, scatter the dataset — all through the
`chainermn` package name — then runs one real data-parallel step.
"""

import numpy as np
import optax
import pytest


def test_top_level_factories_resolve():
    import chainermn
    import chainermn_tpu

    assert chainermn.create_communicator is chainermn_tpu.create_communicator
    assert (chainermn.create_multi_node_optimizer
            is chainermn_tpu.create_multi_node_optimizer)
    assert chainermn.scatter_dataset is chainermn_tpu.scatter_dataset
    assert chainermn.__version__ == chainermn_tpu.__version__


def test_submodule_imports_match_reference_layout():
    # the reference layout's documented module paths (SURVEY.md §1)
    from chainermn.functions import send, recv, pseudo_connect  # noqa: F401
    from chainermn.links import (  # noqa: F401
        MultiNodeBatchNormalization,
        MultiNodeChainList,
    )
    import chainermn.communicators
    import chainermn_tpu.comm

    assert chainermn.communicators is chainermn_tpu.comm
    assert hasattr(chainermn.communicators, "CommunicatorBase")


def test_deep_imports_are_the_same_modules():
    # deep module paths must alias, not re-execute (isinstance must hold
    # across the two spellings)
    from chainermn.communicators.base import CommunicatorBase as C1
    from chainermn_tpu.comm.base import CommunicatorBase as C2

    assert C1 is C2

    import chainermn

    comm = chainermn.create_communicator("naive")
    assert isinstance(comm, C1)

    import chainermn.functions.collective as a
    import chainermn_tpu.functions.collective as b

    assert a is b


def test_three_line_diff_end_to_end():
    import chainermn
    from chainermn_tpu.models import MLP
    from chainermn_tpu.training.step import make_data_parallel_train_step

    comm = chainermn.create_communicator("naive")

    ds = [(np.random.RandomState(i).rand(4).astype(np.float32), i % 3)
          for i in range(32)]
    shard = chainermn.scatter_dataset(ds, comm, shuffle=True, seed=0)
    assert len(shard) == 32  # single process keeps the whole set

    model = MLP(n_units=8, n_out=3)
    opt = chainermn.create_multi_node_optimizer(optax.sgd(0.1), comm)

    import jax

    x = np.stack([s[0] for s in ds[:16]])
    y = np.array([s[1] for s in ds[:16]], np.int32)
    params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
    params = comm.bcast_data(params)
    step = make_data_parallel_train_step(model, opt, comm)
    state = (params, opt.init(params))
    state, metrics = step(state, x, y)
    assert np.isfinite(float(metrics["main/loss"]))


def test_legacy_communicator_names():
    import chainermn

    for name in ("naive", "flat", "pure_nccl", "single_node"):
        comm = chainermn.create_communicator(name)
        assert comm.size >= 1

# the <2-minute parity battery (see pyproject.toml markers)
pytestmark = pytest.mark.quick

"""dlint dataflow-rule fixtures (DL118–DL122, DL125): every rule trips on a
seeded violation and stays quiet on its clean twin — the contract the
catalogue rows in docs/static_analysis.md promise.

Pure-AST tests (no jax import, no devices), plus one module-scoped run
over the real repo roots asserting each dataflow rule is clean on the
code it ships with (the finding-or-clean acceptance check).
"""

import os
import textwrap

import pytest

from chainermn_tpu.analysis import lint_source, run_lint


def _lint(src, rules=None):
    return lint_source(textwrap.dedent(src), "fixture.py", rules=rules)


def _only(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# DL118 — prng-key-reuse
# ---------------------------------------------------------------------------


def test_dl118_flags_straight_line_key_reuse():
    src = """\
    import jax

    def sample(key):
        a = jax.random.normal(key, (4,))
        b = jax.random.uniform(key, (4,))
        return a, b
    """
    fs = _only(_lint(src), "DL118")
    assert len(fs) == 1
    assert fs[0].line == 5
    assert "'key'" in fs[0].message
    assert "docs/static_analysis.md#dl118" in fs[0].message


def test_dl118_flags_reuse_across_loop_iterations():
    src = """\
    import jax

    def sample(key, xs):
        out = []
        for x in xs:
            out.append(jax.random.normal(key, (4,)))
        return out
    """
    fs = _only(_lint(src), "DL118")
    assert len(fs) == 1
    assert fs[0].line == 6


def test_dl118_flags_discarded_split_result():
    src = """\
    import jax

    def advance(key):
        jax.random.split(key)
        return key
    """
    fs = _only(_lint(src), "DL118")
    assert len(fs) == 1
    assert "discarded" in fs[0].message


def test_dl118_flags_reuse_through_a_callee():
    src = """\
    import jax

    def draw(k):
        return jax.random.normal(k, (4,))

    def sample(key):
        a = draw(key)
        b = draw(key)
        return a, b
    """
    fs = _only(_lint(src), "DL118")
    assert len(fs) == 1
    assert fs[0].line == 8


def test_dl118_clean_split_and_rebind():
    src = """\
    import jax

    def sample(key):
        key, sub = jax.random.split(key)
        a = jax.random.normal(sub, (4,))
        key, sub = jax.random.split(key)
        b = jax.random.uniform(sub, (4,))
        return a, b
    """
    assert _only(_lint(src), "DL118") == []


def test_dl118_clean_distinct_split_indices():
    src = """\
    import jax

    def sample(key):
        ks = jax.random.split(key, 3)
        a = jax.random.normal(ks[0], (4,))
        b = jax.random.uniform(ks[1], (4,))
        return a, b
    """
    assert _only(_lint(src), "DL118") == []


def test_dl118_clean_fold_in_per_iteration():
    # the sanctioned loop idiom (training/step.py): fold varying data
    # into one base key, consume only the folded keys
    src = """\
    import jax

    def sample(key, xs):
        out = []
        for i, x in enumerate(xs):
            k = jax.random.fold_in(key, i)
            out.append(jax.random.normal(k, (4,)))
        return out
    """
    assert _only(_lint(src), "DL118") == []


def test_dl118_clean_one_consumer_per_branch_arm():
    src = """\
    import jax

    def sample(key, gumbel):
        if gumbel:
            return jax.random.gumbel(key, (4,))
        return jax.random.normal(key, (4,))
    """
    assert _only(_lint(src), "DL118") == []


# ---------------------------------------------------------------------------
# DL119 — use-after-donation
# ---------------------------------------------------------------------------


def test_dl119_flags_read_after_donating_call():
    src = """\
    import jax

    def _impl(state):
        return state

    step = jax.jit(_impl, donate_argnums=(0,))

    def run(state):
        out = step(state)
        return state + out
    """
    fs = _only(_lint(src), "DL119")
    assert len(fs) == 1
    assert fs[0].line == 10
    assert "'state'" in fs[0].message
    assert "docs/static_analysis.md#dl119" in fs[0].message


def test_dl119_flags_self_attribute_jit_alias():
    src = """\
    import jax

    class Runner:
        def __init__(self, fn):
            self._step = jax.jit(fn, donate_argnums=(0,))

        def run(self, state):
            out = self._step(state)
            return state.sum() + out
    """
    fs = _only(_lint(src), "DL119")
    assert len(fs) == 1
    assert fs[0].line == 9


def test_dl119_flags_donation_through_a_callee():
    src = """\
    import jax

    def _impl(state):
        return state

    step = jax.jit(_impl, donate_argnums=(0,))

    def advance(s):
        return step(s)

    def run(state):
        advance(state)
        return state
    """
    fs = _only(_lint(src), "DL119")
    assert len(fs) == 1
    assert fs[0].line == 13


def test_dl119_clean_rebind_over_input():
    src = """\
    import jax

    def _impl(state):
        return state

    step = jax.jit(_impl, donate_argnums=(0,))

    def run(state):
        state = step(state)
        return state
    """
    assert _only(_lint(src), "DL119") == []


def test_dl119_clean_conditional_donation_stays_opaque():
    # maybe-donated must not flag: the (0,) if donate else () switch is
    # deliberately not resolved
    src = """\
    import jax

    def _impl(state):
        return state

    def make(donate):
        return jax.jit(_impl,
                       donate_argnums=(0,) if donate else ())

    step = make(True)

    def run(state):
        out = step(state)
        return state + out
    """
    assert _only(_lint(src), "DL119") == []


def test_dl119_clean_callee_donates_derived_value_not_param():
    src = """\
    import jax
    import jax.numpy as jnp

    def _impl(state):
        return state

    step = jax.jit(_impl, donate_argnums=(0,))

    def advance(n):
        buf = jnp.zeros((n,))
        return step(buf)

    def run(n):
        advance(n)
        return n
    """
    assert _only(_lint(src), "DL119") == []


# ---------------------------------------------------------------------------
# DL120 — nondeterministic-iteration
# ---------------------------------------------------------------------------


def test_dl120_flags_set_iteration_driving_tagged_sends():
    src = """\
    def fan_out(comm, peers, payload):
        targets = set(peers)
        for p in targets:
            comm.send(payload, dest=p, tag=7)
    """
    fs = _only(_lint(src), "DL120")
    assert len(fs) == 1
    assert fs[0].line == 3
    assert "'targets'" in fs[0].message
    assert "docs/static_analysis.md#dl120" in fs[0].message


def test_dl120_flags_direct_set_call_iteration():
    src = """\
    def fan_out(comm, peers, payload):
        for p in set(peers):
            comm.send(payload, dest=p, tag=7)
    """
    fs = _only(_lint(src), "DL120")
    assert len(fs) == 1
    assert fs[0].line == 2
    assert "set(...)" in fs[0].message


def test_dl120_flags_set_iteration_driving_collectives():
    src = """\
    def sync_all(comm, shards):
        for s in {x.name for x in shards}:
            comm.allreduce(s)
    """
    fs = _only(_lint(src), "DL120")
    assert len(fs) == 1


def test_dl120_flags_signature_tuple_built_from_set():
    src = """\
    def trace_key(shapes):
        seen = set(shapes)
        sig = tuple(seen)
        return sig
    """
    fs = _only(_lint(src), "DL120")
    assert len(fs) == 1
    assert fs[0].line == 3
    assert "'sig'" in fs[0].message


def test_dl120_clean_sorted_set_iteration():
    src = """\
    def fan_out(comm, peers, payload):
        targets = set(peers)
        for p in sorted(targets):
            comm.send(payload, dest=p, tag=7)
    """
    assert _only(_lint(src), "DL120") == []


def test_dl120_clean_set_loop_without_comm():
    src = """\
    def total(peers):
        acc = 0
        for p in set(peers):
            acc += p
        return acc
    """
    assert _only(_lint(src), "DL120") == []


def test_dl120_clean_dict_iteration():
    # dict order is a language guarantee (3.7+) — the repo relies on it
    src = """\
    def fan_out(comm, routes, payload):
        for p in routes:
            comm.send(payload, dest=p, tag=7)
    """
    assert _only(_lint(src), "DL120") == []


# ---------------------------------------------------------------------------
# DL121 — host-sync-in-decode
# ---------------------------------------------------------------------------


def test_dl121_flags_np_asarray_in_decode_root():
    src = """\
    import numpy as np

    def decode_k_step(tokens, logits):
        host = np.asarray(logits)
        return host
    """
    fs = _only(_lint(src), "DL121")
    assert len(fs) == 1
    assert fs[0].line == 4
    assert "np.asarray" in fs[0].message
    assert "docs/static_analysis.md#dl121" in fs[0].message


def test_dl121_flags_host_pull_reached_through_callee():
    src = """\
    def _pull(v):
        return float(v)

    def decode_k_loop(logits):
        return _pull(logits)
    """
    fs = _only(_lint(src), "DL121")
    assert len(fs) == 1
    assert fs[0].line == 2
    assert "reached from decode_k_loop" in fs[0].message


def test_dl121_flags_item_in_serving_step_method():
    src = """\
    class ServingStep:
        def step(self, tokens):
            return tokens.item()
    """
    fs = _only(_lint(src), "DL121")
    assert len(fs) == 1
    assert ".item()" in fs[0].message


def test_dl121_clean_device_resident_decode():
    src = """\
    import jax.numpy as jnp

    def decode_k_step(logits):
        return jnp.argmax(logits, axis=-1)
    """
    assert _only(_lint(src), "DL121") == []


def test_dl121_clean_self_state_pull():
    # sanctioned debug pulls (ServingStep.cursors) read self.cache —
    # self is not a data parameter
    src = """\
    import numpy as np

    class ServingStep:
        def cursors(self):
            return np.asarray(self.cache)
    """
    assert _only(_lint(src), "DL121") == []


def test_dl121_clean_test_functions_are_not_roots():
    src = """\
    import numpy as np

    def test_decode_k_eos_masks(logits):
        return np.asarray(logits)
    """
    assert _only(_lint(src), "DL121") == []


# ---------------------------------------------------------------------------
# DL122 — trace-count-instability
# ---------------------------------------------------------------------------


def test_dl122_flags_if_on_traced_argument():
    src = """\
    import jax

    @jax.jit
    def act(x):
        if x > 0:
            return x
        return -x
    """
    fs = _only(_lint(src), "DL122")
    assert len(fs) == 1
    assert fs[0].line == 5
    assert "'x'" in fs[0].message
    assert "docs/static_analysis.md#dl122" in fs[0].message


def test_dl122_flags_while_in_jit_application_form():
    src = """\
    import jax

    def countdown(x):
        while x > 0:
            x = x - 1
        return x

    stepped = jax.jit(countdown)
    """
    fs = _only(_lint(src), "DL122")
    assert len(fs) == 1
    assert "while" in fs[0].message


def test_dl122_clean_static_argnums():
    src = """\
    import jax
    from functools import partial

    @partial(jax.jit, static_argnums=(0,))
    def scale(n, x):
        if n > 3:
            return x * 2
        return x
    """
    assert _only(_lint(src), "DL122") == []


def test_dl122_clean_shape_branch_is_trace_time():
    src = """\
    import jax

    @jax.jit
    def reduce(x):
        if x.shape[0] > 1:
            return x.sum()
        return x
    """
    assert _only(_lint(src), "DL122") == []


def test_dl122_clean_is_none_dispatch():
    src = """\
    import jax

    @jax.jit
    def apply(x, mask):
        if mask is None:
            return x
        return x * mask
    """
    assert _only(_lint(src), "DL122") == []


def test_dl122_clean_defaulted_capture_param():
    src = """\
    import jax

    @jax.jit
    def act(x, _k=3):
        if _k > 2:
            return x * 2
        return x
    """
    assert _only(_lint(src), "DL122") == []


def test_dl122_clean_uncompiled_function():
    src = """\
    def act(x):
        if x > 0:
            return x
        return -x
    """
    assert _only(_lint(src), "DL122") == []


# ---------------------------------------------------------------------------
# DL125 — draft-target-key-confusion
# ---------------------------------------------------------------------------


def test_dl125_flags_unverified_draft_commit():
    src = """\
    from chainermn_tpu.serving.sampling import draft_shadow_keys, \\
        sample_tokens

    def round(self, req, logits, keys, temps, topks):
        shadow = draft_shadow_keys(keys)
        tok, shadow = sample_tokens(logits, shadow, temps, topks)
        self._emit(req, tok)
    """
    fs = _only(_lint(src), "DL125")
    assert len(fs) == 1
    assert fs[0].line == 7
    assert "'tok'" in fs[0].message
    assert "docs/static_analysis.md#dl125" in fs[0].message


def test_dl125_flags_commit_of_rebound_shadow_sample():
    # the shadow key advanced through sample_tokens stays a shadow key:
    # the SECOND draw is just as unverified as the first
    src = """\
    from chainermn_tpu.serving.sampling import draft_shadow_keys, \\
        sample_tokens

    def round(self, out, logits, keys, temps, topks):
        shadow = draft_shadow_keys(keys)
        d1, shadow = sample_tokens(logits, shadow, temps, topks)
        d2, shadow = sample_tokens(logits, shadow, temps, topks)
        out.append(d2)
    """
    fs = _only(_lint(src), "DL125")
    assert len(fs) == 1
    assert "'d2'" in fs[0].message


def test_dl125_clean_verified_draft_commit():
    src = """\
    from chainermn_tpu.serving.sampling import draft_shadow_keys, \\
        sample_tokens

    def round(self, req, logits, keys, temps, topks):
        shadow = draft_shadow_keys(keys)
        tok, shadow = sample_tokens(logits, shadow, temps, topks)
        ok = self.verify_apply(tok)
        if ok:
            self._emit(req, tok)
    """
    assert _only(_lint(src), "DL125") == []


def test_dl125_clean_real_key_sampling():
    src = """\
    from chainermn_tpu.serving.sampling import sample_tokens

    def round(self, req, logits, keys, temps, topks):
        tok, keys = sample_tokens(logits, keys, temps, topks)
        self._emit(req, tok)
    """
    assert _only(_lint(src), "DL125") == []


def test_dl125_clean_verify_on_one_branch_only_still_flags():
    # blessing must hold on EVERY path reaching the commit — a verify
    # on one branch does not sanctify the other
    src = """\
    from chainermn_tpu.serving.sampling import draft_shadow_keys, \\
        sample_tokens

    def round(self, req, logits, keys, temps, topks, fast):
        shadow = draft_shadow_keys(keys)
        tok, shadow = sample_tokens(logits, shadow, temps, topks)
        if fast:
            pass
        else:
            self.verify_apply(tok)
        self._emit(req, tok)
    """
    fs = _only(_lint(src), "DL125")
    assert len(fs) == 1


# ---------------------------------------------------------------------------
# the repo itself, per rule — the finding-or-clean acceptance check
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_ROOTS = [os.path.join(_REPO, d)
          for d in ("chainermn_tpu", "examples", "tests", "tools")]


@pytest.fixture(scope="module")
def dataflow_repo_run():
    return run_lint(_ROOTS,
                    rules=["DL118", "DL119", "DL120", "DL121", "DL122",
                           "DL125"])


@pytest.mark.parametrize("rule", ["DL118", "DL119", "DL120", "DL121",
                                  "DL122", "DL125"])
def test_repo_is_clean_per_dataflow_rule(dataflow_repo_run, rule):
    fs = _only(dataflow_repo_run.findings, rule)
    assert fs == [], "\n" + "\n".join(f.format() for f in fs)


def test_repo_run_exercised_every_dataflow_pass(dataflow_repo_run):
    # the clean verdict above is only meaningful if the passes ran
    assert {"DL118", "DL119", "DL120", "DL121",
            "DL122", "DL125"} <= set(dataflow_repo_run.timings)
